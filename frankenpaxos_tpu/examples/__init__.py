"""Pedagogical simulated systems (the analog of the reference's
``shared/src/test/scala/bankaccount`` and ``diehard`` examples): tiny
state machines demonstrating how the property-testing simulator explores
state spaces — and, for Die Hard, that it can *find* target states via
invariant violations, exactly like Lamport's TLA+ water-jug example.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from frankenpaxos_tpu.sim import SimulatedSystem


# -- Bank account (BankAccount.scala) ---------------------------------------


class BankAccount:
    """Deposits and guarded withdrawals; the balance must never go
    negative."""

    def __init__(self) -> None:
        self.balance = 0

    def deposit(self, amount: int) -> None:
        self.balance += amount

    def withdraw(self, amount: int) -> None:
        if self.balance - amount < 0:
            return
        self.balance -= amount


@dataclasses.dataclass(frozen=True)
class Deposit:
    amount: int


@dataclasses.dataclass(frozen=True)
class Withdraw:
    amount: int


class SimulatedBankAccount(SimulatedSystem):
    """State = the balance; invariant: never negative
    (BankAccountTest.scala: "A bank account should always be positive")."""

    def new_system(self, seed: int) -> BankAccount:
        return BankAccount()

    def get_state(self, system: BankAccount) -> int:
        return system.balance

    def generate_command(self, system: BankAccount, rng: random.Random):
        if rng.random() < 0.5:
            return Deposit(rng.randrange(0, 101))
        return Withdraw(rng.randrange(0, 101))

    def run_command(self, system: BankAccount, command) -> BankAccount:
        if isinstance(command, Deposit):
            system.deposit(command.amount)
        else:
            system.withdraw(command.amount)
        return system

    def state_invariant(self, state: int) -> Optional[str]:
        if state < 0:
            return f"balance went negative: {state}"
        return None


class BuggyBankAccount(BankAccount):
    """An unguarded withdraw — the simulator must catch the overdraft."""

    def withdraw(self, amount: int) -> None:
        self.balance -= amount


class SimulatedBuggyBankAccount(SimulatedBankAccount):
    def new_system(self, seed: int) -> BankAccount:
        return BuggyBankAccount()


# -- Die Hard (DieHard.scala / Lamport's TLA+ course) -----------------------


class DieHard:
    """The 3- and 5-gallon jug puzzle: measure exactly 4 gallons."""

    def __init__(self) -> None:
        self.small = 0  # 3-gallon jug
        self.big = 0  # 5-gallon jug

    def fill_small(self) -> None:
        self.small = 3

    def fill_big(self) -> None:
        self.big = 5

    def empty_small(self) -> None:
        self.small = 0

    def empty_big(self) -> None:
        self.big = 0

    def small_to_big(self) -> None:
        poured = min(self.small, 5 - self.big)
        self.small -= poured
        self.big += poured

    def big_to_small(self) -> None:
        poured = min(self.big, 3 - self.small)
        self.big -= poured
        self.small += poured


DIE_HARD_COMMANDS = (
    "fill_small",
    "fill_big",
    "empty_small",
    "empty_big",
    "small_to_big",
    "big_to_small",
)


class SimulatedDieHard(SimulatedSystem):
    """State = (small, big). The "invariant" big != 4 is deliberately
    falsifiable: a violating history IS a solution to the puzzle, showing
    the simulator finds states, not just checks them."""

    def new_system(self, seed: int) -> DieHard:
        return DieHard()

    def get_state(self, system: DieHard):
        return (system.small, system.big)

    def generate_command(self, system: DieHard, rng: random.Random) -> str:
        return rng.choice(DIE_HARD_COMMANDS)

    def run_command(self, system: DieHard, command: str) -> DieHard:
        getattr(system, command)()
        return system

    def state_invariant(self, state) -> Optional[str]:
        small, big = state
        if big == 4:
            return f"big jug holds exactly 4 gallons (small={small})"
        if not (0 <= small <= 3 and 0 <= big <= 5):
            return f"jug over/underflow: {state}"
        return None
