"""Interactive step-through simulation driver — the capability analog of
the reference's browser visualizations (``js/``: Vue + snap.svg apps over
``JsTransport``), reimagined as a terminal/notebook tool.

A :class:`Stepper` wraps any cluster built on a :class:`SimTransport` and
exposes what the browser UI exposed (JsTransport.scala:175-298):

  * inspect pending messages (decoded) and running timers;
  * deliver / drop / duplicate any message, fire any timer;
  * partition and unpartition actors;
  * inspect live actor state;
  * export the session's command history as a runnable regression test
    (the analog of ``JsTransport.commandToUnitTest``).

Use it interactively (``python -m frankenpaxos_tpu.viz.repl``), from a
notebook, or programmatically in tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from frankenpaxos_tpu.core import SimTransport, wire
from frankenpaxos_tpu.core.sim_transport import (
    DeliverMessage,
    DropMessage,
    DuplicateMessage,
    PartitionActor,
    TriggerTimer,
    UnpartitionActor,
)


class Stepper:
    def __init__(self, transport: SimTransport):
        self.transport = transport

    # -- Inspection ----------------------------------------------------------

    def messages(self) -> List[str]:
        """Numbered, decoded pending messages."""
        out = []
        for i, m in enumerate(self.transport.messages):
            try:
                decoded = wire.decode(m.data)
            except Exception:  # noqa: BLE001 — raw transports
                decoded = m.data
            out.append(f"[{i}] {m.src} -> {m.dst}: {decoded!r}")
        return out

    def timers(self) -> List[str]:
        return [
            f"[{i}] {t.address}: {t.name()}"
            for i, t in enumerate(self.transport.running_timers())
        ]

    def actors(self) -> List[str]:
        return sorted(str(a) for a in self.transport.actors)

    def state(self, address) -> Dict[str, Any]:
        """A live actor's public state (the @JSExport fields analog)."""
        actor = self._resolve_actor(address)
        return {
            k: v
            for k, v in vars(actor).items()
            if not k.startswith("_")
            and k not in ("transport", "logger", "serializer")
        }

    def _resolve_actor(self, address):
        for a, actor in self.transport.actors.items():
            if a == address or str(a) == str(address):
                return actor
        raise KeyError(f"no actor at {address!r}; actors: {self.actors()}")

    # -- Stepping ------------------------------------------------------------

    def deliver(self, i: int) -> None:
        self.transport.deliver_message(self.transport.messages[i])

    def drop(self, i: int) -> None:
        self.transport.drop_message(self.transport.messages[i])

    def duplicate(self, i: int) -> None:
        self.transport.duplicate_message(self.transport.messages[i])

    def occurrence_of(self, i: int) -> int:
        """Occurrence ordinal of the i-th running timer (see
        SimTransport.timer_occurrence, the single source of truth)."""
        return self.transport.timer_occurrence(i)

    def fire(self, i: int) -> None:
        # The i-th running timer may share (address, name) with earlier
        # ones; fire THAT instance, not the first name match.
        timer = self.transport.running_timers()[i]
        self.transport.trigger_timer(
            timer.address, timer.name(), occurrence=self.occurrence_of(i)
        )

    def partition(self, address) -> None:
        self.transport.partition_actor(self._resolve_actor(address).address)

    def unpartition(self, address) -> None:
        self.transport.unpartition_actor(self._resolve_actor(address).address)

    def deliver_all(self, max_steps: int = 100000) -> int:
        steps = 0
        while self.transport.messages and steps < max_steps:
            self.transport.deliver_message(self.transport.messages[0])
            steps += 1
        return steps

    # -- History export (JsTransport.scala:260-298) --------------------------

    def export_test(self, test_name: str, setup_code: str) -> str:
        """Generate a pytest function replaying the recorded history.
        ``setup_code`` must define a variable ``t`` (the SimTransport) with
        the same actors and seeds as this session."""
        lines = [
            f"def {test_name}():",
        ]
        for line in setup_code.strip().splitlines():
            lines.append(f"    {line}")
        lines.append(
            "    from frankenpaxos_tpu.core import ("
            "HostPort, QueuedMessage, SimAddress)"
        )

        def addr_expr(a) -> str:
            # Clusters built from the deployment registry use HostPort
            # role addresses on the SimTransport; sessions may mix kinds.
            if hasattr(a, "name"):
                return f"SimAddress({a.name!r})"
            return f"HostPort({a.host!r}, {a.port!r})"

        def msg_expr(m) -> str:
            return (
                f"QueuedMessage({addr_expr(m.src)}, "
                f"{addr_expr(m.dst)}, {m.data!r})"
            )

        for cmd in self.transport.history:
            if isinstance(cmd, DeliverMessage):
                lines.append(f"    t.deliver_message({msg_expr(cmd.msg)})")
            elif isinstance(cmd, TriggerTimer):
                lines.append(
                    f"    t.trigger_timer({addr_expr(cmd.address)}, "
                    f"{cmd.name!r}, occurrence={cmd.occurrence})"
                )
            elif isinstance(cmd, DropMessage):
                lines.append(f"    t.drop_message({msg_expr(cmd.msg)})")
            elif isinstance(cmd, DuplicateMessage):
                lines.append(f"    t.duplicate_message({msg_expr(cmd.msg)})")
            elif isinstance(cmd, PartitionActor):
                lines.append(
                    f"    t.partition_actor({addr_expr(cmd.address)})"
                )
            elif isinstance(cmd, UnpartitionActor):
                lines.append(
                    f"    t.unpartition_actor({addr_expr(cmd.address)})"
                )
        lines.append("    # TODO: add assertions about the final state.")
        return "\n".join(lines) + "\n"
