"""Terminal REPL over a Stepper (the browser-viz analog):

    python -m frankenpaxos_tpu.viz.repl [protocol]

Commands: msgs | timers | actors | state <actor> | deliver <i> | drop <i> |
dup <i> | fire <i> | partition <actor> | unpartition <actor> | run |
export <test_name> | quit
"""

from __future__ import annotations

import sys

from frankenpaxos_tpu.viz import Stepper


def build_cluster(protocol: str):
    """Build a small demo cluster; returns (transport, description)."""
    from frankenpaxos_tpu.core import FakeLogger, SimAddress, SimTransport
    from frankenpaxos_tpu.core.logger import LogLevel

    t = SimTransport(FakeLogger(LogLevel.FATAL))
    log = lambda: FakeLogger(LogLevel.FATAL)
    if protocol == "paxos":
        from frankenpaxos_tpu.protocols import paxos as px

        config = px.PaxosConfig(
            f=1,
            leader_addresses=(SimAddress("leader0"), SimAddress("leader1")),
            acceptor_addresses=tuple(
                SimAddress(f"acceptor{i}") for i in range(3)
            ),
        )
        for a in config.leader_addresses:
            px.PaxosLeader(a, t, log(), config)
        for a in config.acceptor_addresses:
            px.PaxosAcceptor(a, t, log(), config)
        clients = [
            px.PaxosClient(SimAddress(f"client{i}"), t, log(), config)
            for i in range(2)
        ]
        clients[0].propose("apple")
        clients[1].propose("banana")
        return t, "paxos: 2 clients proposed 'apple' and 'banana'"
    raise SystemExit(f"unknown protocol {protocol!r}; try: paxos")


def main() -> None:
    protocol = sys.argv[1] if len(sys.argv) > 1 else "paxos"
    transport, description = build_cluster(protocol)
    stepper = Stepper(transport)
    print(description)
    print("commands: msgs timers actors state deliver drop dup fire "
          "partition unpartition run export quit")
    while True:
        try:
            line = input("viz> ").strip()
        except EOFError:
            return
        if not line:
            continue
        cmd, *args = line.split()
        try:
            if cmd == "quit":
                return
            elif cmd == "msgs":
                print("\n".join(stepper.messages()) or "(none)")
            elif cmd == "timers":
                print("\n".join(stepper.timers()) or "(none)")
            elif cmd == "actors":
                print("\n".join(stepper.actors()))
            elif cmd == "state":
                for k, v in stepper.state(args[0]).items():
                    print(f"  {k} = {v!r}")
            elif cmd == "deliver":
                stepper.deliver(int(args[0]))
            elif cmd == "drop":
                stepper.drop(int(args[0]))
            elif cmd == "dup":
                stepper.duplicate(int(args[0]))
            elif cmd == "fire":
                stepper.fire(int(args[0]))
            elif cmd == "partition":
                stepper.partition(args[0])
            elif cmd == "unpartition":
                stepper.unpartition(args[0])
            elif cmd == "run":
                print(f"delivered {stepper.deliver_all()} messages")
            elif cmd == "export":
                name = args[0] if args else "test_replay"
                print(stepper.export_test(name, "# setup: rebuild the cluster here\nt = ..."))
            else:
                print(f"unknown command {cmd!r}")
        except (IndexError, KeyError, ValueError) as e:
            print(f"error: {e}")


if __name__ == "__main__":
    main()
