"""Browser visualization over the Stepper (the analog of the reference's
24 Vue/snap.svg apps, ``js/src/main/js`` + ``JsTransport.scala:175-298``):

    python -m frankenpaxos_tpu.viz.web --protocol paxos --port 8765

builds the chosen protocol's cluster on a SimTransport (via the same
deployment registry the TCP mains use, so EVERY registered protocol is
viewable), serves a self-contained HTML page that renders the actors on
an SVG ring with in-flight messages between them, and exposes the
Stepper's controls: click a message to deliver it (buttons drop or
duplicate it), fire timers, partition actors, inspect live actor state,
and issue client operations. All mutations run on the single HTTP
thread, preserving the single-threaded event-loop contract
(Transport.scala:37-39).
"""

from __future__ import annotations

import argparse
import http.server
import json
import sys
import urllib.parse
from typing import Optional

from frankenpaxos_tpu.core import FakeLogger, SimTransport, wire
from frankenpaxos_tpu.core.logger import LogLevel
from frankenpaxos_tpu.viz import Stepper

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>frankenpaxos_tpu viz</title>
<style>
 body { font-family: monospace; margin: 0; display: flex; height: 100vh; }
 #left { flex: 1; position: relative; }
 #right { width: 420px; overflow-y: auto; border-left: 1px solid #ccc;
          padding: 8px; background: #fafafa; }
 svg { width: 100%; height: 100%; }
 .actor circle { fill: #4a90d9; cursor: pointer; }
 .actor.partitioned circle { fill: #d94a4a; }
 .actor.selected circle { stroke: #222; stroke-width: 3; }
 .actor text { font-size: 11px; text-anchor: middle; pointer-events: none; }
 .msg line { stroke: #999; stroke-width: 1.5; marker-end: url(#arrow); }
 .msg circle { fill: #e8a33d; cursor: pointer; }
 .msg:hover circle { fill: #d9534a; }
 h3 { margin: 6px 0; }
 button { margin: 1px; font-family: monospace; }
 pre { background: #fff; border: 1px solid #ddd; padding: 6px;
       white-space: pre-wrap; word-break: break-all; }
 .row { margin: 2px 0; }
</style></head>
<body>
<div id="left"><svg id="svg" viewBox="0 0 800 800">
 <defs><marker id="arrow" viewBox="0 0 10 10" refX="9" refY="5"
   markerWidth="6" markerHeight="6" orient="auto-start-reverse">
   <path d="M 0 0 L 10 5 L 0 10 z" fill="#999"/></marker></defs>
 <g id="links"></g><g id="actors"></g>
</svg></div>
<div id="right">
 <h3 id="title"></h3>
 <div class="row">
  <button onclick="api('op')">client op</button>
  <button onclick="api('deliver_all')">deliver all</button>
  <button onclick="exportTest()">export as test</button>
 </div>
 <h3>in-flight messages</h3><div id="msgs"></div>
 <h3>timers</h3><div id="timers"></div>
 <h3>actor state</h3><div id="state"><i>click an actor</i></div>
</div>
<script>
let selected = null;
async function api(path, params) {
  const q = params ? '?' + new URLSearchParams(params) : '';
  await fetch('/api/' + path + q, {method: 'POST'});
  refresh();
}
function positions(names) {
  const cx = 400, cy = 400, r = 320, pos = {};
  names.forEach((name, i) => {
    const a = 2 * Math.PI * i / names.length - Math.PI / 2;
    pos[name] = [cx + r * Math.cos(a), cy + r * Math.sin(a)];
  });
  return pos;
}
async function refresh() {
  const s = await (await fetch('/api/state')).json();
  document.getElementById('title').textContent =
    s.protocol + ' — ' + s.messages.length + ' in flight';
  const pos = positions(s.actors.map(a => a.name));
  const actors = s.actors.map(a => {
    const [x, y] = pos[a.name];
    const cls = 'actor' + (a.partitioned ? ' partitioned' : '')
      + (a.name === selected ? ' selected' : '');
    return `<g class="${cls}" onclick="select('${a.name}')">
      <circle cx="${x}" cy="${y}" r="26"></circle>
      <text x="${x}" y="${y + 44}">${a.name}</text></g>`;
  }).join('');
  document.getElementById('actors').innerHTML = actors;
  const links = s.messages.map((m, j) => {
    const [x1, y1] = pos[m.src] || [400, 400];
    const [x2, y2] = pos[m.dst] || [400, 400];
    // Spread concurrent messages along their line.
    const t = 0.35 + 0.3 * ((j * 37) % 100) / 100;
    const mx = x1 + (x2 - x1) * t, my = y1 + (y2 - y1) * t;
    return `<g class="msg"><line x1="${x1}" y1="${y1}" x2="${x2}" y2="${y2}"/>
      <circle cx="${mx}" cy="${my}" r="8"
        onclick="api('deliver', {tok: '${m.tok}'})"><title>${m.desc}</title>
      </circle></g>`;
  }).join('');
  document.getElementById('links').innerHTML = links;
  document.getElementById('msgs').innerHTML = s.messages.map(m =>
    `<div class="row">${m.desc}
     <button onclick="api('deliver', {tok: '${m.tok}'})">deliver</button>
     <button onclick="api('drop', {tok: '${m.tok}'})">drop</button>
     <button onclick="api('duplicate', {tok: '${m.tok}'})">dup</button></div>`
  ).join('') || '<i>none</i>';
  document.getElementById('timers').innerHTML = s.timers.map(t =>
    `<div class="row">${t.desc}
     <button onclick="api('fire', {tok: '${t.tok}'})">fire</button></div>`
  ).join('') || '<i>none</i>';
  if (selected) {
    const st = s.states[selected] || {};
    const a = s.actors.find(a => a.name === selected) || {};
    document.getElementById('state').innerHTML =
      `<div class="row"><b>${selected}</b>
       <button onclick="api('${a.partitioned ? 'unpartition' : 'partition'}',
         {addr: '${selected}'})">${a.partitioned ? 'heal' : 'partition'}
       </button></div><pre>${JSON.stringify(st, null, 1)}</pre>`;
  }
}
function select(name) { selected = name; refresh(); }
async function exportTest() {
  const r = await (await fetch('/api/export', {method: 'POST'})).json();
  const esc = r.code.replace(/&/g, '&amp;').replace(/</g, '&lt;');
  document.getElementById('state').innerHTML =
    '<b>replay test (copy into tests/)</b><pre>' + esc + '</pre>';
}
refresh();
setInterval(refresh, 1000);
</script></body></html>
"""


class VizServer:
    """Serves the page + a JSON API over a Stepper. Single-threaded: the
    HTTP server IS the event loop, so handler mutations are serial."""

    def __init__(self, protocol: str, stepper: Stepper, client, issue):
        self.protocol = protocol
        self.stepper = stepper
        self.client = client
        self.issue = issue
        self.op_counter = 0
        self.trace: list = []  # replayable code lines (export_test)

    def _message_tokens(self):
        """Stable per-message tokens: object identity plus an occurrence
        ordinal (duplicate_message re-queues the SAME object). Clicks act
        on tokens, not list positions, so a click racing a state change
        becomes a reported no-op instead of acting on the wrong
        message."""
        tokens = []
        seen = {}
        for m in self.stepper.transport.messages:
            n = seen.get(id(m), 0)
            seen[id(m)] = n + 1
            tokens.append(f"{id(m)}.{n}")
        return tokens

    def _resolve_message(self, token: str) -> int:
        for i, tok in enumerate(self._message_tokens()):
            if tok == token:
                return i
        raise KeyError(f"stale message token {token!r}")

    def _timer_tokens(self):
        """Stable per-timer tokens: address|name plus an occurrence
        ordinal — an actor may run several timers under one name (e.g.
        per-op retry timers), and without the ordinal a 'fire' click
        could fire a different timer than the one displayed."""
        seen = {}
        tokens = []
        for t in self.stepper.transport.running_timers():
            base = f"{t.address}|{t.name()}"
            n = seen.get(base, 0)
            seen[base] = n + 1
            tokens.append(f"{base}.{n}")
        return tokens

    def _resolve_timer(self, token: str) -> int:
        for i, tok in enumerate(self._timer_tokens()):
            if tok == token:
                return i
        raise KeyError(f"stale timer token {token!r}")

    def snapshot(self) -> dict:
        t = self.stepper.transport
        partitioned = {str(a) for a in getattr(t, "partitioned", ())}
        actors = []
        states = {}
        for name in self.stepper.actors():
            actors.append({"name": name, "partitioned": name in partitioned})
            try:
                states[name] = {
                    k: repr(v)[:400]
                    for k, v in self.stepper.state(name).items()
                }
            except Exception as e:  # noqa: BLE001 - viz must not crash
                states[name] = {"error": repr(e)}
        messages = []
        for m, tok in zip(t.messages, self._message_tokens()):
            try:
                desc = repr(wire.decode(m.data))[:120]
            except Exception:  # noqa: BLE001
                desc = f"<{len(m.data)} bytes>"
            messages.append({
                "tok": tok, "src": str(m.src), "dst": str(m.dst), "desc": desc,
            })
        timers = [
            {"tok": tok, "desc": desc}
            for tok, desc in zip(self._timer_tokens(), self.stepper.timers())
        ]
        return {
            "protocol": self.protocol,
            "actors": actors,
            "states": states,
            "messages": messages,
            "timers": timers,
        }

    @staticmethod
    def _addr_expr(a) -> str:
        # Viz clusters built from the deployment registry use HostPort
        # role addresses on the SimTransport; sessions may mix both kinds.
        if hasattr(a, "name"):
            return f"SimAddress({a.name!r})"
        return f"HostPort({a.host!r}, {a.port!r})"

    def _msg_expr(self, i: int) -> str:
        m = self.stepper.transport.messages[i]
        return (
            f"QueuedMessage({self._addr_expr(m.src)}, "
            f"{self._addr_expr(m.dst)}, {m.data!r})"
        )

    def export_test(self, test_name: str = "test_replay") -> str:
        """A runnable pytest function replaying this browser session —
        the JsTransport.scala:260-298 export-as-unit-test capability,
        from the web UI. Setup is real code: build_cluster is
        deterministic, so the replayed deliveries match."""
        lines = [
            f"def {test_name}():",
            "    from frankenpaxos_tpu.core import (",
            "        HostPort, QueuedMessage, SimAddress,",
            "    )",
            "    from frankenpaxos_tpu.viz.web import build_cluster",
            f"    t, client, issue = build_cluster({self.protocol!r})",
        ]
        lines += [f"    {line}" for line in self.trace]
        lines.append("    # assert on final actor/client state here")
        return "\n".join(lines)

    def handle(self, path: str, params: dict) -> Optional[dict]:
        s = self.stepper
        if path == "state":
            return self.snapshot()
        if path == "export":
            return {"code": self.export_test(params.get("name", "test_replay"))}
        if path == "deliver":
            i = self._resolve_message(params["tok"])
            self.trace.append(f"t.deliver_message({self._msg_expr(i)})")
            s.deliver(i)
        elif path == "drop":
            i = self._resolve_message(params["tok"])
            self.trace.append(f"t.drop_message({self._msg_expr(i)})")
            s.drop(i)
        elif path == "duplicate":
            i = self._resolve_message(params["tok"])
            self.trace.append(f"t.duplicate_message({self._msg_expr(i)})")
            s.duplicate(i)
        elif path == "fire":
            i = self._resolve_timer(params["tok"])
            timer = s.transport.running_timers()[i]
            self.trace.append(
                f"t.trigger_timer({self._addr_expr(timer.address)}, "
                f"{timer.name()!r}, occurrence={s.occurrence_of(i)})"
            )
            s.fire(i)
        elif path == "partition":
            addr = s._resolve_actor(params["addr"]).address
            self.trace.append(
                f"t.partition_actor({self._addr_expr(addr)})"
            )
            s.partition(params["addr"])
        elif path == "unpartition":
            addr = s._resolve_actor(params["addr"]).address
            self.trace.append(
                f"t.unpartition_actor({self._addr_expr(addr)})"
            )
            s.unpartition(params["addr"])
        elif path == "deliver_all":
            self.trace.append(
                # Bounded like the live Stepper.deliver_all: a retrans-
                # mitting protocol must not turn the replay into a hang.
                "for _ in range(100000):\n"
                "        if not t.messages: break\n"
                "        t.deliver_message(t.messages[0])"
            )
            s.deliver_all()
        elif path == "op":
            if self.issue is not None:
                self.trace.append(f"issue(client, 0, {self.op_counter})")
                self.issue(self.client, 0, self.op_counter)
                self.op_counter += 1
        else:
            return None
        return {"ok": True}

    def serve(self, port: int, host: str = "127.0.0.1") -> None:
        viz = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def _json(self, obj, code=200):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                if parsed.path == "/":
                    body = _PAGE.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif parsed.path == "/api/state":
                    self._json(viz.snapshot())
                else:
                    self._json({"error": "not found"}, 404)

            def do_POST(self):  # noqa: N802
                parsed = urllib.parse.urlparse(self.path)
                params = {
                    k: v[0]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                if not parsed.path.startswith("/api/"):
                    self._json({"error": "not found"}, 404)
                    return
                try:
                    result = viz.handle(parsed.path[len("/api/"):], params)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": repr(e)}, 400)
                    return
                if result is None:
                    self._json({"error": "unknown action"}, 404)
                else:
                    self._json(result)

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer((host, port), Handler)
        print(f"viz: http://{host}:{port}/ ({self.protocol})")
        server.serve_forever()


def build_cluster(protocol: str):
    """Build the protocol's standard small cluster on a SimTransport via
    the deployment registry (the Scala.js wrapper analog,
    ``js/src/main/scala/frankenpaxos/<proto>/<Proto>.scala``)."""
    from frankenpaxos_tpu.mains.registry import REGISTRY

    spec = REGISTRY[protocol]
    transport = SimTransport(FakeLogger(LogLevel.FATAL))
    config = spec.parse_config(spec.local_config(lambda i: f"n{i}:0"))
    logger = FakeLogger(LogLevel.FATAL)
    from frankenpaxos_tpu.mains.registry import iter_role_instances

    for role_name, role, g, i in iter_role_instances(spec, config):
        role.build(config, i, g, transport, logger, 0)
    from frankenpaxos_tpu.core import SimAddress

    # Protocols whose config lists client addresses (e.g. matchmakerpaxos)
    # expect the client to live at one of them.
    listed = getattr(config, "client_addresses", None)
    listen = listed[0] if listed else SimAddress("client")
    client = spec.make_client(config, listen, transport, logger, 99)
    return transport, client, spec.issue


def main() -> None:
    from frankenpaxos_tpu.mains.registry import REGISTRY

    parser = argparse.ArgumentParser(prog="frankenpaxos_tpu.viz.web")
    parser.add_argument("--protocol", default="paxos", choices=sorted(REGISTRY))
    parser.add_argument("--port", type=int, default=8765)
    parser.add_argument("--host", default="127.0.0.1")
    args = parser.parse_args()

    transport, client, issue = build_cluster(args.protocol)
    viz = VizServer(args.protocol, Stepper(transport), client, issue)
    viz.serve(args.port, args.host)


if __name__ == "__main__":
    sys.exit(main())
