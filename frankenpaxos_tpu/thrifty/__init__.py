"""Thriftiness policies: which quorum members to message.

Capability parity with ``thrifty/ThriftySystem.scala:29-80``: ``NotThrifty``
(message everyone), ``Random`` (a random minimal subset), and ``Closest``
(the nearest by heartbeat-measured network delay).
"""

from __future__ import annotations

import random
from typing import Callable, Dict, Sequence, Set, TypeVar

T = TypeVar("T")

INFINITE_DELAY = float("inf")


class ThriftySystem:
    def choose(
        self,
        delays: Dict[T, float],
        min_size: int,
        rng: random.Random,
    ) -> Set[T]:
        """Choose which of ``delays.keys()`` to message such that at least
        ``min_size`` are chosen."""
        raise NotImplementedError


class NotThrifty(ThriftySystem):
    def choose(self, delays, min_size, rng) -> Set:
        return set(delays.keys())

    def __repr__(self) -> str:
        return "NotThrifty"


class RandomThrifty(ThriftySystem):
    def choose(self, delays, min_size, rng) -> Set:
        nodes = sorted(delays.keys())
        return set(rng.sample(nodes, min(min_size, len(nodes))))

    def __repr__(self) -> str:
        return "Random"


class Closest(ThriftySystem):
    """Pick the min_size nodes with smallest measured delay (ties broken by
    node order for determinism)."""

    def choose(self, delays, min_size, rng) -> Set:
        ranked = sorted(delays.items(), key=lambda kv: (kv[1], kv[0]))
        return {node for node, _ in ranked[:min_size]}

    def __repr__(self) -> str:
        return "Closest"


REGISTRY = {
    "NotThrifty": NotThrifty,
    "Random": RandomThrifty,
    "Closest": Closest,
}


def from_name(name: str) -> ThriftySystem:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(f"{name} is not one of {', '.join(sorted(REGISTRY))}.") from None
