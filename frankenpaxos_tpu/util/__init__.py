"""Small perf-critical data structures.

Capability parity with the reference ``util`` package and ``Util.scala``:

  * :class:`BufferMap` — watermark-offset growable log with GC
    (``util/BufferMap.scala:8-100``);
  * :class:`QuorumWatermark` — the largest k-of-n frontier
    (``util/QuorumWatermark.scala:31-48``);
  * :class:`QuorumWatermarkVector` (``util/QuorumWatermarkVector.scala``);
  * :class:`TopOne` / :class:`TopK` — per-leader max / top-k dependency
    compression (``util/TopOne.scala``, ``util/TopK.scala:6-33``);
  * :class:`VertexIdLike` — (leader_index, id) typeclass
    (``util/VertexIdLike.scala``);
  * ``histogram`` / ``popular_items`` / ``random_duration`` helpers
    (``Util.scala:5-60``).
"""

from __future__ import annotations

import random as _random
from typing import (
    Callable,
    Dict,
    Generic,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
    TypeVar,
)

V = TypeVar("V")
T = TypeVar("T")


class BufferMap(Generic[V]):
    """A map from int keys >= a GC watermark to values, backed by a growable
    buffer so gets/puts are O(1) and GC is a prefix drop
    (BufferMap.scala:8-100). Keys below the watermark read as None and puts
    to them are ignored."""

    def __init__(self, grow_size: int = 5000):
        self.grow_size = grow_size
        self.buffer: List[Optional[V]] = [None] * grow_size
        self.watermark = 0
        self.largest_key = -1

    def __repr__(self) -> str:
        return f"BufferMap(watermark={self.watermark}, {self.to_map()!r})"

    def _normalize(self, key: int) -> int:
        return key - self.watermark

    def get(self, key: int) -> Optional[V]:
        i = self._normalize(key)
        if i < 0 or i >= len(self.buffer):
            return None
        return self.buffer[i]

    def put(self, key: int, value: V) -> None:
        self.largest_key = max(self.largest_key, key)
        i = self._normalize(key)
        if i < 0:
            return
        if i >= len(self.buffer):
            self.buffer.extend([None] * (i + 1 + self.grow_size - len(self.buffer)))
        self.buffer[i] = value

    def contains(self, key: int) -> bool:
        return self.get(key) is not None

    def garbage_collect(self, watermark: int) -> None:
        if watermark <= self.watermark:
            return
        drop = min(watermark - self.watermark, len(self.buffer))
        del self.buffer[:drop]
        self.watermark = watermark

    def items(self) -> Iterator[Tuple[int, V]]:
        return self.items_from(self.watermark)

    def items_from(self, key: int) -> Iterator[Tuple[int, V]]:
        for k in range(max(key, self.watermark), self.largest_key + 1):
            v = self.get(k)
            if v is not None:
                yield (k, v)

    def to_map(self) -> Dict[int, V]:
        return {
            i + self.watermark: v
            for i, v in enumerate(self.buffer)
            if v is not None
        }


class QuorumWatermark:
    """Given n monotonically-increasing watermarks, ``watermark(k)`` is the
    largest w such that >= k watermarks are >= w — i.e. the k'th largest
    (QuorumWatermark.scala:31-48)."""

    def __init__(self, num_watermarks: int):
        self.watermarks = [0] * num_watermarks

    def __repr__(self) -> str:
        return f"[{','.join(map(str, self.watermarks))}]"

    def update(self, index: int, watermark: int) -> None:
        self.watermarks[index] = max(self.watermarks[index], watermark)

    def watermark(self, quorum_size: int) -> int:
        n = len(self.watermarks)
        if not 1 <= quorum_size <= n:
            raise ValueError(f"quorum_size {quorum_size} not in [1, {n}]")
        return sorted(self.watermarks)[n - quorum_size]


class QuorumWatermarkVector:
    """n watermark vectors of depth d; each column is an independent
    QuorumWatermark (QuorumWatermarkVector.scala)."""

    def __init__(self, n: int, depth: int):
        self.columns = [QuorumWatermark(n) for _ in range(depth)]

    def __repr__(self) -> str:
        return "\n".join(repr(c) for c in self.columns)

    def update(self, index: int, watermark: Sequence[int]) -> None:
        for w, col in zip(watermark, self.columns):
            col.update(index, w)

    def watermark(self, quorum_size: int) -> List[int]:
        return [col.watermark(quorum_size) for col in self.columns]


class VertexIdLike(Generic[V]):
    """Typeclass viewing V as a (leader_index, id) vertex id
    (util/VertexIdLike.scala)."""

    def leader_index(self, v: V) -> int:
        raise NotImplementedError

    def id(self, v: V) -> int:
        raise NotImplementedError

    def make(self, leader_index: int, id: int) -> V:
        raise NotImplementedError


class TupleVertexIdLike(VertexIdLike[Tuple[int, int]]):
    def leader_index(self, v: Tuple[int, int]) -> int:
        return v[0]

    def id(self, v: Tuple[int, int]) -> int:
        return v[1]

    def make(self, leader_index: int, id: int) -> Tuple[int, int]:
        return (leader_index, id)


class TopOne(Generic[V]):
    """Per-leader max id + 1 (an exclusive frontier), mergeable
    (TopOne.scala)."""

    def __init__(self, num_leaders: int, like: VertexIdLike[V]):
        self.like = like
        self.top_ones = [0] * num_leaders

    def put(self, x: V) -> None:
        i = self.like.leader_index(x)
        self.top_ones[i] = max(self.top_ones[i], self.like.id(x) + 1)

    def get(self) -> List[int]:
        return self.top_ones

    def merge_equals(self, other: "TopOne[V]") -> None:
        for i in range(len(self.top_ones)):
            self.top_ones[i] = max(self.top_ones[i], other.top_ones[i])


class TopK(Generic[V]):
    """Per-leader top-k ids, mergeable (TopK.scala:6-33)."""

    def __init__(self, k: int, num_leaders: int, like: VertexIdLike[V]):
        self.k = k
        self.like = like
        self.top: List[Set[int]] = [set() for _ in range(num_leaders)]

    def put(self, x: V) -> None:
        ids = self.top[self.like.leader_index(x)]
        ids.add(self.like.id(x))
        if len(ids) > self.k:
            ids.discard(min(ids))

    def get(self) -> List[Set[int]]:
        return self.top

    def merge_equals(self, other: "TopK[V]") -> None:
        for i in range(len(self.top)):
            ids = self.top[i]
            ids |= other.top[i]
            while len(ids) > self.k:
                ids.discard(min(ids))


# -- Util.scala helpers ------------------------------------------------------


def histogram(xs: Iterable[T]) -> Dict[T, int]:
    h: Dict[T, int] = {}
    for x in xs:
        h[x] = h.get(x, 0) + 1
    return h


def popular_items(xs: Iterable[T], n: int) -> Set[T]:
    """The elements appearing n or more times (Util.popularItems:
    popularItems(Seq('a','a','a','b','b','c'), 2) == Set('a','b'))."""
    return {x for x, c in histogram(xs).items() if c >= n}


def random_duration(rng: _random.Random, min_s: float, max_s: float) -> float:
    """Uniform duration in [min_s, max_s] (Util.randomDuration)."""
    return min_s + rng.random() * (max_s - min_s)


def merge_maps_with(
    a: Dict, b: Dict, merge: Callable[[V, V], V]
) -> Dict:
    """Merge two maps, combining values under ``merge`` on key collision
    (Util.scala map merge)."""
    out = dict(a)
    for k, v in b.items():
        out[k] = merge(out[k], v) if k in out else v
    return out
