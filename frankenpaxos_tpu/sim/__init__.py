"""Randomized simulation / property testing harness.

Capability parity with the reference's test harness
(``shared/src/test/scala/simulator/SimulatedSystem.scala:152-200`` and
``Simulator.scala:28-70``): a :class:`SimulatedSystem` supplies
``new_system(seed)``, ``get_state``, ``generate_command``, ``run_command``
and three invariant kinds — *state* (every state), *step* (consecutive
pairs), *history* (whole run). :func:`simulate` runs seeded random command
histories checking invariants after every command; on failure it returns a
:class:`BadHistory` and :func:`minimize` shrink-searches sub-histories
(random-subset sampling à la ScalaCheck ``Gen.someOf`` plus greedy
delta-debugging) for a minimal counterexample.

Design notes vs the reference: ``generate_command`` receives an explicit
``random.Random`` (the reference uses ScalaCheck generators with ambient
randomness), so whole runs — including scheduling — are replayable from
``(seed, history)`` alone. Command replay must be tolerant of stale
commands (e.g. delivering an already-delivered message is a no-op), which
is exactly the contract of ``SimTransport``; that is what makes arbitrary
subsequences of a bad history executable during shrinking.
"""

from __future__ import annotations

import dataclasses
import random
import traceback
from typing import Any, Generic, List, Optional, Sequence, TypeVar

System = TypeVar("System")
State = TypeVar("State")
Command = TypeVar("Command")


class InvariantViolated(Exception):
    pass


class SimulatedSystem(Generic[System, State, Command]):
    def new_system(self, seed: int) -> System:
        """Create a fresh system; all randomness must derive from seed."""
        raise NotImplementedError

    def get_state(self, system: System) -> State:
        """Extract the (immutable) state the invariants talk about."""
        raise NotImplementedError

    def generate_command(
        self, system: System, rng: random.Random
    ) -> Optional[Command]:
        """Generate the next random command, or None if the system halted."""
        raise NotImplementedError

    def run_command(self, system: System, command: Command) -> System:
        """Run a command (stale commands must be no-ops for shrinkability)."""
        raise NotImplementedError

    # Invariants: return None if the invariant holds, else an explanation
    # string (the analog of InvariantHolds/InvariantViolated).

    def state_invariant(self, state: State) -> Optional[str]:
        return None

    def step_invariant(self, old: State, new: State) -> Optional[str]:
        return None

    def history_invariant(self, history: Sequence[State]) -> Optional[str]:
        return None


@dataclasses.dataclass
class BadHistory(Generic[Command]):
    seed: int
    history: List[Command]
    error: str

    def __str__(self) -> str:
        lines = [f"BadHistory(seed={self.seed}):", f"  error: {self.error}"]
        for i, cmd in enumerate(self.history):
            lines.append(f"  [{i}] {cmd!r}")
        return "\n".join(lines)


def _check_invariants(sim: SimulatedSystem, states: List[Any]) -> Optional[str]:
    """Check state on the last state, step on the last pair, history on all
    (Simulator.scala:checkInvariants)."""
    if not states:
        return None
    err = sim.state_invariant(states[-1])
    if err is not None:
        return err
    if len(states) >= 2:
        err = sim.step_invariant(states[-2], states[-1])
        if err is not None:
            return err
    return sim.history_invariant(states)


def run_history(
    sim: SimulatedSystem, seed: int, history: Sequence[Any]
) -> Optional[str]:
    """Replay a command history on a fresh system; return an error string if
    an invariant is violated or an exception is raised."""
    try:
        system = sim.new_system(seed)
        states = [sim.get_state(system)]
        err = _check_invariants(sim, states)
        if err is not None:
            return err
        for command in history:
            system = sim.run_command(system, command)
            states.append(sim.get_state(system))
            err = _check_invariants(sim, states)
            if err is not None:
                return err
        return None
    except Exception:
        return traceback.format_exc()


def _simulate_one(
    sim: SimulatedSystem, seed: int, run_length: int
) -> Optional[BadHistory]:
    rng = random.Random(seed ^ 0x5EED)
    history: List[Any] = []
    try:
        system = sim.new_system(seed)
        states = [sim.get_state(system)]
        err = _check_invariants(sim, states)
        if err is not None:
            return BadHistory(seed, history, err)
        for _ in range(run_length):
            command = sim.generate_command(system, rng)
            if command is None:
                return None
            history.append(command)
            system = sim.run_command(system, command)
            states.append(sim.get_state(system))
            err = _check_invariants(sim, states)
            if err is not None:
                return BadHistory(seed, history, err)
        return None
    except Exception:
        return BadHistory(seed, history, traceback.format_exc())


def simulate(
    sim: SimulatedSystem,
    run_length: int,
    num_runs: int,
    seed: int = 0,
) -> Optional[BadHistory]:
    """Run ``num_runs`` seeded simulations of length <= ``run_length``,
    checking invariants after every command (Simulator.scala:28-41). Returns
    the first (un-minimized) BadHistory, or None."""
    for i in range(num_runs):
        bad = _simulate_one(sim, seed + i, run_length)
        if bad is not None:
            return bad
    return None


def minimize(
    sim: SimulatedSystem,
    seed: int,
    history: Sequence[Any],
    num_trials: int = 1500,
) -> BadHistory:
    """Find a small sub-history of a bad history that still fails
    (Simulator.scala:43-70). Greedy delta-debugging (try dropping chunks,
    halving chunk size) followed by random-subset probing."""
    err = run_history(sim, seed, history)
    if err is None:
        raise ValueError("minimize() called with a good history")
    best = list(history)

    # Greedy chunk removal (ddmin-flavored).
    trials = 0
    chunk = max(1, len(best) // 2)
    while chunk >= 1 and trials < num_trials:
        i = 0
        shrunk = False
        while i < len(best) and trials < num_trials:
            candidate = best[:i] + best[i + chunk :]
            trials += 1
            cand_err = run_history(sim, seed, candidate)
            if cand_err is not None:
                best = candidate
                err = cand_err
                shrunk = True
            else:
                i += chunk
        if not shrunk or chunk > len(best):
            chunk //= 2

    # Random subset probing to escape greedy local minima.
    rng = random.Random(seed ^ 0xD1CE)
    while trials < num_trials and len(best) > 1:
        k = rng.randrange(1, len(best))
        idx = sorted(rng.sample(range(len(best)), k))
        candidate = [best[i] for i in idx]
        trials += 1
        cand_err = run_history(sim, seed, candidate)
        if cand_err is not None and len(candidate) < len(best):
            best = candidate
            err = cand_err
    return BadHistory(seed, best, err)


def simulate_and_minimize(
    sim: SimulatedSystem,
    run_length: int,
    num_runs: int,
    seed: int = 0,
    num_trials: int = 1500,
) -> Optional[BadHistory]:
    bad = simulate(sim, run_length, num_runs, seed)
    if bad is None:
        return None
    if not bad.history:
        return bad
    return minimize(sim, bad.seed, bad.history, num_trials)


# -- Command-generation helpers for protocol testbeds ------------------------


def weighted_choice(rng: random.Random, choices):
    """Pick from [(weight, value), ...] proportionally to weight; None if
    empty."""
    total = sum(w for w, _ in choices)
    if total == 0:
        return None
    pick = rng.randrange(total)
    for w, value in choices:
        if pick < w:
            return value
        pick -= w
    raise AssertionError("unreachable")


def mixed_command(rng: random.Random, transport, op_choices):
    """The standard testbed command generator: client operations (given as
    [(weight, command), ...]) mixed with transport deliveries and timer
    firings weighted by queue sizes — the FakeTransport.generateCommand
    model with protocol-specific operations layered on top."""
    from frankenpaxos_tpu.core import DeliverMessage, TriggerTimer

    choices = list(op_choices)
    if transport.messages:
        choices.append((len(transport.messages), "__deliver__"))
    running = transport.running_timers()
    if running:
        choices.append((len(running), "__timer__"))
    choice = weighted_choice(rng, choices)
    if choice == "__deliver__":
        return DeliverMessage(
            transport.messages[rng.randrange(len(transport.messages))]
        )
    if choice == "__timer__":
        i = rng.randrange(len(running))
        timer = running[i]
        return TriggerTimer(
            timer.address, timer.name(), transport.timer_occurrence(i)
        )
    return choice
