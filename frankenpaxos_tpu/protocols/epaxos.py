"""EPaxos — leaderless SMR with dependency tracking (reference ``epaxos/``:
an all-in-one Replica actor plus a thin Client).

Every replica leads its own instances (replica_index, instance_number).
PreAccept computes dependencies from a conflict index; a fast quorum
(n-1 = 2f of n = 2f+1) agreeing on identical (seq, deps) commits on the
fast path (Replica.scala handlePreAcceptOk); otherwise the slow path runs
Paxos-Accept with f+1. Committed instances enter a dependency graph and
execute as eligible SCCs in deterministic order. Recovery: a recover timer
on a blocking instance runs Prepare in a higher ballot
(Replica.scala:1121-1560) — on a quorum of PrepareOks the new leader
adopts an Accepted triple if any, else a triple pre-accepted by f
non-leader replicas in the default ballot, else restarts PreAccept
(avoiding the fast path), else pre-accepts a noop.
"""

from __future__ import annotations

import dataclasses
import functools
import random
from typing import Dict, FrozenSet, List, Optional, Tuple, Union

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.clienttable import ClientTable, Executed, NotExecuted
from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import (
    TupleVertexIdLike,
    popular_items,
    random_duration,
)

# Instances are (replica_index, instance_number) tuples; ballots are
# (ordering, replica_index) tuples ordered lexicographically; NULL_BALLOT
# sorts below every real ballot. Dependencies travel as sorted tuples of
# instances (exact mode) or as compact EpPrefixDeps watermark vectors
# (top_k_dependencies mode) and are handled via the _deps_* helpers
# internally; they materialize into explicit instance sets only at the
# dependency-graph boundary.
NULL_BALLOT = (-1, -1)

NOT_SEEN, PRE_ACCEPTED, ACCEPTED, COMMITTED = range(4)


@wire.message
@dataclasses.dataclass(frozen=True)
class EpCommand:
    client_address: bytes
    client_pseudonym: int
    client_id: int
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class EpClientRequest:
    command: EpCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class EpPrefixDeps:
    """A prefix-shaped dependency set compressed to per-column watermarks:
    {(col, i) : i < watermarks[col]} minus the optional ``exclude``
    instance (the instance whose dependencies these are, when it falls
    inside its own column's prefix). O(replicas) in state and on the wire
    regardless of instance history — the analog of the reference's
    InstancePrefixSet (epaxos/InstancePrefixSet.scala,
    Replica.scala:578-589)."""

    watermarks: tuple
    exclude: Optional[tuple]


Deps = Union[FrozenSet[tuple], EpPrefixDeps]


def _normalize_prefix_deps(watermarks: List[int], exclude) -> EpPrefixDeps:
    """Canonicalize so that equal sets compare equal on the fast path: an
    exclusion outside the prefix is dropped, and one at the very top of
    its column is folded into the watermark."""
    if exclude is not None:
        col, i = exclude
        if col >= len(watermarks) or i >= watermarks[col]:
            exclude = None
        elif i == watermarks[col] - 1:
            watermarks = list(watermarks)
            watermarks[col] = i
            exclude = None
    return EpPrefixDeps(watermarks=tuple(watermarks), exclude=exclude)


def _deps_union(a: Deps, b: Deps) -> Deps:
    if isinstance(a, EpPrefixDeps) and isinstance(b, EpPrefixDeps):
        wa, wb = a.watermarks, b.watermarks
        n = max(len(wa), len(wb))
        wa = wa + (0,) * (n - len(wa))
        wb = wb + (0,) * (n - len(wb))
        # Both sides describe the deps of the same instance, so when the
        # instance lies inside either prefix that side excluded it; union
        # therefore excludes it too.
        return _normalize_prefix_deps(
            [max(x, y) for x, y in zip(wa, wb)], a.exclude or b.exclude
        )
    if isinstance(a, frozenset) and isinstance(b, frozenset):
        return a | b
    # Mixed representations (heterogeneously configured cluster): fall
    # back to an exact set.
    return frozenset(_deps_materialize(a)) | frozenset(_deps_materialize(b))


def _deps_materialize(deps: Deps) -> set:
    """Expand to an explicit set of instances (dependency-graph boundary)."""
    if isinstance(deps, EpPrefixDeps):
        out = {
            (col, i) for col, w in enumerate(deps.watermarks) for i in range(w)
        }
        out.discard(deps.exclude)
        return out
    return set(deps)


def _deps_wire(deps: Deps):
    """Wire form: compact message in top-k mode, sorted tuple otherwise."""
    if isinstance(deps, EpPrefixDeps):
        return deps
    return tuple(sorted(deps))


def _deps_from_wire(w) -> Deps:
    if isinstance(w, EpPrefixDeps):
        return w
    return frozenset(w)


@wire.message
@dataclasses.dataclass(frozen=True)
class EpPreAccept:
    instance: tuple
    ballot: tuple
    command: Optional[EpCommand]  # None = noop
    sequence_number: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpPreAcceptOk:
    instance: tuple
    ballot: tuple
    replica_index: int
    sequence_number: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpAccept:
    instance: tuple
    ballot: tuple
    command: Optional[EpCommand]
    sequence_number: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpAcceptOk:
    instance: tuple
    ballot: tuple
    replica_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class EpCommit:
    instance: tuple
    command: Optional[EpCommand]
    sequence_number: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class EpPrepare:
    instance: tuple
    ballot: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpPrepareOk:
    ballot: tuple
    instance: tuple
    replica_index: int
    vote_ballot: tuple
    status: int
    command: Optional[EpCommand]
    sequence_number: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EpNack:
    instance: tuple
    largest_ballot: tuple


@dataclasses.dataclass(frozen=True)
class EPaxosConfig:
    f: int
    replica_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.n - 1

    @property
    def slow_quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.replica_addresses) != self.n:
            raise ValueError(f"need exactly {self.n} replicas")


@dataclasses.dataclass(frozen=True)
class EPaxosReplicaOptions:
    resend_pre_accepts_period: float = 5.0
    default_to_slow_path_period: float = 5.0
    resend_accepts_period: float = 5.0
    resend_prepares_period: float = 5.0
    recover_instance_min_period: float = 5.0
    recover_instance_max_period: float = 10.0
    execute_graph_batch_size: int = 1
    execute_graph_timer_period: float = 1.0  # flushes partial batches
    unsafe_skip_graph_execution: bool = False
    # When set, dependency sets are PREFIX-SHAPED: the top-k conflict
    # index tracks each replica column's newest conflicting instance,
    # and the dependency set is the whole column prefix up to that
    # frontier (the reference expands top-k the same way via
    # InstancePrefixSet.fromTopOne/fromTopK, Replica.scala:578-589 —
    # raw frontier ids alone would be UNSAFE: a multi-key command can
    # conflict with two mutually non-conflicting instances in one
    # column, and only the newer would make it into the dep set).
    # Prefix-shaped sets trade extra (harmless) ordering edges for
    # O(columns) compressibility.
    top_k_dependencies: int = 0  # 0 = exact conflict sets


@dataclasses.dataclass
class _Triple:
    command: Optional[EpCommand]
    sequence_number: int
    dependencies: FrozenSet[tuple]


@dataclasses.dataclass
class _NoCommandEntry:
    ballot: tuple


@dataclasses.dataclass
class _PreAcceptedEntry:
    ballot: tuple
    vote_ballot: tuple
    triple: _Triple


@dataclasses.dataclass
class _AcceptedEntry:
    ballot: tuple
    vote_ballot: tuple
    triple: _Triple


@dataclasses.dataclass
class _CommittedEntry:
    triple: _Triple


@dataclasses.dataclass
class _PreAccepting:
    ballot: tuple
    command: Optional[EpCommand]
    responses: Dict[int, EpPreAcceptOk]
    avoid_fast_path: bool
    resend_timer: object
    slow_path_timer: Optional[object]


@dataclasses.dataclass
class _Accepting:
    ballot: tuple
    triple: _Triple
    responses: Dict[int, EpAcceptOk]
    resend_timer: object


@dataclasses.dataclass
class _Preparing:
    ballot: tuple
    responses: Dict[int, EpPrepareOk]
    resend_timer: object


class EpReplica(Actor):
    def __init__(self, address, transport, logger, config: EPaxosConfig,
                 state_machine: StateMachine,
                 options: EPaxosReplicaOptions = EPaxosReplicaOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.replica_addresses.index(address)
        self.other_addresses = [
            a for a in config.replica_addresses if a != address
        ]
        self.cmd_log: Dict[tuple, object] = {}
        self.next_available_instance = 0
        self.default_ballot = (0, self.index)
        self.largest_ballot = (0, self.index)
        self.dependency_graph = TarjanDependencyGraph()
        self.client_table: ClientTable = ClientTable()
        if options.top_k_dependencies > 0:
            self.conflict_index = state_machine.top_k_conflict_index(
                options.top_k_dependencies,
                len(config.replica_addresses),
                TupleVertexIdLike(),
            )
        else:
            self.conflict_index = state_machine.conflict_index()
        self.leader_states: Dict[tuple, object] = {}
        self.recover_timers: Dict[tuple, object] = {}
        self._pending_committed = 0
        # With batched graph execution, a flush timer guarantees a tail of
        # commits below the batch size still executes (the analog of the
        # reference's executeGraphTimerPeriod timer).
        if (
            options.execute_graph_batch_size > 1
            and not options.unsafe_skip_graph_execution
        ):
            def flush() -> None:
                self._execute_graph()
                self._pending_committed = 0
                self.execute_graph_timer.start()

            self.execute_graph_timer = self.timer(
                "executeGraphTimer", options.execute_graph_timer_period, flush
            )
            self.execute_graph_timer.start()
        else:
            self.execute_graph_timer = None

    # -- Helpers -------------------------------------------------------------

    def _leader_ballot(self, state) -> tuple:
        return state.ballot

    def _thrifty_others(self, n: int) -> List[Address]:
        return [
            self.other_addresses[i]
            for i in self.rng.sample(range(len(self.other_addresses)), n)
        ]

    def _compute_seq_deps(
        self, instance: tuple, command: Optional[EpCommand]
    ) -> Tuple[int, FrozenSet[tuple]]:
        """Dependencies = conflicting instances from the conflict index
        (Replica.scala computeSequenceNumberAndDependencies — note the
        reference also returns sequence number 0: Tarjan's deterministic
        in-component order makes seq numbers unnecessary)."""
        if command is None:
            if self.options.top_k_dependencies > 0:
                return 0, _normalize_prefix_deps([0] * self.config.n, None)
            return 0, frozenset()
        if self.options.top_k_dependencies > 0:
            # Keep deps compact: each column's conflict frontier IS the
            # dependency set (the whole prefix up to it), so state and
            # wire carry only the O(columns) watermark vector (see
            # EPaxosReplicaOptions.top_k_dependencies).
            tops = self.conflict_index.get_top_k_conflicts(command.command)
            watermarks = [max(ids, default=-1) + 1 for ids in tops]
            while len(watermarks) < self.config.n:
                watermarks.append(0)
            return 0, _normalize_prefix_deps(watermarks, instance)
        deps = set(self.conflict_index.get_conflicts(command.command))
        deps.discard(instance)
        return 0, frozenset(deps)

    def _update_conflict_index(self, instance, command) -> None:
        if command is not None:
            self.conflict_index.put(instance, command.command)

    def _stop_timers(self, instance) -> None:
        state = self.leader_states.get(instance)
        if isinstance(state, _PreAccepting):
            state.resend_timer.stop()
            if state.slow_path_timer is not None:
                state.slow_path_timer.stop()
        elif isinstance(state, (_Accepting, _Preparing)):
            state.resend_timer.stop()

    def _make_resend_timer(self, name, period, send_once):
        def fire() -> None:
            send_once()
            timer.start()

        timer = self.timer(name, period, fire)
        timer.start()
        return timer

    def _check_ballot_le(self, instance, ballot) -> None:
        entry = self.cmd_log.get(instance)
        if isinstance(entry, _CommittedEntry):
            self.logger.fatal(f"instance {instance} is already committed")
        if isinstance(entry, _NoCommandEntry):
            self.logger.check_le(entry.ballot, ballot)
        elif isinstance(entry, (_PreAcceptedEntry, _AcceptedEntry)):
            self.logger.check_le(entry.ballot, ballot)
            self.logger.check_le(entry.vote_ballot, ballot)

    # -- Phase transitions ---------------------------------------------------

    def _transition_to_pre_accept(
        self, instance, ballot, command, avoid_fast_path: bool
    ) -> None:
        seq, deps = self._compute_seq_deps(instance, command)
        self._check_ballot_le(instance, ballot)
        self.cmd_log[instance] = _PreAcceptedEntry(
            ballot=ballot, vote_ballot=ballot,
            triple=_Triple(command, seq, deps),
        )
        self._update_conflict_index(instance, command)
        pre_accept = EpPreAccept(
            instance=instance, ballot=ballot, command=command,
            sequence_number=seq, dependencies=_deps_wire(deps),
        )
        for a in self._thrifty_others(self.config.fast_quorum_size - 1):
            self.chan(a).send(pre_accept)
        self._stop_timers(instance)
        self.leader_states[instance] = _PreAccepting(
            ballot=ballot,
            command=command,
            responses={
                self.index: EpPreAcceptOk(
                    instance=instance, ballot=ballot,
                    replica_index=self.index, sequence_number=seq,
                    dependencies=_deps_wire(deps),
                )
            },
            avoid_fast_path=avoid_fast_path,
            resend_timer=self._make_resend_timer(
                f"resendPreAccepts{instance}",
                self.options.resend_pre_accepts_period,
                lambda: [self.chan(a).send(pre_accept) for a in self.other_addresses],
            ),
            slow_path_timer=None,
        )

    def _transition_to_accept(self, instance, ballot, triple: _Triple) -> None:
        self._check_ballot_le(instance, ballot)
        self.cmd_log[instance] = _AcceptedEntry(
            ballot=ballot, vote_ballot=ballot, triple=triple
        )
        self._update_conflict_index(instance, triple.command)
        accept = EpAccept(
            instance=instance, ballot=ballot, command=triple.command,
            sequence_number=triple.sequence_number,
            dependencies=_deps_wire(triple.dependencies),
        )
        for a in self._thrifty_others(self.config.slow_quorum_size - 1):
            self.chan(a).send(accept)
        self._stop_timers(instance)
        self.leader_states[instance] = _Accepting(
            ballot=ballot,
            triple=triple,
            responses={
                self.index: EpAcceptOk(
                    instance=instance, ballot=ballot, replica_index=self.index
                )
            },
            resend_timer=self._make_resend_timer(
                f"resendAccepts{instance}",
                self.options.resend_accepts_period,
                lambda: [self.chan(a).send(accept) for a in self.other_addresses],
            ),
        )

    def _transition_to_prepare(self, instance) -> None:
        self._stop_timers(instance)
        self.largest_ballot = (self.largest_ballot[0] + 1, self.index)
        ballot = self.largest_ballot
        prepare = EpPrepare(instance=instance, ballot=ballot)
        targets = self._thrifty_others(self.config.slow_quorum_size - 1)
        for a in targets:
            self.chan(a).send(prepare)
        self.chan(self.address).send(prepare)  # include self
        self.leader_states[instance] = _Preparing(
            ballot=ballot,
            responses={},
            resend_timer=self._make_resend_timer(
                f"resendPrepares{instance}",
                self.options.resend_prepares_period,
                lambda: [
                    self.chan(a).send(prepare)
                    for a in self.config.replica_addresses
                ],
            ),
        )

    def _pre_accepting_slow_path(self, instance, state: _PreAccepting) -> None:
        seq = max(ok.sequence_number for ok in state.responses.values())
        deps = functools.reduce(
            _deps_union,
            (_deps_from_wire(ok.dependencies) for ok in state.responses.values()),
        )
        self._transition_to_accept(
            instance, state.ballot, _Triple(state.command, seq, deps)
        )

    def _commit(self, instance, triple: _Triple, inform_others: bool) -> None:
        self._stop_timers(instance)
        self.cmd_log[instance] = _CommittedEntry(triple)
        self._update_conflict_index(instance, triple.command)
        self.leader_states.pop(instance, None)
        if inform_others:
            commit = EpCommit(
                instance=instance, command=triple.command,
                sequence_number=triple.sequence_number,
                dependencies=_deps_wire(triple.dependencies),
            )
            for a in self.other_addresses:
                self.chan(a).send(commit)
        timer = self.recover_timers.pop(instance, None)
        if timer is not None:
            timer.stop()
        if self.options.unsafe_skip_graph_execution:
            self._execute_command(instance, triple.command)
            return
        self.dependency_graph.commit(
            instance, triple.sequence_number, _deps_materialize(triple.dependencies)
        )
        self._pending_committed += 1
        if self._pending_committed % self.options.execute_graph_batch_size == 0:
            self._execute_graph()
            self._pending_committed = 0
            if self.execute_graph_timer is not None:
                self.execute_graph_timer.reset()

    def _execute_graph(self) -> None:
        executables, blockers = self.dependency_graph.execute()
        for instance in blockers:
            if instance not in self.recover_timers:
                self.recover_timers[instance] = self._make_recover_timer(instance)
        for instance in executables:
            entry = self.cmd_log.get(instance)
            if not isinstance(entry, _CommittedEntry):
                self.logger.fatal(
                    f"instance {instance} executable but not committed"
                )
            self._execute_command(instance, entry.triple.command)

    def _make_recover_timer(self, instance):
        def fire() -> None:
            self._transition_to_prepare(instance)
            timer.start()

        timer = self.timer(
            f"recoverInstance{instance}",
            random_duration(
                self.rng,
                self.options.recover_instance_min_period,
                self.options.recover_instance_max_period,
            ),
            fire,
        )
        timer.start()
        return timer

    def _execute_command(self, instance, command: Optional[EpCommand]) -> None:
        if command is None:
            return  # noop
        identity = (command.client_address, command.client_pseudonym)
        result = self.client_table.executed(identity, command.client_id)
        if isinstance(result, Executed):
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        # Only the instance's home replica replies (Replica.scala:738-744).
        if self.index == instance[0]:
            client = self.transport.address_from_bytes(command.client_address)
            self.chan(client).send(
                EpClientReply(
                    client_pseudonym=command.client_pseudonym,
                    client_id=command.client_id,
                    result=output,
                )
            )

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, EpClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, EpPreAccept):
            self._handle_pre_accept(src, msg)
        elif isinstance(msg, EpPreAcceptOk):
            self._handle_pre_accept_ok(msg)
        elif isinstance(msg, EpAccept):
            self._handle_accept(src, msg)
        elif isinstance(msg, EpAcceptOk):
            self._handle_accept_ok(msg)
        elif isinstance(msg, EpCommit):
            self._handle_commit(msg)
        elif isinstance(msg, EpNack):
            self._handle_nack(msg)
        elif isinstance(msg, EpPrepare):
            self._handle_prepare(src, msg)
        elif isinstance(msg, EpPrepareOk):
            self._handle_prepare_ok(msg)
        else:
            self.logger.fatal(f"unknown epaxos message {msg!r}")

    def _handle_client_request(self, src: Address, msg: EpClientRequest) -> None:
        command = msg.command
        identity = (command.client_address, command.client_pseudonym)
        result = self.client_table.executed(identity, command.client_id)
        if isinstance(result, Executed):
            if result.output is not None:
                client = self.transport.address_from_bytes(command.client_address)
                self.chan(client).send(
                    EpClientReply(
                        client_pseudonym=command.client_pseudonym,
                        client_id=command.client_id,
                        result=result.output,
                    )
                )
            return
        instance = (self.index, self.next_available_instance)
        self.next_available_instance += 1
        self._transition_to_pre_accept(
            instance, self.default_ballot, command, avoid_fast_path=False
        )

    def _handle_pre_accept(self, src: Address, msg: EpPreAccept) -> None:
        entry = self.cmd_log.get(msg.instance)
        nack = EpNack(instance=msg.instance, largest_ballot=self.largest_ballot)
        if isinstance(entry, _NoCommandEntry):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
        elif isinstance(entry, _PreAcceptedEntry):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
            if msg.ballot == entry.vote_ballot:
                self.chan(src).send(
                    EpPreAcceptOk(
                        instance=msg.instance, ballot=msg.ballot,
                        replica_index=self.index,
                        sequence_number=entry.triple.sequence_number,
                        dependencies=_deps_wire(entry.triple.dependencies),
                    )
                )
                return
        elif isinstance(entry, _AcceptedEntry):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
            if msg.ballot == entry.vote_ballot:
                return
        elif isinstance(entry, _CommittedEntry):
            self.chan(src).send(
                EpCommit(
                    instance=msg.instance, command=entry.triple.command,
                    sequence_number=entry.triple.sequence_number,
                    dependencies=_deps_wire(entry.triple.dependencies),
                )
            )
            return

        state = self.leader_states.get(msg.instance)
        if state is not None and msg.ballot > self._leader_ballot(state):
            self._stop_timers(msg.instance)
            del self.leader_states[msg.instance]
        self.largest_ballot = max(self.largest_ballot, msg.ballot)
        timer = self.recover_timers.get(msg.instance)
        if timer is not None:
            timer.reset()

        seq, deps = self._compute_seq_deps(msg.instance, msg.command)
        seq = max(seq, msg.sequence_number)
        deps = _deps_union(deps, _deps_from_wire(msg.dependencies))
        self.cmd_log[msg.instance] = _PreAcceptedEntry(
            ballot=msg.ballot, vote_ballot=msg.ballot,
            triple=_Triple(msg.command, seq, deps),
        )
        self._update_conflict_index(msg.instance, msg.command)
        self.chan(src).send(
            EpPreAcceptOk(
                instance=msg.instance, ballot=msg.ballot,
                replica_index=self.index, sequence_number=seq,
                dependencies=_deps_wire(deps),
            )
        )

    def _handle_pre_accept_ok(self, msg: EpPreAcceptOk) -> None:
        state = self.leader_states.get(msg.instance)
        if not isinstance(state, _PreAccepting):
            return
        if msg.ballot != state.ballot:
            self.logger.check_lt(msg.ballot, state.ballot)
            return
        old_n = len(state.responses)
        state.responses[msg.replica_index] = msg
        new_n = len(state.responses)
        if new_n < self.config.slow_quorum_size:
            return
        if (
            not state.avoid_fast_path
            and old_n < self.config.slow_quorum_size <= new_n
            and self.config.slow_quorum_size < self.config.fast_quorum_size
        ):
            # A slow quorum formed; wait a beat for the fast quorum.
            state.slow_path_timer = self.timer(
                f"defaultToSlowPath{msg.instance}",
                self.options.default_to_slow_path_period,
                lambda: self._pre_accepting_slow_path(msg.instance, state),
            )
            state.slow_path_timer.start()
            return
        if state.avoid_fast_path and new_n >= self.config.slow_quorum_size:
            self._pre_accepting_slow_path(msg.instance, state)
            return
        if new_n >= self.config.fast_quorum_size:
            seq_deps = [
                (ok.sequence_number, ok.dependencies)
                for i, ok in state.responses.items()
                if i != self.index
            ]
            candidates = popular_items(
                seq_deps, self.config.fast_quorum_size - 1
            )
            if candidates:
                self.logger.check_eq(len(candidates), 1)
                seq, deps = next(iter(candidates))
                self._commit(
                    msg.instance,
                    _Triple(state.command, seq, _deps_from_wire(deps)),
                    inform_others=True,
                )
            else:
                self._pre_accepting_slow_path(msg.instance, state)

    def _handle_accept(self, src: Address, msg: EpAccept) -> None:
        entry = self.cmd_log.get(msg.instance)
        nack = EpNack(instance=msg.instance, largest_ballot=self.largest_ballot)
        if isinstance(entry, (_NoCommandEntry, _PreAcceptedEntry)):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
        elif isinstance(entry, _AcceptedEntry):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
            if msg.ballot == entry.vote_ballot:
                self.chan(src).send(
                    EpAcceptOk(
                        instance=msg.instance, ballot=msg.ballot,
                        replica_index=self.index,
                    )
                )
                return
        elif isinstance(entry, _CommittedEntry):
            self.chan(src).send(
                EpCommit(
                    instance=msg.instance, command=entry.triple.command,
                    sequence_number=entry.triple.sequence_number,
                    dependencies=_deps_wire(entry.triple.dependencies),
                )
            )
            return
        state = self.leader_states.get(msg.instance)
        if state is not None and msg.ballot > self._leader_ballot(state):
            self._stop_timers(msg.instance)
            del self.leader_states[msg.instance]
        self.largest_ballot = max(self.largest_ballot, msg.ballot)
        timer = self.recover_timers.get(msg.instance)
        if timer is not None:
            timer.reset()
        self.cmd_log[msg.instance] = _AcceptedEntry(
            ballot=msg.ballot, vote_ballot=msg.ballot,
            triple=_Triple(
                msg.command, msg.sequence_number, _deps_from_wire(msg.dependencies)
            ),
        )
        self._update_conflict_index(msg.instance, msg.command)
        self.chan(src).send(
            EpAcceptOk(
                instance=msg.instance, ballot=msg.ballot,
                replica_index=self.index,
            )
        )

    def _handle_accept_ok(self, msg: EpAcceptOk) -> None:
        state = self.leader_states.get(msg.instance)
        if not isinstance(state, _Accepting):
            return
        if msg.ballot != state.ballot:
            self.logger.check_lt(msg.ballot, state.ballot)
            return
        state.responses[msg.replica_index] = msg
        if len(state.responses) < self.config.slow_quorum_size:
            return
        self._commit(msg.instance, state.triple, inform_others=True)

    def _handle_commit(self, msg: EpCommit) -> None:
        if isinstance(self.cmd_log.get(msg.instance), _CommittedEntry):
            return
        self._commit(
            msg.instance,
            _Triple(
                msg.command, msg.sequence_number, _deps_from_wire(msg.dependencies)
            ),
            inform_others=False,
        )

    def _handle_nack(self, msg: EpNack) -> None:
        self.largest_ballot = max(self.largest_ballot, msg.largest_ballot)
        state = self.leader_states.get(msg.instance)
        if state is None or state.ballot >= msg.largest_ballot:
            return
        timer = self.recover_timers.get(msg.instance)
        if timer is not None:
            timer.reset()
        else:
            self.recover_timers[msg.instance] = self._make_recover_timer(
                msg.instance
            )

    def _handle_prepare(self, src: Address, msg: EpPrepare) -> None:
        self.largest_ballot = max(self.largest_ballot, msg.ballot)
        timer = self.recover_timers.get(msg.instance)
        if timer is not None:
            timer.reset()
        state = self.leader_states.get(msg.instance)
        if (
            state is not None
            and msg.ballot > self._leader_ballot(state)
            and src != self.address
        ):
            self._stop_timers(msg.instance)
            del self.leader_states[msg.instance]
        entry = self.cmd_log.get(msg.instance)
        nack = EpNack(instance=msg.instance, largest_ballot=self.largest_ballot)
        if entry is None or isinstance(entry, _NoCommandEntry):
            if entry is not None and msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
            self.chan(src).send(
                EpPrepareOk(
                    ballot=msg.ballot, instance=msg.instance,
                    replica_index=self.index, vote_ballot=NULL_BALLOT,
                    status=NOT_SEEN, command=None, sequence_number=0,
                    dependencies=(),
                )
            )
            self.cmd_log[msg.instance] = _NoCommandEntry(msg.ballot)
        elif isinstance(entry, (_PreAcceptedEntry, _AcceptedEntry)):
            if msg.ballot < entry.ballot:
                self.chan(src).send(nack)
                return
            status = (
                PRE_ACCEPTED if isinstance(entry, _PreAcceptedEntry) else ACCEPTED
            )
            self.chan(src).send(
                EpPrepareOk(
                    ballot=msg.ballot, instance=msg.instance,
                    replica_index=self.index, vote_ballot=entry.vote_ballot,
                    status=status, command=entry.triple.command,
                    sequence_number=entry.triple.sequence_number,
                    dependencies=_deps_wire(entry.triple.dependencies),
                )
            )
            entry.ballot = msg.ballot
        elif isinstance(entry, _CommittedEntry):
            self.chan(src).send(
                EpCommit(
                    instance=msg.instance, command=entry.triple.command,
                    sequence_number=entry.triple.sequence_number,
                    dependencies=_deps_wire(entry.triple.dependencies),
                )
            )

    def _handle_prepare_ok(self, msg: EpPrepareOk) -> None:
        state = self.leader_states.get(msg.instance)
        if not isinstance(state, _Preparing):
            return
        if msg.ballot != state.ballot:
            self.logger.check_lt(msg.ballot, state.ballot)
            return
        state.responses[msg.replica_index] = msg
        if len(state.responses) < self.config.slow_quorum_size:
            return
        max_vote = max(ok.vote_ballot for ok in state.responses.values())
        top = [
            ok for ok in state.responses.values() if ok.vote_ballot == max_vote
        ]
        accepted = next((ok for ok in top if ok.status == ACCEPTED), None)
        if accepted is not None:
            self._transition_to_accept(
                msg.instance, state.ballot,
                _Triple(
                    accepted.command, accepted.sequence_number,
                    _deps_from_wire(accepted.dependencies),
                ),
            )
            return
        # Triples pre-accepted in the instance leader's DEFAULT ballot by f
        # replicas other than the recovering leader bind the value
        # (Replica.scala:1496-1520).
        default = (0, msg.instance[0])
        candidates = popular_items(
            [
                (ok.command, ok.sequence_number, ok.dependencies)
                for ok in top
                if ok.status == PRE_ACCEPTED
                and ok.vote_ballot == default
                and ok.replica_index != self.index
            ],
            self.config.f,
        )
        if candidates:
            self.logger.check_eq(len(candidates), 1)
            command, seq, deps = next(iter(candidates))
            self._transition_to_accept(
                msg.instance, state.ballot,
                _Triple(command, seq, _deps_from_wire(deps)),
            )
            return
        pre_accepted = next(
            (ok for ok in top if ok.status == PRE_ACCEPTED), None
        )
        if pre_accepted is not None:
            self._transition_to_pre_accept(
                msg.instance, state.ballot, pre_accepted.command,
                avoid_fast_path=True,
            )
        else:
            self._transition_to_pre_accept(
                msg.instance, state.ballot, None, avoid_fast_path=True
            )


@dataclasses.dataclass
class _EpPending:
    id: int
    result: Promise
    resend: object


class EpClient(Actor):
    def __init__(self, address, transport, logger, config: EPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _EpPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = EpClientRequest(
            EpCommand(
                client_address=self.address_bytes,
                client_pseudonym=pseudonym,
                client_id=id,
                command=command,
            )
        )
        replica = self.config.replica_addresses[
            self.rng.randrange(len(self.config.replica_addresses))
        ]
        self.chan(replica).send(request)

        def resend() -> None:
            target = self.config.replica_addresses[
                self.rng.randrange(len(self.config.replica_addresses))
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(
            f"resendEp[{pseudonym};{id}]", self.resend_period, resend
        )
        timer.start()
        self.pending[pseudonym] = _EpPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, EpClientReply):
            self.logger.fatal(f"unknown epaxos client message {msg!r}")
        pending = self.pending.get(msg.client_pseudonym)
        if pending is None or msg.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[msg.client_pseudonym]
        pending.result.success(msg.result)
