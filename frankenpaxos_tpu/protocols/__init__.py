"""Protocol implementations.

Each protocol package mirrors one of the reference's protocol packages
under ``shared/src/main/scala/frankenpaxos/`` (see SURVEY.md §2.3): a set of
role actors parameterized by transport, a ``Config`` of role addresses with
``check_valid()``, per-role ``Options`` dataclasses with defaults, and
per-role metrics built against the ``monitoring`` facade.
"""
