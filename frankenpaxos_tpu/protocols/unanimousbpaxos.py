"""Unanimous BPaxos (reference ``unanimousbpaxos/``: Client, Leader,
DepServiceNode, Acceptor).

The fast-path variant of BPaxos: each dependency service node is
co-located with an acceptor; on a DependencyRequest it computes the
command's dependencies and hands its acceptor a fast proposal, which the
acceptor fast-votes in round 0 and reports straight to the vertex's
leader (Phase2bFast). If ALL n acceptors report IDENTICAL dependency sets
(fastQuorumSize = n — unanimity), the vertex commits in one round trip;
otherwise the leader, who owns classic round 1, proposes the UNION of the
reported sets in round 1 (Leader.handlePhase2bFast). Recovery of stuck
vertices runs classic rounds with the standard value-selection rule: a
unique max-round vote wins; divergent round-0 votes recover as noop
(Leader.handlePhase1b). Committed vertices execute through a dependency
graph at the leaders with an exactly-once client table.

Deliberate divergence from Leader.scala:745-756: a round-0 value is
adopted during recovery only when EVERY sampled acceptor fast-voted it —
a quorum containing an abstention (a promise with no round-0 vote)
recovers as noop, because the abstention proves unanimity is impossible
and the reference's rule of adopting the partial voters' value also
adopts their possibly-stale dependency sets, which we observed committing
two conflicting commands with no dependency edge between them (divergent
execution orders across leaders).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, FrozenSet, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.clienttable import ClientTable, Executed
from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.roundsystem import RotatedRoundZeroFast
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import random_duration

# Vote values are (command | None, deps tuple) pairs; vertex ids are
# (leader_index, id) tuples.


@wire.message
@dataclasses.dataclass(frozen=True)
class UbCommand:
    client_address: bytes
    client_pseudonym: int
    client_id: int
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class UbClientRequest:
    command: UbCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class UbClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class UbDependencyRequest:
    vertex_id: tuple
    command: UbCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class UbFastProposal:
    vertex_id: tuple
    value: tuple  # (command, deps)


@wire.message
@dataclasses.dataclass(frozen=True)
class UbPhase2bFast:
    vertex_id: tuple
    acceptor_id: int
    value: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class UbPhase1a:
    vertex_id: tuple
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class UbPhase1b:
    vertex_id: tuple
    acceptor_id: int
    round: int
    vote_round: int
    vote_value: Optional[tuple]


@wire.message
@dataclasses.dataclass(frozen=True)
class UbPhase2a:
    vertex_id: tuple
    round: int
    vote_value: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class UbPhase2bClassic:
    vertex_id: tuple
    acceptor_id: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class UbNack:
    vertex_id: tuple
    higher_round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class UbCommit:
    vertex_id: tuple
    value: tuple


@dataclasses.dataclass(frozen=True)
class UnanimousBPaxosConfig:
    f: int
    leader_addresses: tuple
    dep_service_node_addresses: tuple
    acceptor_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.n  # unanimity

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.dep_service_node_addresses) != self.n:
            raise ValueError(f"need exactly {self.n} dep service nodes")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError(f"need exactly {self.n} acceptors")


@dataclasses.dataclass
class _UbPhase2Fast:
    command: UbCommand
    phase2b_fasts: Dict[int, UbPhase2bFast]
    resend: object


@dataclasses.dataclass
class _UbPhase1:
    round: int
    phase1bs: Dict[int, UbPhase1b]
    resend: object


@dataclasses.dataclass
class _UbPhase2Classic:
    round: int
    value: tuple
    phase2bs: Dict[int, UbPhase2bClassic]
    resend: object


@dataclasses.dataclass
class _UbCommitted:
    value: tuple


class UbLeader(Actor):
    def __init__(self, address, transport, logger,
                 config: UnanimousBPaxosConfig, state_machine: StateMachine,
                 resend_period: float = 5.0,
                 recover_min_period: float = 5.0,
                 recover_max_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.recover_min_period = recover_min_period
        self.recover_max_period = recover_max_period
        self.index = config.leader_addresses.index(address)
        self.next_vertex_id = 0
        self.states: Dict[tuple, object] = {}
        self.dependency_graph = TarjanDependencyGraph()
        self.client_table: ClientTable = ClientTable()
        self.recover_timers: Dict[tuple, object] = {}

    def _round_system(self, vertex_id: tuple):
        # Round 0 is the FAST round; classic rounds rotate starting from
        # the vertex's own leader, so round 1 (the first classic round)
        # belongs to the owner — which is what lets the owner jump
        # straight to round 1 on fast-path disagreement
        # (Leader.scala roundSystem + the checkEq at Leader.scala:664).
        return RotatedRoundZeroFast(
            len(self.config.leader_addresses), vertex_id[0]
        )

    def _make_resend(self, name: str, send_once):
        def fire() -> None:
            send_once()
            timer.start()

        timer = self.timer(name, self.resend_period, fire)
        timer.start()
        return timer

    def _stop_timers(self, vertex_id) -> None:
        state = self.states.get(vertex_id)
        if isinstance(state, (_UbPhase2Fast, _UbPhase1, _UbPhase2Classic)):
            state.resend.stop()

    def _will_be_committed(self, vertex_id) -> bool:
        return isinstance(
            self.states.get(vertex_id), (_UbPhase1, _UbPhase2Classic, _UbCommitted)
        )

    def _make_recover_timer(self, vertex_id):
        def fire() -> None:
            if not self._will_be_committed(vertex_id):
                self._recover(vertex_id, nack_round=-1)

        timer = self.timer(
            f"recoverVertex{vertex_id}",
            random_duration(
                self.rng, self.recover_min_period, self.recover_max_period
            ),
            fire,
        )
        timer.start()
        return timer

    def _recover(self, vertex_id, nack_round: int) -> None:
        state = self.states.get(vertex_id)
        if isinstance(state, _UbCommitted):
            return
        current = 0
        if isinstance(state, (_UbPhase1, _UbPhase2Classic)):
            current = state.round
        round = self._round_system(vertex_id).next_classic_round(
            self.index, max(nack_round, current)
        )
        self._stop_timers(vertex_id)
        phase1a = UbPhase1a(vertex_id=vertex_id, round=round)
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase1a)
        self.states[vertex_id] = _UbPhase1(
            round=round,
            phase1bs={},
            resend=self._make_resend(
                f"resendPhase1a{vertex_id}",
                lambda: [
                    self.chan(a).send(phase1a)
                    for a in self.config.acceptor_addresses
                ],
            ),
        )
        timer = self.recover_timers.pop(vertex_id, None)
        if timer is not None:
            timer.stop()

    def _commit(self, vertex_id, value: tuple, inform_others: bool) -> None:
        if isinstance(self.states.get(vertex_id), _UbCommitted):
            return
        self._stop_timers(vertex_id)
        self.states[vertex_id] = _UbCommitted(value)
        if inform_others:
            commit = UbCommit(vertex_id=vertex_id, value=value)
            for leader in self.config.leader_addresses:
                if leader != self.address:
                    self.chan(leader).send(commit)
        timer = self.recover_timers.pop(vertex_id, None)
        if timer is not None:
            timer.stop()
        command, dependencies = value
        # Arm recovery for uncommitted dependencies (Leader.commit).
        for dep in dependencies:
            if not self._will_be_committed(dep) and dep not in self.recover_timers:
                self.recover_timers[dep] = self._make_recover_timer(dep)
        self.dependency_graph.commit(vertex_id, 0, set(dependencies))
        executables, _blockers = self.dependency_graph.execute()
        for v in executables:
            committed = self.states.get(v)
            if not isinstance(committed, _UbCommitted):
                self.logger.fatal(f"vertex {v} executable but not committed")
            self._execute(v, committed.value)

    def _execute(self, vertex_id, value: tuple) -> None:
        command, _ = value
        if command is None:
            return  # noop
        identity = (command.client_address, command.client_pseudonym)
        if isinstance(self.client_table.executed(identity, command.client_id),
                      Executed):
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        if self.index == vertex_id[0]:
            client = self.transport.address_from_bytes(command.client_address)
            self.chan(client).send(
                UbClientReply(
                    client_pseudonym=command.client_pseudonym,
                    client_id=command.client_id,
                    result=output,
                )
            )

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, UbClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, UbPhase2bFast):
            self._handle_phase2b_fast(msg)
        elif isinstance(msg, UbPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, UbPhase2bClassic):
            self._handle_phase2b_classic(msg)
        elif isinstance(msg, UbNack):
            self._handle_nack(msg)
        elif isinstance(msg, UbCommit):
            self._commit(msg.vertex_id, msg.value, inform_others=False)
        else:
            self.logger.fatal(f"unknown ubpaxos leader message {msg!r}")

    def _handle_client_request(self, src: Address, msg: UbClientRequest) -> None:
        command = msg.command
        identity = (command.client_address, command.client_pseudonym)
        executed = self.client_table.executed(identity, command.client_id)
        if isinstance(executed, Executed):
            if executed.output is not None:
                client = self.transport.address_from_bytes(command.client_address)
                self.chan(client).send(
                    UbClientReply(
                        client_pseudonym=command.client_pseudonym,
                        client_id=command.client_id,
                        result=executed.output,
                    )
                )
            return
        vertex_id = (self.index, self.next_vertex_id)
        self.next_vertex_id += 1
        request = UbDependencyRequest(vertex_id=vertex_id, command=command)
        for node in self.config.dep_service_node_addresses:
            self.chan(node).send(request)
        self.states[vertex_id] = _UbPhase2Fast(
            command=command,
            phase2b_fasts={},
            resend=self._make_resend(
                f"resendDeps{vertex_id}",
                lambda: [
                    self.chan(node).send(request)
                    for node in self.config.dep_service_node_addresses
                ],
            ),
        )
        self.recover_timers[vertex_id] = self._make_recover_timer(vertex_id)

    def _handle_phase2b_fast(self, msg: UbPhase2bFast) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _UbPhase2Fast):
            return
        state.phase2b_fasts[msg.acceptor_id] = msg
        if len(state.phase2b_fasts) < self.config.fast_quorum_size:
            return
        dep_sets = {
            tuple(sorted(b.value[1])) for b in state.phase2b_fasts.values()
        }
        if len(dep_sets) == 1:
            # Unanimous fast path: one round trip.
            self._commit(
                msg.vertex_id,
                (state.command, next(iter(dep_sets))),
                inform_others=True,
            )
            return
        # Disagreement: this leader owns round 1 (the first classic round
        # of the rotated-round-zero-fast system) — propose the UNION.
        self.logger.check_eq(
            self._round_system(msg.vertex_id).leader(1), self.index
        )
        union = tuple(
            sorted({d for b in state.phase2b_fasts.values() for d in b.value[1]})
        )
        value = (state.command, union)
        state.resend.stop()
        phase2a = UbPhase2a(vertex_id=msg.vertex_id, round=1, vote_value=value)
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase2a)
        self.states[msg.vertex_id] = _UbPhase2Classic(
            round=1,
            value=value,
            phase2bs={},
            resend=self._make_resend(
                f"resendPhase2a{msg.vertex_id}",
                lambda: [
                    self.chan(a).send(phase2a)
                    for a in self.config.acceptor_addresses
                ],
            ),
        )
        timer = self.recover_timers.pop(msg.vertex_id, None)
        if timer is not None:
            timer.stop()

    def _handle_phase1b(self, msg: UbPhase1b) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _UbPhase1):
            return
        if msg.round != state.round:
            return
        state.phase1bs[msg.acceptor_id] = msg
        if len(state.phase1bs) < self.config.classic_quorum_size:
            return
        max_vote = max(b.vote_round for b in state.phase1bs.values())
        if max_vote == -1:
            proposal = (None, ())  # noop
        else:
            values = {
                b.vote_value
                for b in state.phase1bs.values()
                if b.vote_round == max_vote
            }
            all_voted = all(
                b.vote_round == max_vote for b in state.phase1bs.values()
            )
            if max_vote > 0:
                self.logger.check_eq(len(values), 1)
                proposal = next(iter(values))
            elif len(values) == 1 and all_voted:
                # Every sampled acceptor fast-voted the SAME value: round 0
                # may have chosen it, so it must be adopted.
                proposal = next(iter(values))
            else:
                # Divergent fast-round votes — or an ABSTENTION among the
                # sampled promises. An abstaining acceptor that promised a
                # classic round can never fast-vote, so unanimity is
                # impossible and nothing was (or can be) chosen at round 0.
                # Recover as noop: adopting the partial voters' value here
                # would also adopt their possibly-stale DEPENDENCY sets,
                # which can leave two committed conflicting commands with
                # no edge between them (divergent execution orders). The
                # command itself survives via the client's resend, which
                # gets a fresh vertex with fresh dependencies.
                proposal = (None, ())
        phase2a = UbPhase2a(
            vertex_id=msg.vertex_id, round=state.round, vote_value=proposal
        )
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase2a)
        state.resend.stop()
        self.states[msg.vertex_id] = _UbPhase2Classic(
            round=state.round,
            value=proposal,
            phase2bs={},
            resend=self._make_resend(
                f"resendPhase2a{msg.vertex_id}",
                lambda: [
                    self.chan(a).send(phase2a)
                    for a in self.config.acceptor_addresses
                ],
            ),
        )

    def _handle_phase2b_classic(self, msg: UbPhase2bClassic) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _UbPhase2Classic):
            return
        if msg.round != state.round:
            return
        state.phase2bs[msg.acceptor_id] = msg
        if len(state.phase2bs) < self.config.classic_quorum_size:
            return
        self._commit(msg.vertex_id, state.value, inform_others=True)

    def _handle_nack(self, msg: UbNack) -> None:
        self._recover(msg.vertex_id, nack_round=msg.higher_round)


class UbDepServiceNode(Actor):
    """Computes dependencies and hands its CO-LOCATED acceptor a fast
    proposal (DepServiceNode.handleDependencyRequest)."""

    def __init__(self, address, transport, logger,
                 config: UnanimousBPaxosConfig, state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.dep_service_node_addresses.index(address)
        self.acceptor = config.acceptor_addresses[self.index]
        self.conflict_index = state_machine.conflict_index()
        self.dependencies_cache: Dict[tuple, tuple] = {}

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, UbDependencyRequest):
            self.logger.fatal(f"unknown dep service message {msg!r}")
        deps = self.dependencies_cache.get(msg.vertex_id)
        if deps is None:
            conflicts = set(self.conflict_index.get_conflicts(msg.command.command))
            conflicts.discard(msg.vertex_id)
            deps = tuple(sorted(conflicts))
            self.conflict_index.put(msg.vertex_id, msg.command.command)
            self.dependencies_cache[msg.vertex_id] = deps
        self.chan(self.acceptor).send(
            UbFastProposal(
                vertex_id=msg.vertex_id, value=(msg.command, deps)
            )
        )


class UbAcceptor(Actor):
    def __init__(self, address, transport, logger,
                 config: UnanimousBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        # vertex -> [round, vote_round, vote_value]
        self.states: Dict[tuple, list] = {}

    def _leader_for(self, vertex_id: tuple) -> Address:
        return self.config.leader_addresses[vertex_id[0]]

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, UbFastProposal):
            state = self.states.setdefault(msg.vertex_id, [0, -1, None])
            if state[0] > 0:
                # A classic round already started: nack so the owner stops
                # waiting on the fast path (Acceptor.scala:155-164).
                self.chan(self._leader_for(msg.vertex_id)).send(
                    UbNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            if state[1] >= 0:
                return  # already fast-voted; duplicates are ignored
            state[1] = 0
            state[2] = msg.value
            self.chan(self._leader_for(msg.vertex_id)).send(
                UbPhase2bFast(
                    vertex_id=msg.vertex_id,
                    acceptor_id=self.index,
                    value=msg.value,
                )
            )
        elif isinstance(msg, UbPhase1a):
            state = self.states.setdefault(msg.vertex_id, [0, -1, None])
            if msg.round < state[0]:
                self.chan(src).send(
                    UbNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            self.chan(src).send(
                UbPhase1b(
                    vertex_id=msg.vertex_id,
                    acceptor_id=self.index,
                    round=msg.round,
                    vote_round=state[1],
                    vote_value=state[2],
                )
            )
        elif isinstance(msg, UbPhase2a):
            state = self.states.setdefault(msg.vertex_id, [0, -1, None])
            if msg.round < state[0]:
                self.chan(src).send(
                    UbNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            state[1] = msg.round
            state[2] = msg.vote_value
            self.chan(src).send(
                UbPhase2bClassic(
                    vertex_id=msg.vertex_id,
                    acceptor_id=self.index,
                    round=msg.round,
                )
            )
        else:
            self.logger.fatal(f"unknown ubpaxos acceptor message {msg!r}")


@dataclasses.dataclass
class _UbPending:
    id: int
    result: Promise
    resend: object


class UbClient(Actor):
    def __init__(self, address, transport, logger,
                 config: UnanimousBPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _UbPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = UbClientRequest(
            UbCommand(
                client_address=self.address_bytes,
                client_pseudonym=pseudonym,
                client_id=id,
                command=command,
            )
        )
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))
        ]
        self.chan(leader).send(request)

        def resend() -> None:
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(f"resendUb[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _UbPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, UbClientReply):
            self.logger.fatal(f"unknown ubpaxos client message {msg!r}")
        pending = self.pending.get(msg.client_pseudonym)
        if pending is None or msg.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[msg.client_pseudonym]
        pending.result.success(msg.result)
