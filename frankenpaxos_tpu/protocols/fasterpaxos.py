"""Faster Paxos — delegate-based multi-leader MultiPaxos (reference
``fasterpaxos/``; protocol cheatsheet in ``FasterPaxos.proto``).

There are only CLIENTS and 2f+1 SERVERS. In round r, the round's owner
is the LEADER; it picks f+1 DELEGATES (including itself). After phase 1,
the leader sends Phase2aAny granting the delegates the open log suffix
past ``any_watermark``; delegates round-robin-partition those slots and
accept client commands DIRECTLY — a delegate proposes in a slot it owns,
the other delegates vote, and f+1 votes choose the value without the
leader in the loop. Noop back-filling covers skipped slots; a delegate
that voted noop re-votes for a command on receipt (safe here, unlike
classic Paxos: noops only fill slots their owner will never propose a
command in), and with ``ack_noops_with_commands`` a delegate answers a
noop Phase2a for an already-commanded slot with the command's Phase2b.
All-to-all heartbeats detect dead delegates: any server noticing one
starts phase 1 in its own next round (``Server.scala:497-530``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.heartbeat import HeartbeatOptions, Participant
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, random_duration

COMMAND = "command"
NOOP = "noop"


@wire.message
@dataclasses.dataclass(frozen=True)
class FprCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FprCommand:
    command_id: FprCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class FprClientRequest:
    round: int
    command: FprCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class FprClientReply:
    command_id: FprCommandId
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase1a:
    round: int
    chosen_watermark: int
    delegates: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase1b:
    server_index: int
    round: int
    # (slot, "pending", vote_round, kind, command) or
    # (slot, "chosen", -1, kind, command)
    info: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase2a:
    slot: int
    round: int
    kind: str
    command: Optional[FprCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase2b:
    server_index: int
    slot: int
    round: int
    # ack_noops_with_commands: the non-noop value this server already
    # voted for in the slot (see module docstring).
    command: Optional[FprCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase2aAny:
    round: int
    delegates: tuple
    any_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase2aAnyAck:
    round: int
    server_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FprPhase3a:
    slot: int
    kind: str
    command: Optional[FprCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FprRoundInfo:
    round: int
    delegates: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FprNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FprRecover:
    slot: int


@dataclasses.dataclass(frozen=True)
class FasterPaxosConfig:
    f: int
    server_addresses: tuple  # 2f+1
    heartbeat_addresses: tuple  # one per server

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.server_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 servers")
        if len(self.heartbeat_addresses) != len(self.server_addresses):
            raise ValueError("one heartbeat address per server")


# -- Server -------------------------------------------------------------------


@dataclasses.dataclass
class _FprPhase1:
    round: int
    delegates: tuple  # server indices, f+1 of them
    phase1bs: Dict[int, FprPhase1b]
    pending_requests: List[FprClientRequest]
    resend: object


@dataclasses.dataclass
class _FprPhase2:
    round: int
    delegates: tuple
    delegate_index: int
    any_watermark: int
    next_slot: int
    pending_values: Dict[int, Tuple[str, Optional[FprCommand]]]
    phase2bs: Dict[int, Dict[int, FprPhase2b]]
    waiting_acks: set
    resend: object


@dataclasses.dataclass
class _FprDelegate:
    round: int
    delegates: tuple
    delegate_index: int
    any_watermark: int
    next_slot: int
    pending_values: Dict[int, Tuple[str, Optional[FprCommand]]]
    phase2bs: Dict[int, Dict[int, FprPhase2b]]


@dataclasses.dataclass
class _FprIdle:
    round: int
    delegates: tuple


# Log entries: ("pending", vote_round, kind, command) or
# ("chosen", kind, command).
@dataclasses.dataclass(frozen=True)
class FprServerOptions:
    log_grow_size: int = 5000
    resend_phase1as_period: float = 5.0
    resend_phase2a_anys_period: float = 5.0
    recover_min_period: float = 10.0
    recover_max_period: float = 20.0
    leader_change_min_period: float = 60.0
    leader_change_max_period: float = 120.0
    ack_noops_with_commands: bool = True
    use_f1_optimization: bool = True
    unsafe_dont_recover: bool = False
    heartbeat_options: HeartbeatOptions = HeartbeatOptions()


class FprServer(Actor):
    """``fasterpaxos/Server.scala``: leader, delegate, acceptor, and
    replica in one actor, switching roles per round."""

    def __init__(self, address, transport, logger, config: FasterPaxosConfig,
                 state_machine: StateMachine,
                 options: FprServerOptions = FprServerOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.server_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.server_addresses.index(address)
        self.round_system = ClassicRoundRobin(len(config.server_addresses))
        # Delegates round-robin-partition slots among the f+1 of them.
        self.slot_system = ClassicRoundRobin(config.f + 1)
        self.log: BufferMap = BufferMap(options.log_grow_size)
        self.executed_watermark = 0
        self.num_chosen = 0
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}
        self.heartbeat = Participant(
            config.heartbeat_addresses[self.index], transport, logger,
            [a for a in config.heartbeat_addresses
             if a != config.heartbeat_addresses[self.index]],
            options=options.heartbeat_options,
        )

        def recover() -> None:
            recover_msg = FprRecover(slot=self.executed_watermark)
            for a in self.config.server_addresses:
                if a != self.address:
                    self.chan(a).send(recover_msg)
            self.recover_timer.start()

        self.recover_timer = self.timer(
            "recover",
            random_duration(self.rng, options.recover_min_period,
                            options.recover_max_period),
            recover,
        )

        def leader_change() -> None:
            self.check_delegates_alive()
            self.leader_change_timer.start()

        self.leader_change_timer = self.timer(
            "leaderChange",
            random_duration(self.rng, options.leader_change_min_period,
                            options.leader_change_max_period),
            leader_change,
        )
        self.leader_change_timer.start()

        initial_delegates = tuple(range(config.f + 1))
        self.state: object = _FprIdle(round=0, delegates=initial_delegates)
        if self.index == 0:
            self.start_phase1(0, initial_delegates)

    # -- Helpers -------------------------------------------------------------

    def _round_info(self) -> Tuple[int, tuple]:
        return self.state.round, self.state.delegates

    def _stop_timers(self, state) -> None:
        if isinstance(state, _FprPhase1):
            state.resend.stop()
        elif isinstance(state, _FprPhase2):
            state.resend.stop()

    def pick_delegates(self) -> tuple:
        """Ourselves plus f servers that look alive
        (Server.scala:609-618)."""
        alive = self.heartbeat.unsafe_alive()
        alive_indices = [
            i for i, a in enumerate(self.config.heartbeat_addresses)
            if a in alive and i != self.index
        ]
        self.rng.shuffle(alive_indices)
        others = alive_indices[: self.config.f]
        # Fall back to arbitrary servers if too few look alive.
        i = 0
        while len(others) < self.config.f:
            if i != self.index and i not in others:
                others.append(i)
            i += 1
        return tuple([self.index] + sorted(others))

    def check_delegates_alive(self) -> None:
        """If any delegate looks dead, grab leadership in our next round
        (Server.scala:497-530)."""
        round, delegates = self._round_info()
        delegate_addresses = {
            self.config.heartbeat_addresses[i] for i in delegates
        }
        alive = self.heartbeat.unsafe_alive() | {
            self.config.heartbeat_addresses[self.index]
        }
        if not delegate_addresses <= alive:
            self._stop_timers(self.state)
            self.start_phase1(
                self.round_system.next_classic_round(self.index, round),
                self.pick_delegates(),
            )

    def _get_next_slot(self, delegate_index: int, slot: int) -> int:
        next_slot = self.slot_system.next_classic_round(delegate_index, slot)
        while self.log.get(next_slot) is not None:
            next_slot = self.slot_system.next_classic_round(
                delegate_index, next_slot
            )
        return next_slot

    def _choose(self, slot: int, kind: str,
                command: Optional[FprCommand]) -> None:
        entry = self.log.get(slot)
        if entry is None or entry[0] == "pending":
            self.num_chosen += 1
            self.log.put(slot, ("chosen", kind, command))
        else:
            self.logger.check_eq(entry[1:], (kind, command))
        state = self.state
        if isinstance(state, (_FprPhase2, _FprDelegate)):
            if slot == state.next_slot:
                state.next_slot = self._get_next_slot(
                    state.delegate_index, slot
                )
            state.pending_values.pop(slot, None)
            state.phase2bs.pop(slot, None)

    def _owns_slot(self, state, slot: int) -> bool:
        if isinstance(state, _FprPhase2):
            return (
                slot < state.any_watermark
                or self.slot_system.leader(slot) == state.delegate_index
            )
        if isinstance(state, _FprDelegate):
            return (
                slot >= state.any_watermark
                and self.slot_system.leader(slot) == state.delegate_index
            )
        return False

    def _log_info(self, start: int) -> tuple:
        info = []
        for slot in range(start, self.log.largest_key + 1):
            entry = self.log.get(slot)
            if entry is None:
                continue
            if entry[0] == "pending":
                info.append((slot, "pending", entry[1], entry[2], entry[3]))
            else:
                info.append((slot, "chosen", -1, entry[1], entry[2]))
        return tuple(info)

    def start_phase1(self, round: int, delegates: tuple) -> None:
        phase1a = FprPhase1a(
            round=round, chosen_watermark=self.executed_watermark,
            delegates=delegates,
        )

        def send() -> None:
            for a in self.config.server_addresses:
                if a != self.address:
                    self.chan(a).send(phase1a)

        send()

        def resend() -> None:
            send()
            timer.start()

        timer = self.timer(
            f"resendPhase1as{round}", self.options.resend_phase1as_period,
            resend,
        )
        timer.start()
        # Answer our own phase 1a.
        phase1b = FprPhase1b(
            server_index=self.index, round=round,
            info=self._log_info(self.executed_watermark),
        )
        self.state = _FprPhase1(
            round=round, delegates=delegates,
            phase1bs={self.index: phase1b},
            pending_requests=[], resend=timer,
        )

    def _propose_single(self, state, slot: int, kind: str,
                        command: Optional[FprCommand]) -> None:
        """Vote for (kind, command) in slot ourselves and Phase2a the
        other delegates (Server.scala:728-767)."""
        self.logger.check(self.log.get(slot) is None)
        phase2a = FprPhase2a(
            slot=slot, round=state.round, kind=kind, command=command
        )
        for i in state.delegates:
            if i != self.index:
                self.chan(self.config.server_addresses[i]).send(phase2a)
        self.log.put(slot, ("pending", state.round, kind, command))
        state.pending_values[slot] = (kind, command)
        state.phase2bs[slot] = {
            self.index: FprPhase2b(
                server_index=self.index, slot=slot, round=state.round
            )
        }

    def _propose(self, state, kind: str,
                 command: Optional[FprCommand]) -> None:
        """Noop-fill the covered gap then propose in our next owned slot
        (Server.scala:808-856)."""
        slot = state.next_slot
        for previous in range(
            max(state.any_watermark, slot - len(state.delegates) + 1), slot
        ):
            if self.log.get(previous) is None:
                self._propose_single(state, previous, NOOP, None)
        self._propose_single(state, slot, kind, command)
        state.next_slot = self._get_next_slot(state.delegate_index, slot)

    def _repropose_single(self, state, slot: int) -> None:
        """Re-drive a slot we own: resend our pending value, or propose a
        noop if we have nothing (Server.scala:768-807). NOTE: unlike
        _propose_single, the log may already hold a PENDING entry here —
        we may have voted for another delegate's noop-fill without being
        the proposer — and overwriting it with our own same-round noop
        proposal is exactly what the reference does."""
        pending = state.pending_values.get(slot)
        if pending is None:
            phase2a = FprPhase2a(
                slot=slot, round=state.round, kind=NOOP, command=None
            )
            for i in state.delegates:
                if i != self.index:
                    self.chan(self.config.server_addresses[i]).send(phase2a)
            self.log.put(slot, ("pending", state.round, NOOP, None))
            state.pending_values[slot] = (NOOP, None)
            state.phase2bs[slot] = {
                self.index: FprPhase2b(
                    server_index=self.index, slot=slot, round=state.round
                )
            }
        else:
            phase2a = FprPhase2a(
                slot=slot, round=state.round, kind=pending[0],
                command=pending[1],
            )
            for i in state.delegates:
                if i != self.index:
                    self.chan(self.config.server_addresses[i]).send(phase2a)

    def _execute_command(self, command: FprCommand,
                         reply: bool) -> None:
        cid = command.command_id
        identity = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(identity)
        client = self.transport.address_from_bytes(cid.client_address)
        if cached is not None:
            if cid.client_id < cached[0]:
                return
            if cid.client_id == cached[0]:
                # Always resend the cached reply, for liveness.
                self.chan(client).send(
                    FprClientReply(command_id=cid, result=cached[1])
                )
                return
        result = self.state_machine.run(command.command)
        self.client_table[identity] = (cid.client_id, result)
        if reply:
            self.chan(client).send(
                FprClientReply(command_id=cid, result=result)
            )

    def _execute_log(self, reply_if) -> None:
        while True:
            entry = self.log.get(self.executed_watermark)
            if entry is None or entry[0] == "pending":
                if (
                    not self.options.unsafe_dont_recover
                    and self.num_chosen != self.executed_watermark
                ):
                    self.recover_timer.start()
                return
            slot = self.executed_watermark
            self.executed_watermark += 1
            self.recover_timer.stop()
            _, kind, command = entry
            if kind == COMMAND:
                self._execute_command(command, reply_if(slot))

    def _process_phase2b(self, state, msg: FprPhase2b) -> None:
        entry = self.log.get(msg.slot)
        self.logger.check(entry is not None)
        if entry[0] == "chosen":
            return
        if msg.slot not in state.phase2bs or msg.slot not in state.pending_values:
            return  # duplicate delivery after the slot was resolved
        if not self.options.ack_noops_with_commands:
            state.phase2bs[msg.slot][msg.server_index] = msg
        else:
            pending = state.pending_values[msg.slot]
            owns = self._owns_slot(state, msg.slot)
            if owns and pending[0] == COMMAND and msg.command is not None:
                self.logger.fatal("nack for an owned slot is impossible")
            elif (
                (owns and pending[0] == COMMAND and msg.command is None)
                or (not owns and pending[0] == COMMAND
                    and msg.command is not None)
                or (pending[0] == NOOP and msg.command is None)
            ):
                state.phase2bs[msg.slot][msg.server_index] = msg
            elif not owns and pending[0] == COMMAND and msg.command is None:
                # A Phase2b for our older noop, not the newer command.
                return
            else:
                # We proposed a noop; another delegate already voted a
                # command there. Switch to the command and start over.
                command = msg.command
                self.log.put(
                    msg.slot, ("pending", msg.round, COMMAND, command)
                )
                state.pending_values[msg.slot] = (COMMAND, command)
                state.phase2bs[msg.slot] = {
                    msg.server_index: msg,
                    self.index: FprPhase2b(
                        server_index=self.index, slot=msg.slot,
                        round=msg.round,
                    ),
                }
        if len(state.phase2bs[msg.slot]) < self.config.f + 1:
            return
        kind, command = state.pending_values[msg.slot]
        self._choose(msg.slot, kind, command)
        phase3a = FprPhase3a(slot=msg.slot, kind=kind, command=command)
        for a in self.config.server_addresses:
            if a != self.address:
                self.chan(a).send(phase3a)
        self._execute_log(lambda slot: self._owns_slot(self.state, slot))

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FprClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, FprPhase1a):
            self._handle_phase1a(src, msg)
        elif isinstance(msg, FprPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, FprPhase2a):
            self._handle_phase2a(src, msg)
        elif isinstance(msg, FprPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, FprPhase2aAny):
            self._handle_phase2a_any(src, msg)
        elif isinstance(msg, FprPhase2aAnyAck):
            self._handle_phase2a_any_ack(msg)
        elif isinstance(msg, FprPhase3a):
            self._choose(msg.slot, msg.kind, msg.command)
            self._execute_log(lambda slot: self._owns_slot(self.state, slot))
        elif isinstance(msg, FprRecover):
            self._handle_recover(src, msg)
        elif isinstance(msg, FprNack):
            self._handle_nack(msg)
        else:
            self.logger.fatal(f"unknown fasterpaxos server message {msg!r}")

    def _handle_client_request(self, src: Address,
                               msg: FprClientRequest) -> None:
        cid = msg.command.command_id
        identity = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(identity)
        if cached is not None:
            if cid.client_id < cached[0]:
                return
            if cid.client_id == cached[0]:
                self.chan(src).send(
                    FprClientReply(command_id=cid, result=cached[1])
                )
                return
        round, delegates = self._round_info()
        if msg.round < round:
            self.chan(src).send(
                FprRoundInfo(round=round, delegates=delegates)
            )
            return
        if msg.round > round:
            return
        state = self.state
        if isinstance(state, _FprPhase1):
            state.pending_requests.append(msg)
        elif isinstance(state, (_FprPhase2, _FprDelegate)):
            self._propose(state, COMMAND, msg.command)
        else:
            # Idle in the same round as the client: the client should only
            # talk to delegates; tell it who they are.
            self.chan(src).send(
                FprRoundInfo(round=round, delegates=delegates)
            )

    def _handle_phase1a(self, src: Address, msg: FprPhase1a) -> None:
        round, _ = self._round_info()
        if msg.round < round:
            self.chan(src).send(FprNack(round=round))
            return
        if msg.round == round:
            if not isinstance(self.state, _FprIdle):
                return  # stale or impossible (Server.scala:1306-1333)
        else:
            self._stop_timers(self.state)
            self.state = _FprIdle(
                round=msg.round, delegates=tuple(msg.delegates)
            )
        self.chan(src).send(
            FprPhase1b(
                server_index=self.index, round=self.state.round,
                info=self._log_info(msg.chosen_watermark),
            )
        )

    def _safe_value(self, infos) -> Tuple[str, Tuple[str, Optional[FprCommand]]]:
        """("safe"|"chosen", value) per Server.scala:861-901."""
        if not infos:
            return ("safe", (NOOP, None))
        for info in infos:
            if info[1] == "chosen":
                return ("chosen", (info[3], info[4]))
        largest = max(info[2] for info in infos)
        for info in infos:
            if info[2] == largest and info[3] == COMMAND:
                return ("safe", (COMMAND, info[4]))
        return ("safe", (NOOP, None))

    def _handle_phase1b(self, msg: FprPhase1b) -> None:
        state = self.state
        if not isinstance(state, _FprPhase1) or msg.round != state.round:
            return
        state.phase1bs[msg.server_index] = msg
        if len(state.phase1bs) < self.config.f + 1:
            return
        state.resend.stop()
        round = state.round
        infos_by_slot: Dict[int, list] = {}
        for b in state.phase1bs.values():
            for info in b.info:
                infos_by_slot.setdefault(info[0], []).append(info)
        max_slot = max(infos_by_slot, default=-1)
        pending_values: Dict[int, Tuple[str, Optional[FprCommand]]] = {}
        phase2bs: Dict[int, Dict[int, FprPhase2b]] = {}
        for slot in range(self.executed_watermark, max_slot + 1):
            entry = self.log.get(slot)
            if entry is not None and entry[0] == "chosen":
                continue  # a Phase3a landed while we ran phase 1
            status, value = self._safe_value(infos_by_slot.get(slot, []))
            if status == "chosen":
                self._choose(slot, value[0], value[1])
                continue
            phase2a = FprPhase2a(
                slot=slot, round=round, kind=value[0], command=value[1]
            )
            for a in self.config.server_addresses:
                if a != self.address:
                    self.chan(a).send(phase2a)
            self.log.put(slot, ("pending", round, value[0], value[1]))
            pending_values[slot] = value
            phase2bs[slot] = {
                self.index: FprPhase2b(
                    server_index=self.index, slot=slot, round=round
                )
            }
        self._execute_log(lambda slot: False)
        slot = max_slot
        # Propose the buffered client requests right after max_slot,
        # skipping any slots a concurrent Phase3a already chose.
        for request in state.pending_requests:
            slot += 1
            while (entry := self.log.get(slot)) is not None \
                    and entry[0] == "chosen":
                slot += 1
            value = (COMMAND, request.command)
            phase2a = FprPhase2a(
                slot=slot, round=round, kind=COMMAND, command=request.command
            )
            for a in self.config.server_addresses:
                if a != self.address:
                    self.chan(a).send(phase2a)
            self.log.put(slot, ("pending", round, COMMAND, request.command))
            pending_values[slot] = value
            phase2bs[slot] = {
                self.index: FprPhase2b(
                    server_index=self.index, slot=slot, round=round
                )
            }
        # Hand the open log suffix to the delegates.
        any_watermark = max(max_slot, slot) + 1
        phase2a_any = FprPhase2aAny(
            round=round, delegates=state.delegates,
            any_watermark=any_watermark,
        )

        def send_anys() -> None:
            for i in state.delegates:
                if i != self.index:
                    self.chan(self.config.server_addresses[i]).send(
                        phase2a_any
                    )

        send_anys()

        def resend() -> None:
            send_anys()
            timer.start()

        timer = self.timer(
            f"resendPhase2aAnys{round}",
            self.options.resend_phase2a_anys_period, resend,
        )
        timer.start()
        delegate_index = state.delegates.index(self.index)
        self.state = _FprPhase2(
            round=round, delegates=state.delegates,
            delegate_index=delegate_index,
            any_watermark=any_watermark,
            next_slot=self._get_next_slot(delegate_index, any_watermark - 1),
            pending_values=pending_values, phase2bs=phase2bs,
            waiting_acks={i for i in state.delegates if i != self.index},
            resend=timer,
        )

    def _handle_phase2a(self, src: Address, msg: FprPhase2a) -> None:
        round, _ = self._round_info()
        if msg.round < round:
            self.chan(src).send(FprNack(round=round))
            return
        if msg.round > round:
            return  # wait for the Phase2aAny (Server.scala:1519-1533)
        state = self.state
        # DELIBERATE divergence from Server.scala:1536-1540, which treats a
        # same-round Phase2a at a Phase1/Idle server as impossible: the
        # new leader's phase-1 REPAIR proposals go to arbitrary servers,
        # which are Idle until the Phase2aAny arrives. Voting while Idle is
        # always safe — acceptors need no delegate state.
        phase2b = FprPhase2b(
            server_index=self.index, slot=msg.slot, round=round
        )
        entry = self.log.get(msg.slot)
        if entry is not None and entry[0] == "chosen":
            self.chan(src).send(
                FprPhase3a(slot=msg.slot, kind=entry[1], command=entry[2])
            )
        elif entry is None or entry[2] == NOOP:
            # Nothing voted, or noop voted: vote for what we received
            # (re-voting a command over our noop is safe in Faster Paxos).
            if self.config.f == 1 and self.options.use_f1_optimization:
                self._choose(msg.slot, msg.kind, msg.command)
                self._execute_log(
                    lambda slot: self._owns_slot(self.state, slot)
                )
            else:
                self.log.put(
                    msg.slot, ("pending", round, msg.kind, msg.command)
                )
            self.chan(src).send(phase2b)
        else:
            # We voted for a command.
            if msg.kind == COMMAND:
                self.logger.check_eq(msg.command, entry[3])
                self.chan(src).send(phase2b)
            elif self.options.ack_noops_with_commands:
                # Answer the noop with our command's Phase2b.
                self.chan(src).send(
                    FprPhase2b(
                        server_index=self.index, slot=msg.slot, round=round,
                        command=entry[3],
                    )
                )
        if isinstance(state, (_FprPhase2, _FprDelegate)):
            if msg.slot == state.next_slot:
                state.next_slot = self._get_next_slot(
                    state.delegate_index, msg.slot
                )

    def _handle_phase2b(self, msg: FprPhase2b) -> None:
        round, _ = self._round_info()
        if msg.round < round:
            return
        self.logger.check_eq(msg.round, round)
        state = self.state
        if not isinstance(state, (_FprPhase2, _FprDelegate)):
            self.logger.fatal("Phase2b while Phase1/Idle")
        if msg.slot not in state.phase2bs:
            entry = self.log.get(msg.slot)
            if entry is not None and entry[0] == "chosen":
                return
        self._process_phase2b(state, msg)

    def _handle_phase2a_any(self, src: Address, msg: FprPhase2aAny) -> None:
        round, _ = self._round_info()
        if msg.round < round:
            return
        state = self.state
        if isinstance(state, _FprDelegate) and msg.round == round:
            self.chan(src).send(
                FprPhase2aAnyAck(round=round, server_index=self.index)
            )
            return
        self._stop_timers(state)
        delegate_index = msg.delegates.index(self.index)
        self.state = _FprDelegate(
            round=msg.round, delegates=tuple(msg.delegates),
            delegate_index=delegate_index,
            any_watermark=msg.any_watermark,
            next_slot=self._get_next_slot(
                delegate_index, msg.any_watermark - 1
            ),
            pending_values={}, phase2bs={},
        )
        self.chan(src).send(
            FprPhase2aAnyAck(round=msg.round, server_index=self.index)
        )

    def _handle_phase2a_any_ack(self, msg: FprPhase2aAnyAck) -> None:
        round, _ = self._round_info()
        if msg.round != round:
            return
        state = self.state
        if not isinstance(state, _FprPhase2):
            return
        state.waiting_acks.discard(msg.server_index)
        if not state.waiting_acks:
            state.resend.stop()

    def _handle_recover(self, src: Address, msg: FprRecover) -> None:
        entry = self.log.get(msg.slot)
        if entry is not None and entry[0] == "chosen":
            self.chan(src).send(
                FprPhase3a(slot=msg.slot, kind=entry[1], command=entry[2])
            )
            return
        state = self.state
        if not isinstance(state, (_FprPhase2, _FprDelegate)):
            return
        if not self._owns_slot(state, msg.slot):
            return
        if msg.slot > state.next_slot:
            return
        self._repropose_single(state, msg.slot)
        if msg.slot == state.next_slot:
            state.next_slot = self._get_next_slot(
                state.delegate_index, state.next_slot
            )

    def _handle_nack(self, msg: FprNack) -> None:
        round, _ = self._round_info()
        if msg.round <= round:
            return
        self._stop_timers(self.state)
        self.start_phase1(
            self.round_system.next_classic_round(self.index, msg.round),
            self.pick_delegates(),
        )


# -- Client -------------------------------------------------------------------


@dataclasses.dataclass
class _FprPending:
    id: int
    command: bytes
    result: Promise
    resend: object


class FprClient(Actor):
    """``fasterpaxos/Client.scala``: sends to a random delegate of the
    round it believes current; RoundInfo refreshes round + delegates."""

    def __init__(self, address, transport, logger,
                 config: FasterPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.round = 0
        self.delegates: tuple = tuple(range(config.f + 1))
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _FprPending] = {}

    def _request(self, pseudonym: int, pending: _FprPending):
        return FprClientRequest(
            round=self.round,
            command=FprCommand(
                command_id=FprCommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=pending.id,
                ),
                command=pending.command,
            ),
        )

    def _send(self, pseudonym: int, pending: _FprPending) -> None:
        delegate = self.delegates[self.rng.randrange(len(self.delegates))]
        self.chan(self.config.server_addresses[delegate]).send(
            self._request(pseudonym, pending)
        )

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1

        def resend() -> None:
            pending = self.pending.get(pseudonym)
            if pending is not None:
                # Broadcast: our round/delegate guess may be stale.
                request = self._request(pseudonym, pending)
                for a in self.config.server_addresses:
                    self.chan(a).send(request)
            timer.start()

        timer = self.timer(f"resendFpr{pseudonym}", self.resend_period, resend)
        timer.start()
        pending = _FprPending(
            id=id, command=command, result=promise, resend=timer
        )
        self.pending[pseudonym] = pending
        self._send(pseudonym, pending)
        return promise

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FprClientReply):
            pending = self.pending.get(msg.command_id.client_pseudonym)
            if pending is None or msg.command_id.client_id != pending.id:
                return
            pending.resend.stop()
            del self.pending[msg.command_id.client_pseudonym]
            pending.result.success(msg.result)
        elif isinstance(msg, FprRoundInfo):
            if msg.round <= self.round:
                return
            self.round = msg.round
            self.delegates = tuple(msg.delegates)
            for pseudonym, pending in self.pending.items():
                self._send(pseudonym, pending)
                pending.resend.reset()
        else:
            self.logger.fatal(f"unknown fasterpaxos client message {msg!r}")
