"""Simple BPaxos — modular, disaggregated EPaxos (reference
``simplebpaxos/``; NSDI '21 "Bipartisan Paxos"): the roles EPaxos fuses
into one replica are separate actors.

  * Leader: assigns a vertex id, collects dependencies from f+1 of 2f+1
    DepServiceNodes, unions them, hands off to its co-located Proposer
    (``simplebpaxos/Leader.scala``).
  * DepServiceNode: conflict-index lookup per command, with a
    per-vertex cache so retransmits get identical answers
    (``DepServiceNode.scala:152-215``).
  * Proposer: per-vertex Paxos over the acceptors. Round 0 belongs to the
    vertex's own leader (RotatedClassicRoundRobin), so the first proposal
    skips phase 1 (``Proposer.scala:155-195``). On Recover from a replica
    it proposes a noop for the stuck vertex.
  * Acceptor: per-vertex (round, voteRound, voteValue)
    (``Acceptor.scala``).
  * Replica: commits (command, deps) vertices into a dependency graph and
    executes eligible components, with client table and recover timers
    (``Replica.scala``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, FrozenSet, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.clienttable import ClientTable, Executed
from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.roundsystem import RotatedClassicRoundRobin
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import random_duration

# Vertex ids are (leader_index, id) tuples.


@wire.message
@dataclasses.dataclass(frozen=True)
class BpCommand:
    client_address: bytes
    client_pseudonym: int
    client_id: int
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class BpClientRequest:
    command: BpCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class BpClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class BpDependencyRequest:
    vertex_id: tuple
    command: BpCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class BpDependencyReply:
    vertex_id: tuple
    dep_service_node_index: int
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class BpPropose:
    vertex_id: tuple
    command: BpCommand
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class BpPhase1a:
    vertex_id: tuple
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BpPhase1b:
    vertex_id: tuple
    acceptor_id: int
    round: int
    vote_round: int
    vote_value: Optional[tuple]  # (command|None, dependencies)


@wire.message
@dataclasses.dataclass(frozen=True)
class BpPhase2a:
    vertex_id: tuple
    round: int
    vote_value: tuple  # (command|None, dependencies)


@wire.message
@dataclasses.dataclass(frozen=True)
class BpPhase2b:
    vertex_id: tuple
    acceptor_id: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BpCommit:
    vertex_id: tuple
    value: tuple  # (command|None, dependencies)


@wire.message
@dataclasses.dataclass(frozen=True)
class BpNack:
    vertex_id: tuple
    higher_round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BpRecover:
    vertex_id: tuple


@dataclasses.dataclass(frozen=True)
class SimpleBPaxosConfig:
    f: int
    leader_addresses: tuple
    proposer_addresses: tuple
    dep_service_node_addresses: tuple
    acceptor_addresses: tuple
    replica_addresses: tuple

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.proposer_addresses) != len(self.leader_addresses):
            raise ValueError("one proposer per leader")
        if len(self.dep_service_node_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 dep service nodes")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


class BpLeader(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig,
                 resend_period: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.index = config.leader_addresses.index(address)
        self.proposer = config.proposer_addresses[self.index]
        self.next_vertex_id = 0
        # vertex -> dict of dep replies, or "proposed"
        self.states: Dict[tuple, object] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, BpClientRequest):
            self._handle_client_request(msg)
        elif isinstance(msg, BpDependencyReply):
            self._handle_dependency_reply(msg)
        else:
            self.logger.fatal(f"unknown bpaxos leader message {msg!r}")

    def _handle_client_request(self, msg: BpClientRequest) -> None:
        vertex_id = (self.index, self.next_vertex_id)
        self.next_vertex_id += 1
        request = BpDependencyRequest(vertex_id=vertex_id, command=msg.command)
        nodes = self.config.dep_service_node_addresses
        quorum = [
            nodes[i]
            for i in self.rng.sample(range(len(nodes)), self.config.quorum_size)
        ]
        for node in quorum:
            self.chan(node).send(request)

        def resend() -> None:
            for node in self.config.dep_service_node_addresses:
                self.chan(node).send(request)
            timer.start()

        timer = self.timer(
            f"resendDeps{vertex_id}", self.resend_period, resend
        )
        timer.start()
        self.states[vertex_id] = {"command": msg.command, "replies": {},
                                  "timer": timer}

    def _handle_dependency_reply(self, msg: BpDependencyReply) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, dict):
            return
        state["replies"][msg.dep_service_node_index] = msg
        if len(state["replies"]) < self.config.quorum_size:
            return
        dependencies = frozenset(
            d for reply in state["replies"].values() for d in reply.dependencies
        )
        state["timer"].stop()
        self.chan(self.proposer).send(
            BpPropose(
                vertex_id=msg.vertex_id,
                command=state["command"],
                dependencies=tuple(sorted(dependencies)),
            )
        )
        self.states[msg.vertex_id] = "proposed"


class BpDepServiceNode(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig,
                 state_machine: StateMachine):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.dep_service_node_addresses)
        self.config = config
        self.index = config.dep_service_node_addresses.index(address)
        self.conflict_index = state_machine.conflict_index()
        # Retransmitted requests must get IDENTICAL dependencies
        # (DepServiceNode.scala dependenciesCache).
        self.dependencies_cache: Dict[tuple, tuple] = {}

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BpDependencyRequest):
            self.logger.fatal(f"unknown dep service message {msg!r}")
        deps = self.dependencies_cache.get(msg.vertex_id)
        if deps is None:
            conflicts = set(self.conflict_index.get_conflicts(msg.command.command))
            conflicts.discard(msg.vertex_id)
            deps = tuple(sorted(conflicts))
            self.conflict_index.put(msg.vertex_id, msg.command.command)
            self.dependencies_cache[msg.vertex_id] = deps
        self.chan(src).send(
            BpDependencyReply(
                vertex_id=msg.vertex_id,
                dep_service_node_index=self.index,
                dependencies=deps,
            )
        )


@dataclasses.dataclass
class _BpPhase1:
    round: int
    value: tuple
    phase1bs: Dict[int, BpPhase1b]
    resend: object


@dataclasses.dataclass
class _BpPhase2:
    round: int
    value: tuple
    phase2bs: Dict[int, BpPhase2b]
    resend: object


@dataclasses.dataclass
class _BpChosen:
    value: tuple


class BpProposer(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig,
                 resend_period: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.proposer_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.index = config.proposer_addresses.index(address)
        self.states: Dict[tuple, object] = {}

    def _round_system(self, vertex_id: tuple):
        # Round 0 of a vertex belongs to the vertex's own leader
        # (Proposer.scala roundSystem).
        return RotatedClassicRoundRobin(
            len(self.config.leader_addresses), vertex_id[0]
        )

    def _thrifty_acceptors(self, n: int):
        acceptors = self.config.acceptor_addresses
        return [
            acceptors[i] for i in self.rng.sample(range(len(acceptors)), n)
        ]

    def _make_resend(self, name, msg):
        def fire() -> None:
            for a in self.config.acceptor_addresses:
                self.chan(a).send(msg)
            timer.start()

        timer = self.timer(name, self.resend_period, fire)
        timer.start()
        return timer

    def _propose_impl(self, vertex_id, command: Optional[BpCommand],
                      dependencies: tuple) -> None:
        if vertex_id in self.states:
            return
        value = (command, dependencies)
        round = self._round_system(vertex_id).next_classic_round(self.index, -1)
        if round == 0:
            phase2a = BpPhase2a(vertex_id=vertex_id, round=0, vote_value=value)
            for a in self._thrifty_acceptors(self.config.quorum_size):
                self.chan(a).send(phase2a)
            self.states[vertex_id] = _BpPhase2(
                round=0, value=value, phase2bs={},
                resend=self._make_resend(f"resendPhase2a{vertex_id}", phase2a),
            )
        else:
            phase1a = BpPhase1a(vertex_id=vertex_id, round=round)
            for a in self._thrifty_acceptors(self.config.quorum_size):
                self.chan(a).send(phase1a)
            self.states[vertex_id] = _BpPhase1(
                round=round, value=value, phase1bs={},
                resend=self._make_resend(f"resendPhase1a{vertex_id}", phase1a),
            )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, BpPropose):
            self._propose_impl(msg.vertex_id, msg.command, msg.dependencies)
        elif isinstance(msg, BpPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, BpPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, BpNack):
            self._handle_nack(msg)
        elif isinstance(msg, BpRecover):
            self._handle_recover(msg)
        else:
            self.logger.fatal(f"unknown proposer message {msg!r}")

    def _handle_phase1b(self, msg: BpPhase1b) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _BpPhase1):
            return
        if msg.round != state.round:
            return
        state.phase1bs[msg.acceptor_id] = msg
        if len(state.phase1bs) < self.config.quorum_size:
            return
        max_vote = max(b.vote_round for b in state.phase1bs.values())
        if max_vote == -1:
            proposal = state.value
        else:
            proposal = next(
                b.vote_value
                for b in state.phase1bs.values()
                if b.vote_round == max_vote
            )
        phase2a = BpPhase2a(
            vertex_id=msg.vertex_id, round=state.round, vote_value=proposal
        )
        for a in self._thrifty_acceptors(self.config.quorum_size):
            self.chan(a).send(phase2a)
        state.resend.stop()
        self.states[msg.vertex_id] = _BpPhase2(
            round=state.round, value=proposal, phase2bs={},
            resend=self._make_resend(f"resendPhase2a{msg.vertex_id}", phase2a),
        )

    def _handle_phase2b(self, msg: BpPhase2b) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _BpPhase2):
            return
        if msg.round != state.round:
            return
        state.phase2bs[msg.acceptor_id] = msg
        if len(state.phase2bs) < self.config.quorum_size:
            return
        state.resend.stop()
        self.states[msg.vertex_id] = _BpChosen(state.value)
        commit = BpCommit(vertex_id=msg.vertex_id, value=state.value)
        for replica in self.config.replica_addresses:
            self.chan(replica).send(commit)

    def _handle_nack(self, msg: BpNack) -> None:
        state = self.states.get(msg.vertex_id)
        if state is None or isinstance(state, _BpChosen):
            return
        if msg.higher_round <= state.round:
            return
        value = state.value
        state.resend.stop()
        round = self._round_system(msg.vertex_id).next_classic_round(
            self.index, msg.higher_round
        )
        phase1a = BpPhase1a(vertex_id=msg.vertex_id, round=round)
        for a in self._thrifty_acceptors(self.config.quorum_size):
            self.chan(a).send(phase1a)
        self.states[msg.vertex_id] = _BpPhase1(
            round=round, value=value, phase1bs={},
            resend=self._make_resend(f"resendPhase1a{msg.vertex_id}", phase1a),
        )

    def _handle_recover(self, msg: BpRecover) -> None:
        state = self.states.get(msg.vertex_id)
        if isinstance(state, _BpChosen):
            # Already chosen: re-broadcast the commit.
            commit = BpCommit(vertex_id=msg.vertex_id, value=state.value)
            for replica in self.config.replica_addresses:
                self.chan(replica).send(commit)
            return
        if state is not None:
            return  # already proposing
        # Propose a noop to fill the stuck vertex (Proposer.handleRecover).
        self._propose_impl(msg.vertex_id, None, ())


class BpAcceptor(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        # vertex -> [round, vote_round, vote_value]
        self.states: Dict[tuple, list] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, BpPhase1a):
            state = self.states.setdefault(msg.vertex_id, [-1, -1, None])
            # Strictly less only (Acceptor.scala:125): an EQUAL round must
            # re-send the Phase1b, or a lost reply could never be recovered
            # by the proposer's resend timer.
            if msg.round < state[0]:
                self.chan(src).send(
                    BpNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            self.chan(src).send(
                BpPhase1b(
                    vertex_id=msg.vertex_id, acceptor_id=self.index,
                    round=msg.round, vote_round=state[1], vote_value=state[2],
                )
            )
        elif isinstance(msg, BpPhase2a):
            state = self.states.setdefault(msg.vertex_id, [-1, -1, None])
            if msg.round < state[0]:
                self.chan(src).send(
                    BpNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            state[1] = msg.round
            state[2] = msg.vote_value
            self.chan(src).send(
                BpPhase2b(
                    vertex_id=msg.vertex_id, acceptor_id=self.index,
                    round=msg.round,
                )
            )
        else:
            self.logger.fatal(f"unknown bpaxos acceptor message {msg!r}")


class BpReplica(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig,
                 state_machine: StateMachine,
                 recover_min_period: float = 5.0,
                 recover_max_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.recover_min_period = recover_min_period
        self.recover_max_period = recover_max_period
        self.index = config.replica_addresses.index(address)
        self.dependency_graph = TarjanDependencyGraph()
        self.client_table: ClientTable = ClientTable()
        self.committed: Dict[tuple, tuple] = {}
        self.recover_timers: Dict[tuple, object] = {}

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BpCommit):
            self.logger.fatal(f"unknown bpaxos replica message {msg!r}")
        if msg.vertex_id in self.committed:
            return
        self.committed[msg.vertex_id] = msg.value
        timer = self.recover_timers.pop(msg.vertex_id, None)
        if timer is not None:
            timer.stop()
        command, dependencies = msg.value
        self.dependency_graph.commit(msg.vertex_id, 0, set(dependencies))
        executables, blockers = self.dependency_graph.execute()
        for vertex in blockers:
            if vertex not in self.recover_timers:
                self.recover_timers[vertex] = self._make_recover_timer(vertex)
        for vertex in executables:
            self._execute(vertex)

    def _make_recover_timer(self, vertex_id: tuple):
        def fire() -> None:
            # Ask the vertex's own proposer first; any proposer can recover.
            proposer = self.config.proposer_addresses[
                self.rng.randrange(len(self.config.proposer_addresses))
            ]
            self.chan(proposer).send(BpRecover(vertex_id=vertex_id))
            timer.start()

        timer = self.timer(
            f"recoverVertex{vertex_id}",
            random_duration(
                self.rng, self.recover_min_period, self.recover_max_period
            ),
            fire,
        )
        timer.start()
        return timer

    def _execute(self, vertex_id: tuple) -> None:
        command, _ = self.committed[vertex_id]
        if command is None:
            return  # noop
        identity = (command.client_address, command.client_pseudonym)
        executed = self.client_table.executed(identity, command.client_id)
        if isinstance(executed, Executed):
            # A client retransmit got a fresh vertex for an already-executed
            # command (there is no leader-side dedup in SimpleBPaxos): don't
            # re-execute, but DO resend the cached reply — the original
            # striped reply may be the very message that was lost.
            if (
                executed.output is not None
                and hash(vertex_id) % len(self.config.replica_addresses)
                == self.index
            ):
                client = self.transport.address_from_bytes(
                    command.client_address
                )
                self.chan(client).send(
                    BpClientReply(
                        client_pseudonym=command.client_pseudonym,
                        client_id=command.client_id,
                        result=executed.output,
                    )
                )
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        # Replies striped over replicas by vertex hash (Replica.scala).
        if hash(vertex_id) % len(self.config.replica_addresses) == self.index:
            client = self.transport.address_from_bytes(command.client_address)
            self.chan(client).send(
                BpClientReply(
                    client_pseudonym=command.client_pseudonym,
                    client_id=command.client_id,
                    result=output,
                )
            )


@dataclasses.dataclass
class _BpPending:
    id: int
    result: Promise
    resend: object


class BpClient(Actor):
    def __init__(self, address, transport, logger, config: SimpleBPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _BpPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = BpClientRequest(
            BpCommand(
                client_address=self.address_bytes,
                client_pseudonym=pseudonym,
                client_id=id,
                command=command,
            )
        )
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))
        ]
        self.chan(leader).send(request)

        def resend() -> None:
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(f"resendBp[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _BpPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BpClientReply):
            self.logger.fatal(f"unknown bpaxos client message {msg!r}")
        pending = self.pending.get(msg.client_pseudonym)
        if pending is None or msg.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[msg.client_pseudonym]
        pending.result.success(msg.result)
