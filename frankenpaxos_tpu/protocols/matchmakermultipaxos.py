"""Matchmaker MultiPaxos — MultiPaxos with online reconfiguration of the
acceptor set (reference ``matchmakermultipaxos/``; protocol cheatsheet
in ``MatchmakerMultiPaxos.proto``; VLDB '21 "Matchmaker Paxos").

Every round has its own acceptor quorum system. A leader entering round
i sends its chosen configuration to the MATCHMAKERS (MatchRequest); f+1
MatchReplies return every configuration used in earlier rounds, and the
leader runs phase 1 against a read quorum of EACH prior configuration
before running phase 2 in its own (``Leader.scala:1020-1238``).

  * i/i+1 reconfiguration: an active leader swaps acceptor sets without
    stalling — phase 2 of round i keeps running while the leader
    matchmakes and phase-1s round i+1 (states Phase2Matchmaking →
    Phase212 → Phase22, ``Leader.scala:454-487``).
  * Matchmakers themselves are reconfigured by RECONFIGURERS: stop the
    old epoch, bootstrap the new one with the merged configuration log,
    then choose the new MatchmakerConfiguration with a Paxos round over
    the OLD epoch's matchmakers (``Reconfigurer.scala``).
  * GC pipeline: once f+1 replicas have executed a prefix, the leader
    persists that watermark on a write quorum of acceptors (which then
    answer phase 2 for those slots with ``persisted=true``) and finally
    has the matchmakers drop configurations below its round
    (``Leader.scala:360-419``).
  * The Driver injects failures/reconfigurations on a schedule for
    chaos benchmarks (``matchmakermultipaxos/Driver.scala``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Set, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.election import basic as election
from frankenpaxos_tpu.quorums import SimpleMajority
from frankenpaxos_tpu.roundsystem import (
    ClassicRoundRobin,
    ClassicStutteredRoundRobin,
)
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, random_duration

COMMAND = "command"
NOOP = "noop"


# -- Messages -----------------------------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmCommand:
    command_id: MmmCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmConfiguration:
    round: int
    # SimpleMajority member indices (the reference also hard-codes
    # SimpleMajority quorum systems, Leader.scala:976).
    members: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchmakerConfiguration:
    epoch: int
    reconfigurer_index: int
    matchmaker_indices: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchRequest:
    matchmaker_configuration: MmmMatchmakerConfiguration
    configuration: MmmConfiguration


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchReply:
    epoch: int
    round: int
    matchmaker_index: int
    gc_watermark: int
    configurations: tuple  # of MmmConfiguration with round < request round


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPhase1a:
    round: int
    chosen_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPhase1b:
    round: int
    acceptor_index: int
    persisted_watermark: int
    info: tuple  # of (slot, vote_round, kind, command|None)


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmClientRequest:
    command: MmmCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPhase2a:
    slot: int
    round: int
    kind: str
    command: Optional[MmmCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPhase2b:
    slot: int
    round: int
    acceptor_index: int
    persisted: bool


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmChosen:
    slot: int
    kind: str
    command: Optional[MmmCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmChosenWatermark:
    watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmClientReply:
    command_id: MmmCommandId
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmNotLeader:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmLeaderInfoRequest:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmLeaderInfoReply:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchmakerNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmAcceptorNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmRecover:
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmExecutedWatermarkRequest:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmExecutedWatermarkReply:
    replica_index: int
    executed_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPersisted:
    persisted_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmPersistedAck:
    acceptor_index: int
    persisted_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmGarbageCollect:
    matchmaker_configuration: MmmMatchmakerConfiguration
    gc_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmGarbageCollectAck:
    epoch: int
    matchmaker_index: int
    gc_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmStopped:
    epoch: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmReconfigure:
    matchmaker_configuration: MmmMatchmakerConfiguration
    new_matchmaker_indices: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmStop:
    matchmaker_configuration: MmmMatchmakerConfiguration


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmStopAck:
    epoch: int
    matchmaker_index: int
    gc_watermark: int
    configurations: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmBootstrap:
    epoch: int
    reconfigurer_index: int
    gc_watermark: int
    configurations: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmBootstrapAck:
    epoch: int
    matchmaker_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchPhase1a:
    matchmaker_configuration: MmmMatchmakerConfiguration
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchPhase1b:
    epoch: int
    round: int
    matchmaker_index: int
    vote_round: int  # -1 = no vote
    vote_value: Optional[MmmMatchmakerConfiguration]


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchPhase2a:
    matchmaker_configuration: MmmMatchmakerConfiguration
    round: int
    value: MmmMatchmakerConfiguration


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchPhase2b:
    epoch: int
    round: int
    matchmaker_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchChosen:
    value: MmmMatchmakerConfiguration


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmMatchNack:
    epoch: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmForceReconfiguration:
    acceptor_indices: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class MmmForceMatchmakerReconfiguration:
    matchmaker_indices: tuple


@dataclasses.dataclass(frozen=True)
class MatchmakerMultiPaxosConfig:
    f: int
    leader_addresses: tuple
    leader_election_addresses: tuple
    reconfigurer_addresses: tuple  # f+1
    matchmaker_addresses: tuple  # >= 2f+1; first 2f+1 form epoch 0
    acceptor_addresses: tuple  # >= 2f+1
    replica_addresses: tuple  # >= f+1

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.leader_election_addresses) != len(self.leader_addresses):
            raise ValueError("one election address per leader")
        if len(self.reconfigurer_addresses) < self.f + 1:
            raise ValueError("need >= f+1 reconfigurers")
        if len(self.matchmaker_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 matchmakers")
        if len(self.acceptor_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


def initial_matchmaker_configuration(
    config: MatchmakerMultiPaxosConfig,
) -> MmmMatchmakerConfiguration:
    # The first 2f+1 matchmakers form epoch 0 (Matchmaker.scala:179-188).
    return MmmMatchmakerConfiguration(
        epoch=0,
        reconfigurer_index=0,
        matchmaker_indices=tuple(range(2 * config.f + 1)),
    )


# -- Leader -------------------------------------------------------------------


@dataclasses.dataclass
class _Matchmaking:
    round: int
    matchmaker_configuration: MmmMatchmakerConfiguration
    quorum_members: tuple
    match_replies: Dict[int, MmmMatchReply]
    pending_requests: List[MmmClientRequest]
    resend: object


@dataclasses.dataclass
class _WaitingForNewMatchmakers:
    round: int
    matchmaker_configuration: MmmMatchmakerConfiguration
    quorum_members: tuple
    pending_requests: List[MmmClientRequest]
    resend: object


@dataclasses.dataclass
class _Phase1:
    round: int
    quorum_members: tuple
    previous_quorums: Dict[int, SimpleMajority]  # round -> quorum system
    acceptor_to_rounds: Dict[int, Set[int]]
    pending_rounds: Set[int]
    phase1bs: Dict[int, MmmPhase1b]
    pending_requests: List[MmmClientRequest]
    resend: object


# GC sub-states of Phase2 (Leader.scala:360-419).
@dataclasses.dataclass
class _QueryingReplicas:
    chosen_watermark: int
    max_slot: int
    replies: Set[int]
    resend: object


@dataclasses.dataclass
class _PushingToAcceptors:
    chosen_watermark: int
    max_slot: int
    quorum: SimpleMajority
    acks: Set[int]
    resend: object


@dataclasses.dataclass
class _WaitingForLargerChosenWatermark:
    chosen_watermark: int
    max_slot: int


@dataclasses.dataclass
class _GarbageCollecting:
    gc_watermark: int
    matchmaker_configuration: MmmMatchmakerConfiguration
    acks: Set[int]
    resend: object


_GC_DONE = "gc_done"
_GC_CANCELLED = "gc_cancelled"


@dataclasses.dataclass
class _Phase2:
    round: int
    next_slot: int
    quorum: SimpleMajority
    values: Dict[int, Tuple[str, Optional[MmmCommand]]]
    phase2bs: Dict[int, Dict[int, MmmPhase2b]]
    chosen: Set[int]
    num_chosen_since_watermark_send: int
    resend: object
    gc: object


@dataclasses.dataclass
class _Phase2Matchmaking:
    phase2: _Phase2
    matchmaking: _Matchmaking


@dataclasses.dataclass
class _Phase212:
    old_phase2: _Phase2
    new_phase1: _Phase1
    new_phase2: _Phase2


@dataclasses.dataclass
class _Phase22:
    old_phase2: _Phase2
    new_phase2: _Phase2


@dataclasses.dataclass
class _Inactive:
    round: int


@dataclasses.dataclass(frozen=True)
class MmmLeaderOptions:
    resend_period: float = 5.0
    send_chosen_watermark_every_n: int = 100
    # Each leader owns `stutter` CONSECUTIVE rounds (Leader.scala:516-519):
    # i/i+1 reconfiguration requires the leader to own round i+1 too.
    stutter: int = 1000
    election_options: election.ElectionOptions = election.ElectionOptions()


class MmmLeader(Actor):
    """``matchmakermultipaxos/Leader.scala``."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig,
                 options: MmmLeaderOptions = MmmLeaderOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round_system = ClassicStutteredRoundRobin(
            len(config.leader_addresses), options.stutter
        )
        self.matchmaker_configuration = initial_matchmaker_configuration(
            config
        )
        self.chosen_watermark = 0
        self.election = election.Participant(
            config.leader_election_addresses[self.index],
            transport, logger, config.leader_election_addresses,
            initial_leader_index=0,
            options=options.election_options, seed=seed,
        )
        self.election.register(self._on_election)
        self.state: object = _Inactive(round=-1)
        if self.index == 0:
            self.become_leader(
                self.round_system.next_classic_round(self.index, -1)
            )

    # -- Timers / small helpers ----------------------------------------------

    def _on_election(self, leader_index: int) -> None:
        if leader_index == self.index:
            if isinstance(self.state, _Inactive):
                self.become_leader(
                    self.round_system.next_classic_round(
                        self.index, self._get_round(self.state)
                    )
                )
        else:
            self.stop_being_leader()

    def _make_resend(self, name: str, fire) -> object:
        def cb() -> None:
            fire()
            timer.start()

        timer = self.timer(name, self.options.resend_period, cb)
        timer.start()
        return timer

    def _get_round(self, state) -> int:
        if isinstance(state, _Inactive):
            return state.round
        if isinstance(state, (_Matchmaking, _WaitingForNewMatchmakers,
                              _Phase1, _Phase2)):
            return state.round
        if isinstance(state, _Phase2Matchmaking):
            return state.matchmaking.round
        if isinstance(state, _Phase212):
            return state.new_phase2.round
        if isinstance(state, _Phase22):
            return state.new_phase2.round
        raise AssertionError(state)

    def _pending_requests(self, state) -> List[MmmClientRequest]:
        if isinstance(state, (_Matchmaking, _WaitingForNewMatchmakers,
                              _Phase1)):
            return state.pending_requests
        return []

    def _stop_gc_timers(self, gc) -> None:
        if isinstance(gc, (_QueryingReplicas, _PushingToAcceptors,
                           _GarbageCollecting)):
            gc.resend.stop()

    def _stop_timers(self, state) -> None:
        if isinstance(state, (_Matchmaking, _WaitingForNewMatchmakers,
                              _Phase1)):
            state.resend.stop()
        elif isinstance(state, _Phase2):
            state.resend.stop()
            self._stop_gc_timers(state.gc)
        elif isinstance(state, _Phase2Matchmaking):
            self._stop_timers(state.phase2)
            self._stop_timers(state.matchmaking)
        elif isinstance(state, _Phase212):
            self._stop_timers(state.old_phase2)
            self._stop_timers(state.new_phase1)
            self._stop_timers(state.new_phase2)
        elif isinstance(state, _Phase22):
            self._stop_timers(state.old_phase2)
            self._stop_timers(state.new_phase2)

    def _make_phase2(self, round: int, next_slot: int,
                     quorum: SimpleMajority, gc) -> _Phase2:
        phase2 = _Phase2(
            round=round, next_slot=next_slot, quorum=quorum, values={},
            phase2bs={}, chosen=set(), num_chosen_since_watermark_send=0,
            resend=None, gc=gc,
        )

        def fire() -> None:
            # Resend phase2as for the SMALLEST pending slot only
            # (Leader.scala:632-678): driving the log's first hole is
            # what unblocks execution.
            pending = [s for s in phase2.values if s >= self.chosen_watermark]
            if pending:
                slot = min(pending)
                kind, command = phase2.values[slot]
                phase2a = MmmPhase2a(
                    slot=slot, round=phase2.round, kind=kind, command=command
                )
                for i in phase2.quorum.nodes():
                    self.chan(self.config.acceptor_addresses[i]).send(phase2a)

        phase2.resend = self._make_resend(f"resendPhase2as{round}", fire)
        return phase2

    # -- Matchmaking ----------------------------------------------------------

    def _start_matchmaking(self, round: int,
                           pending: List[MmmClientRequest],
                           quorum_members: tuple) -> _Matchmaking:
        request = MmmMatchRequest(
            matchmaker_configuration=self.matchmaker_configuration,
            configuration=MmmConfiguration(
                round=round, members=quorum_members
            ),
        )
        mc = self.matchmaker_configuration

        def send() -> None:
            for i in mc.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(request)

        send()
        return _Matchmaking(
            round=round,
            matchmaker_configuration=mc,
            quorum_members=quorum_members,
            match_replies={},
            pending_requests=pending,
            resend=self._make_resend(f"resendMatchRequests{round}", send),
        )

    def become_leader(self, new_round: int) -> None:
        self.logger.check_gt(new_round, self._get_round(self.state))
        self.logger.check_eq(self.round_system.leader(new_round), self.index)
        self._stop_timers(self.state)
        members = tuple(range(2 * self.config.f + 1))
        self.state = self._start_matchmaking(
            new_round, self._pending_requests(self.state), members
        )

    def stop_being_leader(self) -> None:
        self._stop_timers(self.state)
        self.state = _Inactive(round=self._get_round(self.state))

    def become_i_i_plus_one_leader(self, members: tuple) -> None:
        """Reconfigure to a new acceptor set without stalling phase 2
        (Leader.scala:976-1018)."""
        state = self.state
        if isinstance(state, _Phase2) and self.round_system.leader(
            state.round + 1
        ) == self.index:
            matchmaking = self._start_matchmaking(
                state.round + 1, [], members
            )
            self.state = _Phase2Matchmaking(
                phase2=state, matchmaking=matchmaking
            )
        else:
            self.become_leader(
                self.round_system.next_classic_round(
                    self.index, self._get_round(state)
                )
            )

    def _process_match_reply(self, matchmaking: _Matchmaking,
                             msg: MmmMatchReply):
        """Returns None (keep waiting), a _Phase1, or a _Phase2
        (Leader.scala:1020-1177)."""
        if msg.epoch != matchmaking.matchmaker_configuration.epoch:
            return None
        if msg.round != matchmaking.round:
            return None
        matchmaking.match_replies[msg.matchmaker_index] = msg
        if len(matchmaking.match_replies) < self.config.quorum_size:
            return None
        matchmaking.resend.stop()

        gc_watermark = max(
            r.gc_watermark for r in matchmaking.match_replies.values()
        )
        pending_rounds: Set[int] = set()
        previous_quorums: Dict[int, SimpleMajority] = {}
        acceptor_to_rounds: Dict[int, Set[int]] = {}
        for reply in matchmaking.match_replies.values():
            for configuration in reply.configurations:
                if configuration.round < gc_watermark:
                    continue
                if configuration.round in pending_rounds:
                    continue
                pending_rounds.add(configuration.round)
                qs = SimpleMajority(set(configuration.members))
                previous_quorums[configuration.round] = qs
                for i in qs.nodes():
                    acceptor_to_rounds.setdefault(i, set()).add(
                        configuration.round
                    )

        if not pending_rounds:
            return self._make_phase2(
                round=matchmaking.round,
                next_slot=self.chosen_watermark,
                quorum=SimpleMajority(set(matchmaking.quorum_members)),
                gc=_GC_DONE,
            )

        phase1a = MmmPhase1a(
            round=matchmaking.round, chosen_watermark=self.chosen_watermark
        )

        def send() -> None:
            for i in acceptor_to_rounds:
                self.chan(self.config.acceptor_addresses[i]).send(phase1a)

        send()
        return _Phase1(
            round=matchmaking.round,
            quorum_members=matchmaking.quorum_members,
            previous_quorums=previous_quorums,
            acceptor_to_rounds=acceptor_to_rounds,
            pending_rounds=pending_rounds,
            phase1bs={},
            pending_requests=matchmaking.pending_requests,
            resend=self._make_resend(
                f"resendPhase1as{matchmaking.round}", send
            ),
        )

    # -- Phase 1 --------------------------------------------------------------

    def _safe_value(self, phase1bs, slot: int):
        infos = [
            info
            for b in phase1bs
            for info in b.info
            if info[0] == slot
        ]
        if not infos:
            return (NOOP, None)
        best = max(infos, key=lambda info: info[1])
        return (best[2], best[3])

    def _process_phase1b(self, phase1: _Phase1, msg: MmmPhase1b):
        """Returns None or {slot: value} to propose
        (Leader.scala:1178-1238)."""
        if msg.round != phase1.round:
            return None
        phase1.phase1bs[msg.acceptor_index] = msg
        for round in list(phase1.acceptor_to_rounds.get(msg.acceptor_index,
                                                        ())):
            if round in phase1.pending_rounds and phase1.previous_quorums[
                round
            ].is_superset_of_read_quorum(set(phase1.phase1bs)):
                phase1.pending_rounds.discard(round)
        if phase1.pending_rounds:
            return None
        phase1.resend.stop()

        max_persisted = max(
            b.persisted_watermark for b in phase1.phase1bs.values()
        )
        self.chosen_watermark = max(self.chosen_watermark, max_persisted)
        slots = [
            info[0] for b in phase1.phase1bs.values() for info in b.info
        ]
        max_slot = max(slots, default=-1)
        values = {}
        for slot in range(self.chosen_watermark, max_slot + 1):
            values[slot] = self._safe_value(phase1.phase1bs.values(), slot)
        return values

    def _send_phase2a(self, quorum: SimpleMajority, slot: int, round: int,
                      value) -> None:
        kind, command = value
        phase2a = MmmPhase2a(slot=slot, round=round, kind=kind,
                             command=command)
        for i in quorum.nodes():
            self.chan(self.config.acceptor_addresses[i]).send(phase2a)

    def _start_gc_query(self, chosen_watermark: int,
                        max_slot: int) -> _QueryingReplicas:
        def send() -> None:
            for a in self.config.replica_addresses:
                self.chan(a).send(MmmExecutedWatermarkRequest())

        send()
        return _QueryingReplicas(
            chosen_watermark=chosen_watermark, max_slot=max_slot,
            replies=set(),
            resend=self._make_resend("resendExecutedWatermarkRequests", send),
        )

    # -- Phase 2 --------------------------------------------------------------

    def _process_client_request(self, phase2: _Phase2,
                                msg: MmmClientRequest) -> None:
        slot = phase2.next_slot
        phase2.next_slot += 1
        value = (COMMAND, msg.command)
        phase2.values[slot] = value
        phase2.phase2bs[slot] = {}
        self._send_phase2a(phase2.quorum, slot, phase2.round, value)

    def _process_phase2b(self, phase2: _Phase2, msg: MmmPhase2b) -> None:
        """(Leader.scala:1239-1352)"""
        if msg.round != phase2.round:
            return
        if msg.slot < self.chosen_watermark or msg.slot in phase2.chosen:
            return
        if not msg.persisted:
            in_slot = phase2.phase2bs.setdefault(msg.slot, {})
            in_slot[msg.acceptor_index] = msg
            if not phase2.quorum.is_superset_of_write_quorum(set(in_slot)):
                return
            kind, command = phase2.values[msg.slot]
            chosen = MmmChosen(slot=msg.slot, kind=kind, command=command)
            for a in self.config.replica_addresses:
                self.chan(a).send(chosen)
        phase2.values.pop(msg.slot, None)
        phase2.phase2bs.pop(msg.slot, None)
        phase2.chosen.add(msg.slot)
        old_watermark = self.chosen_watermark
        while self.chosen_watermark in phase2.chosen:
            phase2.chosen.discard(self.chosen_watermark)
            self.chosen_watermark += 1
        if old_watermark != self.chosen_watermark:
            phase2.resend.reset()
        phase2.num_chosen_since_watermark_send += 1
        if (
            phase2.num_chosen_since_watermark_send
            >= self.options.send_chosen_watermark_every_n
        ):
            for a in self.config.leader_addresses:
                if a != self.address:
                    self.chan(a).send(
                        MmmChosenWatermark(watermark=self.chosen_watermark)
                    )
            phase2.num_chosen_since_watermark_send = 0
        # GC: waiting for the watermark to pass maxSlot?
        gc = phase2.gc
        if (
            isinstance(gc, _WaitingForLargerChosenWatermark)
            and self.chosen_watermark > gc.max_slot
        ):
            self._start_garbage_collecting(phase2)

    def _start_garbage_collecting(self, phase2: _Phase2) -> None:
        mc = self.matchmaker_configuration
        garbage_collect = MmmGarbageCollect(
            matchmaker_configuration=mc, gc_watermark=phase2.round
        )

        def send() -> None:
            for i in mc.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(
                    garbage_collect
                )

        send()
        phase2.gc = _GarbageCollecting(
            gc_watermark=phase2.round,
            matchmaker_configuration=mc,
            acks=set(),
            resend=self._make_resend("resendGarbageCollects", send),
        )

    # -- Handlers -------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmMatchReply):
            self._handle_match_reply(msg)
        elif isinstance(msg, MmmPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, MmmClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, MmmPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, MmmLeaderInfoRequest):
            if not isinstance(self.state, _Inactive):
                self.chan(src).send(
                    MmmLeaderInfoReply(round=self._get_round(self.state))
                )
        elif isinstance(msg, MmmChosenWatermark):
            if isinstance(self.state, _Inactive):
                self.chosen_watermark = max(
                    self.chosen_watermark, msg.watermark
                )
        elif isinstance(msg, MmmMatchmakerNack):
            self._handle_matchmaker_nack(msg)
        elif isinstance(msg, MmmAcceptorNack):
            self._handle_acceptor_nack(msg)
        elif isinstance(msg, MmmRecover):
            self._handle_recover(msg)
        elif isinstance(msg, MmmExecutedWatermarkReply):
            self._handle_executed_watermark_reply(msg)
        elif isinstance(msg, MmmPersistedAck):
            self._handle_persisted_ack(msg)
        elif isinstance(msg, MmmGarbageCollectAck):
            self._handle_garbage_collect_ack(msg)
        elif isinstance(msg, MmmStopped):
            self._handle_stopped(msg)
        elif isinstance(msg, MmmMatchChosen):
            self._handle_match_chosen(msg)
        elif isinstance(msg, MmmForceReconfiguration):
            self.become_i_i_plus_one_leader(tuple(msg.acceptor_indices))
        else:
            self.logger.fatal(f"unknown mmm leader message {msg!r}")

    def _handle_match_reply(self, msg: MmmMatchReply) -> None:
        state = self.state
        if isinstance(state, _Matchmaking):
            result = self._process_match_reply(state, msg)
            if result is None:
                return
            self.state = result
            if isinstance(result, _Phase2):
                for request in state.pending_requests:
                    self._process_client_request(result, request)
        elif isinstance(state, _Phase2Matchmaking):
            result = self._process_match_reply(state.matchmaking, msg)
            if result is None:
                return
            if isinstance(result, _Phase2):
                # No prior configurations at all is impossible here: round
                # i's own configuration must come back.
                self.logger.fatal(
                    "i/i+1 matchmaking returned no configurations"
                )
            # Transition to Phase212: phase 2 of round i keeps going; we
            # are in phase 1 AND phase 2 of round i+1 simultaneously.
            self._stop_timers(state.phase2)
            state.phase2.gc = _GC_CANCELLED
            new_phase2 = self._make_phase2(
                round=state.matchmaking.round,
                next_slot=state.phase2.next_slot,
                quorum=SimpleMajority(set(state.matchmaking.quorum_members)),
                gc=_GC_CANCELLED,
            )
            self.state = _Phase212(
                old_phase2=state.phase2,
                new_phase1=result,
                new_phase2=new_phase2,
            )

    def _handle_phase1b(self, msg: MmmPhase1b) -> None:
        state = self.state
        if isinstance(state, _Phase1):
            values = self._process_phase1b(state, msg)
            if values is None:
                return
            max_slot = max(values, default=-1)
            phase2 = self._make_phase2(
                round=state.round,
                next_slot=max(self.chosen_watermark, max_slot + 1),
                quorum=SimpleMajority(set(state.quorum_members)),
                gc=self._start_gc_query(self.chosen_watermark, max_slot),
            )
            for slot, value in values.items():
                phase2.values[slot] = value
                phase2.phase2bs[slot] = {}
                self._send_phase2a(phase2.quorum, slot, state.round, value)
            self.state = phase2
            for request in state.pending_requests:
                self._process_client_request(phase2, request)
        elif isinstance(state, _Phase212):
            values = self._process_phase1b(state.new_phase1, msg)
            if values is None:
                return
            max_slot = max(values, default=-1)
            new_phase2 = state.new_phase2
            for slot, value in values.items():
                new_phase2.values[slot] = value
                new_phase2.phase2bs[slot] = {}
                self._send_phase2a(
                    new_phase2.quorum, slot, new_phase2.round, value
                )
            # Fill [max_slot+1, old.next_slot) with noops in round i+1
            # (Leader.scala:1622-1642).
            for slot in range(
                max(max_slot + 1, self.chosen_watermark),
                state.old_phase2.next_slot,
            ):
                if slot in new_phase2.values:
                    continue
                new_phase2.values[slot] = (NOOP, None)
                new_phase2.phase2bs[slot] = {}
                self._send_phase2a(
                    new_phase2.quorum, slot, new_phase2.round, (NOOP, None)
                )
            new_phase2.next_slot = max(
                new_phase2.next_slot, state.old_phase2.next_slot
            )
            if self.chosen_watermark >= state.old_phase2.next_slot:
                self._stop_timers(state.old_phase2)
                new_phase2.gc = self._start_gc_query(
                    self.chosen_watermark, max_slot
                )
                self.state = new_phase2
            else:
                self.state = _Phase22(
                    old_phase2=state.old_phase2, new_phase2=new_phase2
                )

    def _handle_client_request(self, src: Address,
                               msg: MmmClientRequest) -> None:
        state = self.state
        if isinstance(state, _Inactive):
            self.chan(src).send(MmmNotLeader())
        elif isinstance(state, (_Matchmaking, _WaitingForNewMatchmakers,
                                _Phase1)):
            state.pending_requests.append(msg)
        elif isinstance(state, _Phase2):
            self._process_client_request(state, msg)
        elif isinstance(state, _Phase2Matchmaking):
            self._process_client_request(state.phase2, msg)
        elif isinstance(state, _Phase212):
            self._process_client_request(state.new_phase2, msg)
        elif isinstance(state, _Phase22):
            self._process_client_request(state.new_phase2, msg)

    def _handle_phase2b(self, msg: MmmPhase2b) -> None:
        state = self.state
        if isinstance(state, _Phase2):
            self._process_phase2b(state, msg)
        elif isinstance(state, _Phase2Matchmaking):
            self._process_phase2b(state.phase2, msg)
        elif isinstance(state, _Phase212):
            if msg.round == state.old_phase2.round:
                self._process_phase2b(state.old_phase2, msg)
            elif msg.round == state.new_phase2.round:
                self._process_phase2b(state.new_phase2, msg)
        elif isinstance(state, _Phase22):
            if msg.round == state.old_phase2.round:
                self._process_phase2b(state.old_phase2, msg)
            elif msg.round == state.new_phase2.round:
                self._process_phase2b(state.new_phase2, msg)
            if self.chosen_watermark >= state.old_phase2.next_slot:
                self._stop_timers(state.old_phase2)
                new_phase2 = state.new_phase2
                new_phase2.gc = self._start_gc_query(
                    state.old_phase2.next_slot, state.old_phase2.next_slot
                )
                self.state = new_phase2

    def _handle_matchmaker_nack(self, msg: MmmMatchmakerNack) -> None:
        if msg.round < self._get_round(self.state):
            return
        state = self.state
        if isinstance(state, _Inactive):
            state.round = msg.round
        elif isinstance(state, (_Matchmaking, _Phase2Matchmaking)):
            self.become_leader(
                self.round_system.next_classic_round(self.index, msg.round)
            )

    def _handle_acceptor_nack(self, msg: MmmAcceptorNack) -> None:
        state = self.state
        if isinstance(state, _Inactive):
            if msg.round > state.round:
                state.round = msg.round
            return
        smaller = (
            state.phase2.round if isinstance(state, _Phase2Matchmaking)
            else state.old_phase2.round
            if isinstance(state, (_Phase212, _Phase22))
            else state.round
        )
        if msg.round < smaller:
            return
        if isinstance(state, (_Phase1, _Phase2, _Phase2Matchmaking,
                              _Phase212, _Phase22)):
            self.become_leader(
                self.round_system.next_classic_round(
                    self.index, max(msg.round, self._get_round(state))
                )
            )

    def _handle_recover(self, msg: MmmRecover) -> None:
        if isinstance(self.state, _Inactive):
            return
        # Heavy-handed but rare: lower the watermark and run a full leader
        # change so the slot gets re-chosen (Leader.scala:2006-2028).
        if self.chosen_watermark > msg.slot:
            self.chosen_watermark = msg.slot
        self.become_leader(
            self.round_system.next_classic_round(
                self.index, self._get_round(self.state)
            )
        )

    def _handle_executed_watermark_reply(
        self, msg: MmmExecutedWatermarkReply
    ) -> None:
        state = self.state
        if not isinstance(state, _Phase2):
            return
        gc = state.gc
        if not isinstance(gc, _QueryingReplicas):
            return
        if msg.executed_watermark < gc.chosen_watermark:
            return
        gc.replies.add(msg.replica_index)
        if len(gc.replies) < self.config.f + 1:
            return
        gc.resend.stop()
        persisted = MmmPersisted(persisted_watermark=gc.chosen_watermark)
        quorum = state.quorum

        def send() -> None:
            for i in quorum.nodes():
                self.chan(self.config.acceptor_addresses[i]).send(persisted)

        send()
        state.gc = _PushingToAcceptors(
            chosen_watermark=gc.chosen_watermark, max_slot=gc.max_slot,
            quorum=quorum, acks=set(),
            resend=self._make_resend("resendPersisted", send),
        )

    def _handle_persisted_ack(self, msg: MmmPersistedAck) -> None:
        state = self.state
        if not isinstance(state, _Phase2):
            return
        gc = state.gc
        if not isinstance(gc, _PushingToAcceptors):
            return
        if msg.persisted_watermark < gc.chosen_watermark:
            return
        gc.acks.add(msg.acceptor_index)
        if not gc.quorum.is_superset_of_write_quorum(gc.acks):
            return
        gc.resend.stop()
        if self.chosen_watermark <= gc.max_slot:
            state.gc = _WaitingForLargerChosenWatermark(
                chosen_watermark=gc.chosen_watermark, max_slot=gc.max_slot
            )
            return
        self._start_garbage_collecting(state)

    def _handle_garbage_collect_ack(self, msg: MmmGarbageCollectAck) -> None:
        state = self.state
        if not isinstance(state, _Phase2):
            return
        gc = state.gc
        if not isinstance(gc, _GarbageCollecting):
            return
        if msg.epoch != gc.matchmaker_configuration.epoch:
            return
        if msg.gc_watermark < gc.gc_watermark:
            return
        gc.acks.add(msg.matchmaker_index)
        if len(gc.acks) < self.config.f + 1:
            return
        gc.resend.stop()
        state.gc = _GC_DONE

    def _handle_stopped(self, msg: MmmStopped) -> None:
        state = self.state
        if isinstance(state, _Phase2Matchmaking):
            # Give up and retry the whole round (Leader.scala:2237-2239).
            self.become_leader(
                self.round_system.next_classic_round(
                    self.index, self._get_round(state)
                )
            )
        elif isinstance(state, _Matchmaking):
            if msg.epoch != state.matchmaker_configuration.epoch:
                return
            state.resend.stop()
            reconfigure = MmmReconfigure(
                matchmaker_configuration=state.matchmaker_configuration,
                new_matchmaker_indices=tuple(
                    self.rng.sample(
                        range(len(self.config.matchmaker_addresses)),
                        2 * self.config.f + 1,
                    )
                ),
            )
            reconfigurer = self.config.reconfigurer_addresses[
                self.rng.randrange(len(self.config.reconfigurer_addresses))
            ]

            def send() -> None:
                self.chan(reconfigurer).send(reconfigure)

            send()
            self.state = _WaitingForNewMatchmakers(
                round=state.round,
                matchmaker_configuration=state.matchmaker_configuration,
                quorum_members=state.quorum_members,
                pending_requests=state.pending_requests,
                resend=self._make_resend("resendReconfigure", send),
            )
        elif isinstance(state, _Phase2):
            if isinstance(state.gc, _GarbageCollecting):
                if msg.epoch != state.gc.matchmaker_configuration.epoch:
                    return
                state.gc.resend.stop()
                state.gc = _GC_CANCELLED

    def _handle_match_chosen(self, msg: MmmMatchChosen) -> None:
        if msg.value.epoch <= self.matchmaker_configuration.epoch:
            return
        self.matchmaker_configuration = msg.value
        state = self.state
        if isinstance(state, _Matchmaking):
            state.resend.stop()
            self.state = self._start_matchmaking(
                state.round, state.pending_requests, state.quorum_members
            )
        elif isinstance(state, _WaitingForNewMatchmakers):
            state.resend.stop()
            self.state = self._start_matchmaking(
                state.round, state.pending_requests, state.quorum_members
            )


# -- Matchmaker ---------------------------------------------------------------


@dataclasses.dataclass
class _MmPending:
    logs: Dict[int, Tuple[int, Dict[int, MmmConfiguration]]]


@dataclasses.dataclass
class _MmNormal:
    gc_watermark: int
    configurations: Dict[int, MmmConfiguration]


@dataclasses.dataclass
class _MmHasStopped:
    gc_watermark: int
    configurations: Dict[int, MmmConfiguration]


@dataclasses.dataclass
class _MmAcceptorState:
    round: int
    vote_round: int
    vote_value: Optional[MmmMatchmakerConfiguration]


class MmmMatchmaker(Actor):
    """``matchmakermultipaxos/Matchmaker.scala``: one PHYSICAL matchmaker
    plays a logical matchmaker in many epochs; per epoch it is Pending →
    Normal → HasStopped, and doubles as an acceptor for choosing the next
    epoch's MatchmakerConfiguration."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.matchmaker_addresses)
        self.config = config
        self.index = config.matchmaker_addresses.index(address)
        self.states: Dict[int, object] = {}
        self.acceptor_states: Dict[int, _MmAcceptorState] = {}
        if self.index < 2 * config.f + 1:
            self.states[0] = _MmNormal(gc_watermark=0, configurations={})
            self.acceptor_states[0] = _MmAcceptorState(-1, -1, None)

    def _to_stopped(self, epoch: int, reconfigurer_index: int) -> _MmHasStopped:
        state = self.states[epoch]
        if isinstance(state, _MmPending):
            gc_watermark, configurations = state.logs[reconfigurer_index]
            stopped = _MmHasStopped(gc_watermark, dict(configurations))
        elif isinstance(state, _MmNormal):
            stopped = _MmHasStopped(state.gc_watermark, state.configurations)
        else:
            return state
        self.states[epoch] = stopped
        return stopped

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmMatchRequest):
            self._handle_match_request(src, msg)
        elif isinstance(msg, MmmGarbageCollect):
            self._handle_garbage_collect(src, msg)
        elif isinstance(msg, MmmStop):
            self._handle_stop(src, msg)
        elif isinstance(msg, MmmBootstrap):
            self._handle_bootstrap(src, msg)
        elif isinstance(msg, MmmMatchPhase1a):
            self._handle_match_phase1a(src, msg)
        elif isinstance(msg, MmmMatchPhase2a):
            self._handle_match_phase2a(src, msg)
        elif isinstance(msg, MmmMatchChosen):
            self._handle_match_chosen(msg)
        else:
            self.logger.fatal(f"unknown matchmaker message {msg!r}")

    def _normal_or_stopped(self, src, configuration):
        """Resolve the state for an epoch, promoting Pending; replies
        Stopped and returns None if the epoch has stopped."""
        epoch = configuration.epoch
        self.logger.check(epoch in self.states)
        state = self.states[epoch]
        if isinstance(state, _MmPending):
            gc_watermark, configurations = state.logs[
                configuration.reconfigurer_index
            ]
            state = _MmNormal(gc_watermark, dict(configurations))
            self.states[epoch] = state
        if isinstance(state, _MmHasStopped):
            self.chan(src).send(MmmStopped(epoch=epoch))
            return None
        return state

    def _handle_match_request(self, src, msg: MmmMatchRequest) -> None:
        normal = self._normal_or_stopped(src, msg.matchmaker_configuration)
        if normal is None:
            return
        round = msg.configuration.round
        if round < normal.gc_watermark:
            self.chan(src).send(
                MmmMatchmakerNack(round=normal.gc_watermark - 1)
            )
            return
        if normal.configurations and round <= max(normal.configurations):
            self.chan(src).send(
                MmmMatchmakerNack(round=max(normal.configurations))
            )
            return
        self.chan(src).send(
            MmmMatchReply(
                epoch=msg.matchmaker_configuration.epoch,
                round=round,
                matchmaker_index=self.index,
                gc_watermark=normal.gc_watermark,
                configurations=tuple(
                    normal.configurations[r]
                    for r in sorted(normal.configurations)
                    if r < round
                ),
            )
        )
        normal.configurations[round] = msg.configuration

    def _handle_garbage_collect(self, src, msg: MmmGarbageCollect) -> None:
        if msg.matchmaker_configuration.epoch not in self.states:
            return
        normal = self._normal_or_stopped(src, msg.matchmaker_configuration)
        if normal is None:
            return
        gc_watermark = max(normal.gc_watermark, msg.gc_watermark)
        self.chan(src).send(
            MmmGarbageCollectAck(
                epoch=msg.matchmaker_configuration.epoch,
                matchmaker_index=self.index,
                gc_watermark=gc_watermark,
            )
        )
        normal.gc_watermark = gc_watermark
        for round in [r for r in normal.configurations if r < gc_watermark]:
            del normal.configurations[round]

    def _handle_stop(self, src, msg: MmmStop) -> None:
        epoch = msg.matchmaker_configuration.epoch
        self.logger.check(epoch in self.states)
        stopped = self._to_stopped(
            epoch, msg.matchmaker_configuration.reconfigurer_index
        )
        self.chan(src).send(
            MmmStopAck(
                epoch=epoch,
                matchmaker_index=self.index,
                gc_watermark=stopped.gc_watermark,
                configurations=tuple(
                    stopped.configurations[r]
                    for r in sorted(stopped.configurations)
                ),
            )
        )

    def _handle_bootstrap(self, src, msg: MmmBootstrap) -> None:
        state = self.states.get(msg.epoch)
        log = (
            msg.gc_watermark,
            {c.round: c for c in msg.configurations},
        )
        if state is None:
            self.states[msg.epoch] = _MmPending(
                logs={msg.reconfigurer_index: log}
            )
            self.acceptor_states[msg.epoch] = _MmAcceptorState(-1, -1, None)
        elif isinstance(state, _MmPending):
            state.logs[msg.reconfigurer_index] = log
        self.chan(src).send(
            MmmBootstrapAck(epoch=msg.epoch, matchmaker_index=self.index)
        )

    def _handle_match_phase1a(self, src, msg: MmmMatchPhase1a) -> None:
        epoch = msg.matchmaker_configuration.epoch
        self.logger.check(epoch in self.states)
        self._to_stopped(epoch, msg.matchmaker_configuration.reconfigurer_index)
        acceptor = self.acceptor_states[epoch]
        if msg.round < acceptor.round:
            self.chan(src).send(MmmMatchNack(epoch=epoch,
                                             round=acceptor.round))
            return
        acceptor.round = msg.round
        self.chan(src).send(
            MmmMatchPhase1b(
                epoch=epoch, round=msg.round, matchmaker_index=self.index,
                vote_round=acceptor.vote_round,
                vote_value=acceptor.vote_value,
            )
        )

    def _handle_match_phase2a(self, src, msg: MmmMatchPhase2a) -> None:
        epoch = msg.matchmaker_configuration.epoch
        self.logger.check(epoch in self.states)
        self._to_stopped(epoch, msg.matchmaker_configuration.reconfigurer_index)
        acceptor = self.acceptor_states[epoch]
        if msg.round < acceptor.round:
            self.chan(src).send(MmmMatchNack(epoch=epoch,
                                             round=acceptor.round))
            return
        acceptor.round = msg.round
        acceptor.vote_round = msg.round
        acceptor.vote_value = msg.value
        self.chan(src).send(
            MmmMatchPhase2b(
                epoch=epoch, round=msg.round, matchmaker_index=self.index
            )
        )

    def _handle_match_chosen(self, msg: MmmMatchChosen) -> None:
        epoch = msg.value.epoch
        state = self.states.get(epoch)
        if isinstance(state, _MmPending):
            gc_watermark, configurations = state.logs[
                msg.value.reconfigurer_index
            ]
            self.states[epoch] = _MmNormal(gc_watermark, dict(configurations))


# -- Reconfigurer -------------------------------------------------------------


@dataclasses.dataclass
class _RcIdle:
    configuration: MmmMatchmakerConfiguration


@dataclasses.dataclass
class _RcStopping:
    configuration: MmmMatchmakerConfiguration
    new_configuration: MmmMatchmakerConfiguration
    stop_acks: Dict[int, MmmStopAck]
    resend: object


@dataclasses.dataclass
class _RcBootstrapping:
    configuration: MmmMatchmakerConfiguration
    new_configuration: MmmMatchmakerConfiguration
    bootstrap_acks: Dict[int, MmmBootstrapAck]
    resend: object


@dataclasses.dataclass
class _RcPhase1:
    configuration: MmmMatchmakerConfiguration
    new_configuration: MmmMatchmakerConfiguration
    round: int
    phase1bs: Dict[int, MmmMatchPhase1b]
    resend: object


@dataclasses.dataclass
class _RcPhase2:
    configuration: MmmMatchmakerConfiguration
    new_configuration: MmmMatchmakerConfiguration
    round: int
    phase2bs: Dict[int, MmmMatchPhase2b]
    resend: object


class MmmReconfigurer(Actor):
    """``matchmakermultipaxos/Reconfigurer.scala``: stop the old epoch's
    matchmakers, bootstrap the new ones with the merged configuration
    log, then run a Paxos round over the OLD epoch to choose the new
    MatchmakerConfiguration."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig,
                 resend_period: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.reconfigurer_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.index = config.reconfigurer_addresses.index(address)
        self.round_system = ClassicRoundRobin(
            len(config.reconfigurer_addresses)
        )
        self.state: object = _RcIdle(
            configuration=initial_matchmaker_configuration(config)
        )

    def _make_resend(self, name, fire):
        def cb() -> None:
            fire()
            timer.start()

        timer = self.timer(name, self.resend_period, cb)
        timer.start()
        return timer

    def _start_stopping(self, configuration, new_indices: tuple) -> None:
        stop = MmmStop(matchmaker_configuration=configuration)

        def send() -> None:
            for i in configuration.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(stop)

        send()
        self.state = _RcStopping(
            configuration=configuration,
            new_configuration=MmmMatchmakerConfiguration(
                epoch=configuration.epoch + 1,
                reconfigurer_index=self.index,
                matchmaker_indices=new_indices,
            ),
            stop_acks={},
            resend=self._make_resend("resendStops", send),
        )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmReconfigure):
            self._handle_reconfigure(src, msg)
        elif isinstance(msg, MmmStopAck):
            self._handle_stop_ack(msg)
        elif isinstance(msg, MmmBootstrapAck):
            self._handle_bootstrap_ack(msg)
        elif isinstance(msg, MmmMatchPhase1b):
            self._handle_match_phase1b(msg)
        elif isinstance(msg, MmmMatchPhase2b):
            self._handle_match_phase2b(msg)
        elif isinstance(msg, MmmMatchChosen):
            self._handle_match_chosen(msg)
        elif isinstance(msg, MmmMatchNack):
            self._handle_match_nack(msg)
        elif isinstance(msg, MmmForceMatchmakerReconfiguration):
            if isinstance(self.state, _RcIdle):
                self._start_stopping(
                    self.state.configuration, tuple(msg.matchmaker_indices)
                )
        else:
            self.logger.fatal(f"unknown reconfigurer message {msg!r}")

    def _handle_reconfigure(self, src, msg: MmmReconfigure) -> None:
        state = self.state
        if not isinstance(state, _RcIdle):
            return
        if msg.matchmaker_configuration.epoch < state.configuration.epoch:
            # Stale: tell the leader about the newer configuration.
            self.chan(src).send(MmmMatchChosen(value=state.configuration))
            return
        self._start_stopping(
            msg.matchmaker_configuration, tuple(msg.new_matchmaker_indices)
        )

    def _handle_stop_ack(self, msg: MmmStopAck) -> None:
        state = self.state
        if not isinstance(state, _RcStopping):
            return
        if msg.epoch != state.configuration.epoch:
            return
        state.stop_acks[msg.matchmaker_index] = msg
        if len(state.stop_acks) < self.config.f + 1:
            return
        state.resend.stop()
        gc_watermark = max(a.gc_watermark for a in state.stop_acks.values())
        merged: Dict[int, MmmConfiguration] = {}
        for ack in state.stop_acks.values():
            for configuration in ack.configurations:
                if configuration.round >= gc_watermark:
                    merged[configuration.round] = configuration
        bootstrap = MmmBootstrap(
            epoch=state.new_configuration.epoch,
            reconfigurer_index=self.index,
            gc_watermark=gc_watermark,
            configurations=tuple(
                merged[r] for r in sorted(merged)
            ),
        )
        new_configuration = state.new_configuration

        def send() -> None:
            for i in new_configuration.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(bootstrap)

        send()
        self.state = _RcBootstrapping(
            configuration=state.configuration,
            new_configuration=new_configuration,
            bootstrap_acks={},
            resend=self._make_resend("resendBootstraps", send),
        )

    def _handle_bootstrap_ack(self, msg: MmmBootstrapAck) -> None:
        state = self.state
        if not isinstance(state, _RcBootstrapping):
            return
        if msg.epoch != state.new_configuration.epoch:
            return
        state.bootstrap_acks[msg.matchmaker_index] = msg
        # ALL new matchmakers must be bootstrapped before the epoch can be
        # chosen (Reconfigurer.scala:497-500).
        if len(state.bootstrap_acks) < 2 * self.config.f + 1:
            return
        state.resend.stop()
        self._start_phase1(
            state.configuration, state.new_configuration,
            self.round_system.next_classic_round(self.index, -1),
        )

    def _start_phase1(self, configuration, new_configuration,
                      round: int) -> None:
        phase1a = MmmMatchPhase1a(
            matchmaker_configuration=configuration, round=round
        )

        def send() -> None:
            for i in configuration.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(phase1a)

        send()
        self.state = _RcPhase1(
            configuration=configuration,
            new_configuration=new_configuration,
            round=round, phase1bs={},
            resend=self._make_resend("resendMatchPhase1as", send),
        )

    def _handle_match_phase1b(self, msg: MmmMatchPhase1b) -> None:
        state = self.state
        if not isinstance(state, _RcPhase1):
            return
        if msg.epoch != state.configuration.epoch:
            return
        if msg.round != state.round:
            return
        state.phase1bs[msg.matchmaker_index] = msg
        if len(state.phase1bs) < self.config.f + 1:
            return
        state.resend.stop()
        votes = [
            b for b in state.phase1bs.values() if b.vote_round >= 0
        ]
        if votes:
            value = max(votes, key=lambda b: b.vote_round).vote_value
        else:
            value = state.new_configuration
        phase2a = MmmMatchPhase2a(
            matchmaker_configuration=state.configuration,
            round=state.round, value=value,
        )
        configuration = state.configuration

        def send() -> None:
            for i in configuration.matchmaker_indices:
                self.chan(self.config.matchmaker_addresses[i]).send(phase2a)

        send()
        self.state = _RcPhase2(
            configuration=configuration,
            new_configuration=value,
            round=state.round, phase2bs={},
            resend=self._make_resend("resendMatchPhase2as", send),
        )

    def _handle_match_phase2b(self, msg: MmmMatchPhase2b) -> None:
        state = self.state
        if not isinstance(state, _RcPhase2):
            return
        if msg.epoch != state.configuration.epoch:
            return
        if msg.round != state.round:
            return
        state.phase2bs[msg.matchmaker_index] = msg
        if len(state.phase2bs) < self.config.f + 1:
            return
        state.resend.stop()
        chosen = MmmMatchChosen(value=state.new_configuration)
        for a in self.config.leader_addresses:
            self.chan(a).send(chosen)
        for a in self.config.reconfigurer_addresses:
            if a != self.address:
                self.chan(a).send(chosen)
        for i in state.new_configuration.matchmaker_indices:
            self.chan(self.config.matchmaker_addresses[i]).send(chosen)
        self.state = _RcIdle(configuration=state.new_configuration)

    def _handle_match_chosen(self, msg: MmmMatchChosen) -> None:
        state = self.state
        epoch = state.configuration.epoch
        if msg.value.epoch <= epoch:
            return
        if isinstance(state, (_RcStopping, _RcBootstrapping, _RcPhase1,
                              _RcPhase2)):
            state.resend.stop()
        self.state = _RcIdle(configuration=msg.value)

    def _handle_match_nack(self, msg: MmmMatchNack) -> None:
        state = self.state
        if not isinstance(state, (_RcPhase1, _RcPhase2)):
            return
        if msg.epoch != state.configuration.epoch or msg.round <= state.round:
            return
        state.resend.stop()
        self._start_phase1(
            state.configuration, state.new_configuration,
            self.round_system.next_classic_round(self.index, msg.round),
        )


# -- Acceptor -----------------------------------------------------------------


class MmmAcceptor(Actor):
    """``matchmakermultipaxos/Acceptor.scala``: per-slot votes with a
    persisted watermark — slots below it answer phase 2 with
    persisted=true and are garbage collected."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = -1
        self.persisted_watermark = 0
        # slot -> (vote_round, kind, command)
        self.states: Dict[int, Tuple[int, str, Optional[MmmCommand]]] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmPhase1a):
            self._handle_phase1a(src, msg)
        elif isinstance(msg, MmmPhase2a):
            self._handle_phase2a(src, msg)
        elif isinstance(msg, MmmPersisted):
            self.persisted_watermark = max(
                self.persisted_watermark, msg.persisted_watermark
            )
            for slot in [
                s for s in self.states if s < self.persisted_watermark
            ]:
                del self.states[slot]
            self.chan(src).send(
                MmmPersistedAck(
                    acceptor_index=self.index,
                    persisted_watermark=self.persisted_watermark,
                )
            )
        else:
            self.logger.fatal(f"unknown mmm acceptor message {msg!r}")

    def _handle_phase1a(self, src, msg: MmmPhase1a) -> None:
        if msg.round < self.round:
            self.chan(src).send(MmmAcceptorNack(round=self.round))
            return
        self.round = msg.round
        info = []
        start = max(self.persisted_watermark, msg.chosen_watermark)
        for slot in sorted(self.states):
            if slot < start:
                continue
            vote_round, kind, command = self.states[slot]
            # Subtle i/i+1 case: don't return votes cast in the CURRENT
            # round — the leader already proposed those safely
            # (Acceptor.scala:225-236).
            if vote_round < self.round:
                info.append((slot, vote_round, kind, command))
        self.chan(src).send(
            MmmPhase1b(
                round=self.round, acceptor_index=self.index,
                persisted_watermark=self.persisted_watermark,
                info=tuple(info),
            )
        )

    def _handle_phase2a(self, src, msg: MmmPhase2a) -> None:
        if msg.slot < self.persisted_watermark:
            self.chan(src).send(
                MmmPhase2b(slot=msg.slot, round=msg.round,
                           acceptor_index=self.index, persisted=True)
            )
            return
        if msg.round < self.round:
            self.chan(src).send(MmmAcceptorNack(round=self.round))
            return
        self.round = msg.round
        self.states[msg.slot] = (msg.round, msg.kind, msg.command)
        self.chan(src).send(
            MmmPhase2b(slot=msg.slot, round=msg.round,
                       acceptor_index=self.index, persisted=False)
        )


# -- Replica ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MmmReplicaOptions:
    log_grow_size: int = 5000
    recover_min_period: float = 10.0
    recover_max_period: float = 20.0
    unsafe_dont_recover: bool = False


class MmmReplica(Actor):
    """``matchmakermultipaxos/Replica.scala``: executes the chosen log in
    prefix order, answers ExecutedWatermarkRequests (the GC pipeline's
    first stage), and recovers holes via other replicas then leaders."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig,
                 state_machine: StateMachine,
                 options: MmmReplicaOptions = MmmReplicaOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.replica_addresses.index(address)
        self.log: BufferMap = BufferMap(options.log_grow_size)
        self.executed_watermark = 0
        self.num_chosen = 0
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}

        def recover() -> None:
            recover_msg = MmmRecover(slot=self.executed_watermark)
            for a in self.config.replica_addresses:
                if a != self.address:
                    self.chan(a).send(recover_msg)
            for a in self.config.leader_addresses:
                self.chan(a).send(recover_msg)
            self.recover_timer.start()

        self.recover_timer = self.timer(
            "recover",
            random_duration(self.rng, options.recover_min_period,
                            options.recover_max_period),
            recover,
        )

    def _execute_command(self, slot: int, command: MmmCommand) -> None:
        cid = command.command_id
        identity = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(identity)
        client = self.transport.address_from_bytes(cid.client_address)
        if cached is not None:
            if cid.client_id < cached[0]:
                return
            if cid.client_id == cached[0]:
                self.chan(client).send(
                    MmmClientReply(command_id=cid, result=cached[1])
                )
                return
        result = self.state_machine.run(command.command)
        self.client_table[identity] = (cid.client_id, result)
        if slot % len(self.config.replica_addresses) == self.index:
            self.chan(client).send(
                MmmClientReply(command_id=cid, result=result)
            )

    def _execute_log(self) -> None:
        while True:
            entry = self.log.get(self.executed_watermark)
            if entry is None:
                return
            kind, command = entry
            if kind == COMMAND:
                self._execute_command(self.executed_watermark, command)
            self.executed_watermark += 1

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmChosen):
            self._handle_chosen(msg)
        elif isinstance(msg, MmmRecover):
            entry = self.log.get(msg.slot)
            if entry is not None:
                self.chan(src).send(
                    MmmChosen(slot=msg.slot, kind=entry[0], command=entry[1])
                )
        elif isinstance(msg, MmmExecutedWatermarkRequest):
            self.chan(src).send(
                MmmExecutedWatermarkReply(
                    replica_index=self.index,
                    executed_watermark=self.executed_watermark,
                )
            )
        else:
            self.logger.fatal(f"unknown mmm replica message {msg!r}")

    def _handle_chosen(self, msg: MmmChosen) -> None:
        was_running = self.num_chosen != self.executed_watermark
        old_watermark = self.executed_watermark
        if self.log.get(msg.slot) is not None:
            return
        self.log.put(msg.slot, (msg.kind, msg.command))
        self.num_chosen += 1
        self._execute_log()
        if self.options.unsafe_dont_recover:
            return
        should_run = self.num_chosen != self.executed_watermark
        moved = old_watermark != self.executed_watermark
        if was_running:
            if should_run and moved:
                self.recover_timer.reset()
            elif not should_run:
                self.recover_timer.stop()
        elif should_run:
            self.recover_timer.start()


# -- Client -------------------------------------------------------------------


@dataclasses.dataclass
class _MmmPending:
    id: int
    command: bytes
    result: Promise
    resend: object


class MmmClient(Actor):
    """``matchmakermultipaxos/Client.scala``: tracks the leader's round;
    NotLeader triggers LeaderInfoRequests to every leader."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig,
                 resend_period: float = 10.0, stutter: int = 1000,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        # The SAME stuttered round system as the leaders (Client.scala:
        # 107-109) — a plain round-robin would compute the wrong leader
        # for every round inside a stutter run.
        self.round_system = ClassicStutteredRoundRobin(
            len(config.leader_addresses), stutter
        )
        self.round = 0
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _MmmPending] = {}

    def _leader_chan(self):
        return self.chan(
            self.config.leader_addresses[
                self.round_system.leader(self.round)
            ]
        )

    def _request(self, pseudonym: int, pending: _MmmPending):
        return MmmClientRequest(
            command=MmmCommand(
                command_id=MmmCommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=pending.id,
                ),
                command=pending.command,
            )
        )

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1

        def resend() -> None:
            pending = self.pending.get(pseudonym)
            if pending is not None:
                # Broadcast to every leader: our round guess may be stale.
                request = self._request(pseudonym, pending)
                for a in self.config.leader_addresses:
                    self.chan(a).send(request)
            timer.start()

        timer = self.timer(f"resendMmm{pseudonym}", self.resend_period, resend)
        timer.start()
        pending = _MmmPending(
            id=id, command=command, result=promise, resend=timer
        )
        self.pending[pseudonym] = pending
        self._leader_chan().send(self._request(pseudonym, pending))
        return promise

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmmClientReply):
            pending = self.pending.get(msg.command_id.client_pseudonym)
            if pending is None or msg.command_id.client_id != pending.id:
                return
            pending.resend.stop()
            del self.pending[msg.command_id.client_pseudonym]
            pending.result.success(msg.result)
        elif isinstance(msg, MmmNotLeader):
            request = MmmLeaderInfoRequest()
            for a in self.config.leader_addresses:
                self.chan(a).send(request)
        elif isinstance(msg, MmmLeaderInfoReply):
            if msg.round > self.round:
                self.round = msg.round
                for pseudonym, pending in self.pending.items():
                    self._leader_chan().send(
                        self._request(pseudonym, pending)
                    )
        else:
            self.logger.fatal(f"unknown mmm client message {msg!r}")


# -- Driver -------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DoNothing:
    pass


@dataclasses.dataclass(frozen=True)
class RepeatedLeaderReconfiguration:
    period: float = 1.0


@dataclasses.dataclass(frozen=True)
class MatchmakerReconfigurationWorkload:
    period: float = 1.0


@dataclasses.dataclass(frozen=True)
class LeaderFailure:
    failure_delay: float = 5.0


@dataclasses.dataclass(frozen=True)
class Chaos:
    period: float = 1.0


class MmmDriver(Actor):
    """``matchmakermultipaxos/Driver.scala``: an ACTOR that injects
    failures and reconfigurations on a schedule. Sim tests fire its
    timers deterministically; real deployments let them run."""

    def __init__(self, address, transport, logger,
                 config: MatchmakerMultiPaxosConfig, workload,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.workload = workload
        self.rng = random.Random(seed)
        self.num_acceptors = len(config.acceptor_addresses)
        self.num_matchmakers = len(config.matchmaker_addresses)

        def reconfigure() -> None:
            self.force_reconfiguration()
            self.reconfigure_timer.start()

        def matchmaker_reconfigure() -> None:
            self.force_matchmaker_reconfiguration()
            self.matchmaker_reconfigure_timer.start()

        def fail_leader() -> None:
            self.force_leader_change()

        self.reconfigure_timer = self.timer(
            "driverReconfigure", getattr(workload, "period", 1.0), reconfigure
        )
        self.matchmaker_reconfigure_timer = self.timer(
            "driverMatchmakerReconfigure", getattr(workload, "period", 1.0),
            matchmaker_reconfigure,
        )
        self.leader_failure_timer = self.timer(
            "driverLeaderFailure",
            getattr(workload, "failure_delay", 5.0), fail_leader,
        )
        if isinstance(workload, (RepeatedLeaderReconfiguration, Chaos)):
            self.reconfigure_timer.start()
        if isinstance(workload, (MatchmakerReconfigurationWorkload, Chaos)):
            self.matchmaker_reconfigure_timer.start()
        if isinstance(workload, (LeaderFailure, Chaos)):
            self.leader_failure_timer.start()

    def receive(self, src: Address, msg) -> None:
        self.logger.fatal("the driver does not receive messages")

    def force_reconfiguration(self, members: Optional[tuple] = None,
                              leader_index: int = 0) -> None:
        # One SPECIFIC leader (Driver.scala reconfigure(leader, ...)):
        # broadcasting would make inactive leaders grab leadership.
        if members is None:
            members = tuple(
                self.rng.sample(range(self.num_acceptors),
                                2 * self.config.f + 1)
            )
        self.chan(self.config.leader_addresses[leader_index]).send(
            MmmForceReconfiguration(acceptor_indices=members)
        )

    def force_matchmaker_reconfiguration(
        self, members: Optional[tuple] = None
    ) -> None:
        if members is None:
            members = tuple(
                self.rng.sample(range(self.num_matchmakers),
                                2 * self.config.f + 1)
            )
        reconfigurer = self.config.reconfigurer_addresses[
            self.rng.randrange(len(self.config.reconfigurer_addresses))
        ]
        self.chan(reconfigurer).send(
            MmmForceMatchmakerReconfiguration(matchmaker_indices=members)
        )

    def force_leader_change(self, leader_index: Optional[int] = None) -> None:
        if leader_index is None:
            leader_index = self.rng.randrange(
                len(self.config.leader_election_addresses)
            )
        self.chan(
            self.config.leader_election_addresses[leader_index]
        ).send(election.ForceNoPing())
