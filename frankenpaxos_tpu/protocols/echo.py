"""Echo — the tutorial protocol (reference ``echo/``: Server echoes every
request; the client pings on a timer and counts replies)."""

from __future__ import annotations

import dataclasses

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors


@wire.message
@dataclasses.dataclass(frozen=True)
class EchoRequest:
    msg: str


@wire.message
@dataclasses.dataclass(frozen=True)
class EchoReply:
    msg: str


class EchoServer(Actor):
    def __init__(self, address, transport, logger, collectors=None):
        super().__init__(address, transport, logger)
        collectors = collectors or FakeCollectors()
        self.num_messages_received = 0
        self.requests_total = collectors.counter(
            "echo_requests_total", "Total echo requests."
        )

    def receive(self, src: Address, msg) -> None:
        self.num_messages_received += 1
        self.requests_total.inc()
        self.chan(src).send(EchoReply(msg.msg))


class EchoClient(Actor):
    def __init__(self, address, transport, logger, server: Address,
                 ping_period: float = 1.0):
        super().__init__(address, transport, logger)
        self.server = server
        self.num_messages_received = 0
        self.ping_timer = self.timer("pingTimer", ping_period, self._ping)
        self.ping_timer.start()

    def _ping(self) -> None:
        self.chan(self.server).send(EchoRequest("ping"))
        self.ping_timer.start()

    def echo(self, msg: str) -> None:
        self.chan(self.server).send(EchoRequest(msg))

    def receive(self, src: Address, msg) -> None:
        self.num_messages_received += 1
