"""Unreplicated state machine — the performance-ceiling baseline
(reference ``unreplicated/``): one server runs a state machine; clients
send commands with (pseudonym, id) exactly-once bookkeeping and resend
timers; the server keeps a simple largest-id client table."""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.statemachine import StateMachine


@wire.message
@dataclasses.dataclass(frozen=True)
class UnrepCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class UnrepClientRequest:
    command_id: UnrepCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class UnrepClientReply:
    command_id: UnrepCommandId
    result: bytes


@dataclasses.dataclass(frozen=True)
class ServerOptions:
    flush_every_n: int = 1


class Server(Actor):
    def __init__(self, address, transport, logger,
                 state_machine: StateMachine,
                 options: ServerOptions = ServerOptions()):
        super().__init__(address, transport, logger)
        self.state_machine = state_machine
        self.options = options
        # (client address bytes, pseudonym) -> (largest id, cached output).
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}
        self._unflushed = 0
        self._clients: set = set()

    def receive(self, src: Address, msg) -> None:
        cid = msg.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None and cid.client_id < cached[0]:
            return  # stale
        if cached is not None and cid.client_id == cached[0]:
            result = cached[1]  # resend cached reply
        else:
            result = self.state_machine.run(msg.command)
            self.client_table[key] = (cid.client_id, result)
        reply = UnrepClientReply(command_id=cid, result=result)
        if self.options.flush_every_n == 1:
            self.chan(src).send(reply)
        else:
            self._clients.add(src)
            self.chan(src).send_no_flush(reply)
            self._unflushed += 1
            if self._unflushed >= self.options.flush_every_n:
                # Flush EVERY client channel, not just the current sender's
                # (cf. unreplicated/Server.scala: clients.values.foreach(flush)).
                for client in self._clients:
                    self.flush(client)
                self._unflushed = 0


@dataclasses.dataclass(frozen=True)
class ClientOptions:
    resend_client_request_period: float = 10.0


@dataclasses.dataclass
class PendingWrite:
    id: int
    command: bytes
    result: Promise
    resend: object


class Client(Actor):
    def __init__(self, address, transport, logger, server: Address,
                 options: ClientOptions = ClientOptions()):
        super().__init__(address, transport, logger)
        self.server = server
        self.options = options
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, PendingWrite] = {}
        self.address_bytes = transport.address_to_bytes(address)

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(
                f"pseudonym {pseudonym} already has a pending request"
            ))
            return promise
        id = self.ids.get(pseudonym, 0)
        request = UnrepClientRequest(
            command_id=UnrepCommandId(self.address_bytes, pseudonym, id),
            command=command,
        )
        self.chan(self.server).send(request)

        def resend() -> None:
            self.chan(self.server).send(request)
            timer.start()

        timer = self.timer(
            f"resendClientRequest{pseudonym}",
            self.options.resend_client_request_period,
            resend,
        )
        timer.start()
        self.pending[pseudonym] = PendingWrite(id, command, promise, timer)
        self.ids[pseudonym] = id + 1
        return promise

    def receive(self, src: Address, msg) -> None:
        pseudonym = msg.command_id.client_pseudonym
        pending = self.pending.get(pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return  # stale
        pending.resend.stop()
        del self.pending[pseudonym]
        pending.result.success(msg.result)
