"""Matchmaker Paxos — the reconfiguration core (reference
``matchmakerpaxos/``: Client, Leader, Matchmaker, Acceptor; VLDB '20).

Single-decree Paxos where each round's acceptor configuration (a whole
read-write quorum system) is chosen ON THE FLY and registered with a
quorum of matchmakers. A leader starting round r sends its proposed
quorum system to the matchmakers (MatchRequest); a matchmaker replies
with every configuration it has seen for earlier rounds (MatchReply) and
refuses stale rounds (MatchmakerNack, Matchmaker.scala:116-170). The
leader then runs phase 1 against a read quorum OF EVERY prior
configuration (Leader.handleMatchReply/handlePhase1b: pendingRounds
empties as read quorums complete), picks the max-vote-round value, and
runs phase 2 against a write quorum of its own new configuration. This is
the machinery Matchmaker MultiPaxos reconfigures acceptor sets with.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Set

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.quorums import (
    QuorumSystemProto,
    SimpleMajority,
    UnanimousWrites,
    from_proto,
    to_proto,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@wire.message
@dataclasses.dataclass(frozen=True)
class MmClientRequest:
    v: str


@wire.message
@dataclasses.dataclass(frozen=True)
class MmClientReply:
    chosen: str


@wire.message
@dataclasses.dataclass(frozen=True)
class MmAcceptorGroup:
    round: int
    quorum_system: QuorumSystemProto


@wire.message
@dataclasses.dataclass(frozen=True)
class MmMatchRequest:
    acceptor_group: MmAcceptorGroup


@wire.message
@dataclasses.dataclass(frozen=True)
class MmMatchReply:
    round: int
    matchmaker_index: int
    acceptor_groups: tuple  # every MmAcceptorGroup seen for earlier rounds


@wire.message
@dataclasses.dataclass(frozen=True)
class MmMatchmakerNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmPhase1a:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmPhase1b:
    round: int
    acceptor_index: int
    vote_round: int
    vote_value: Optional[str]


@wire.message
@dataclasses.dataclass(frozen=True)
class MmPhase2a:
    round: int
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class MmPhase2b:
    round: int
    acceptor_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class MmAcceptorNack:
    round: int


@dataclasses.dataclass(frozen=True)
class MatchmakerPaxosConfig:
    f: int
    client_addresses: tuple
    leader_addresses: tuple
    matchmaker_addresses: tuple
    acceptor_addresses: tuple

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    @property
    def num_acceptors(self) -> int:
        return len(self.acceptor_addresses)

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.matchmaker_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 matchmakers")
        if self.num_acceptors < self.f + 1:
            raise ValueError("need >= f+1 acceptors")


_INACTIVE = "inactive"


@dataclasses.dataclass
class _Matchmaking:
    v: str
    quorum_system: object
    match_replies: Dict[int, MmMatchReply]


@dataclasses.dataclass
class _MmPhase1:
    v: str
    quorum_system: object
    previous_quorum_systems: Dict[int, object]
    acceptor_to_rounds: Dict[int, Set[int]]
    pending_rounds: Set[int]
    phase1bs: Dict[int, MmPhase1b]


@dataclasses.dataclass
class _MmPhase2:
    v: str
    quorum_system: object
    phase2bs: Dict[int, MmPhase2b]


@dataclasses.dataclass
class _MmChosen:
    v: str


class MmLeader(Actor):
    def __init__(self, address, transport, logger,
                 config: MatchmakerPaxosConfig, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = -1
        self.state = _INACTIVE
        self.clients: List[Address] = []

    def _random_quorum_system(self):
        """A fresh configuration over a random subset of the acceptors
        (Leader.getRandomQuorumSystem): either a simple majority over
        2f+1 of them or unanimous writes over f+1."""
        n = self.config.num_acceptors
        indices = list(range(n))
        self.rng.shuffle(indices)
        if n >= 2 * self.config.f + 1 and self.rng.random() < 0.5:
            qs = SimpleMajority(
                set(indices[: 2 * self.config.f + 1]),
                seed=self.rng.randrange(2**31),
            )
        else:
            qs = UnanimousWrites(
                set(indices[: self.config.quorum_size]),
                seed=self.rng.randrange(2**31),
            )
        return qs, to_proto(qs)

    def _start_matchmaking(self, new_round: int, v: str) -> None:
        self.round = new_round
        qs, qs_proto = self._random_quorum_system()
        request = MmMatchRequest(
            acceptor_group=MmAcceptorGroup(
                round=self.round, quorum_system=qs_proto
            )
        )
        for matchmaker in self.config.matchmaker_addresses:
            self.chan(matchmaker).send(request)
        self.state = _Matchmaking(v=v, quorum_system=qs, match_replies={})

    def _handle_nack_round(self, nack_round: int) -> None:
        if nack_round <= self.round:
            return
        if self.state == _INACTIVE or isinstance(self.state, _MmChosen):
            return
        v = self.state.v
        self._start_matchmaking(
            self.round_system.next_classic_round(self.index, nack_round), v
        )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, MmMatchReply):
            self._handle_match_reply(msg)
        elif isinstance(msg, MmPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, MmPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, (MmMatchmakerNack, MmAcceptorNack)):
            self._handle_nack_round(msg.round)
        else:
            self.logger.fatal(f"unknown matchmaker leader message {msg!r}")

    def _handle_client_request(self, src: Address, msg: MmClientRequest) -> None:
        if isinstance(self.state, _MmChosen):
            self.chan(src).send(MmClientReply(chosen=self.state.v))
            return
        if src not in self.clients:
            self.clients.append(src)
        self._start_matchmaking(
            self.round_system.next_classic_round(self.index, self.round), msg.v
        )

    def _handle_match_reply(self, msg: MmMatchReply) -> None:
        if not isinstance(self.state, _Matchmaking):
            return
        if msg.round != self.round:
            self.logger.check_lt(msg.round, self.round)
            return
        matchmaking = self.state
        matchmaking.match_replies[msg.matchmaker_index] = msg
        if len(matchmaking.match_replies) < self.config.quorum_size:
            return
        # Union of all previously-registered configurations: phase 1 must
        # read a quorum of EVERY one of them.
        pending_rounds: Set[int] = set()
        previous: Dict[int, object] = {}
        acceptor_indices: Set[int] = set()
        acceptor_to_rounds: Dict[int, Set[int]] = {}
        for reply in matchmaking.match_replies.values():
            for group in reply.acceptor_groups:
                pending_rounds.add(group.round)
                qs = from_proto(group.quorum_system)
                previous[group.round] = qs
                acceptor_indices |= qs.random_read_quorum()
                for index in qs.nodes():
                    acceptor_to_rounds.setdefault(index, set()).add(group.round)
        if not pending_rounds:
            # First configuration ever: straight to phase 2.
            for index in matchmaking.quorum_system.random_write_quorum():
                self.chan(self.config.acceptor_addresses[index]).send(
                    MmPhase2a(round=self.round, value=matchmaking.v)
                )
            self.state = _MmPhase2(
                v=matchmaking.v,
                quorum_system=matchmaking.quorum_system,
                phase2bs={},
            )
        else:
            for index in acceptor_indices:
                self.chan(self.config.acceptor_addresses[index]).send(
                    MmPhase1a(round=self.round)
                )
            self.state = _MmPhase1(
                v=matchmaking.v,
                quorum_system=matchmaking.quorum_system,
                previous_quorum_systems=previous,
                acceptor_to_rounds=acceptor_to_rounds,
                pending_rounds=pending_rounds,
                phase1bs={},
            )

    def _handle_phase1b(self, msg: MmPhase1b) -> None:
        if not isinstance(self.state, _MmPhase1):
            return
        if msg.round != self.round:
            self.logger.check_lt(msg.round, self.round)
            return
        phase1 = self.state
        phase1.phase1bs[msg.acceptor_index] = msg
        responded = set(phase1.phase1bs.keys())
        for round in list(phase1.acceptor_to_rounds.get(msg.acceptor_index, ())):
            if round in phase1.pending_rounds and phase1.previous_quorum_systems[
                round
            ].is_superset_of_read_quorum(responded):
                phase1.pending_rounds.discard(round)
        if phase1.pending_rounds:
            return
        votes = [
            b for b in phase1.phase1bs.values() if b.vote_value is not None
        ]
        v = (
            max(votes, key=lambda b: b.vote_round).vote_value
            if votes
            else phase1.v
        )
        for index in phase1.quorum_system.random_write_quorum():
            self.chan(self.config.acceptor_addresses[index]).send(
                MmPhase2a(round=self.round, value=v)
            )
        self.state = _MmPhase2(
            v=v, quorum_system=phase1.quorum_system, phase2bs={}
        )

    def _handle_phase2b(self, msg: MmPhase2b) -> None:
        if not isinstance(self.state, _MmPhase2):
            return
        if msg.round != self.round:
            self.logger.check_lt(msg.round, self.round)
            return
        phase2 = self.state
        phase2.phase2bs[msg.acceptor_index] = msg
        if not phase2.quorum_system.is_write_quorum(set(phase2.phase2bs.keys())):
            return
        for client in self.clients:
            self.chan(client).send(MmClientReply(chosen=phase2.v))
        self.state = _MmChosen(v=phase2.v)


class MmMatchmaker(Actor):
    def __init__(self, address, transport, logger,
                 config: MatchmakerPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.matchmaker_addresses)
        self.config = config
        self.index = config.matchmaker_addresses.index(address)
        # round -> MmAcceptorGroup, insertion-ordered by round.
        self.acceptor_groups: Dict[int, MmAcceptorGroup] = {}

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, MmMatchRequest):
            self.logger.fatal(f"unknown matchmaker message {msg!r}")
        if (
            self.acceptor_groups
            and msg.acceptor_group.round <= max(self.acceptor_groups)
        ):
            self.chan(src).send(
                MmMatchmakerNack(round=max(self.acceptor_groups))
            )
            return
        self.chan(src).send(
            MmMatchReply(
                round=msg.acceptor_group.round,
                matchmaker_index=self.index,
                acceptor_groups=tuple(
                    self.acceptor_groups[r]
                    for r in sorted(self.acceptor_groups)
                ),
            )
        )
        self.acceptor_groups[msg.acceptor_group.round] = msg.acceptor_group


class MmAcceptor(Actor):
    def __init__(self, address, transport, logger,
                 config: MatchmakerPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[str] = None

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, MmPhase1a):
            if msg.round < self.round:
                self.chan(src).send(MmAcceptorNack(round=self.round))
                return
            self.round = msg.round
            self.chan(src).send(
                MmPhase1b(
                    round=msg.round,
                    acceptor_index=self.index,
                    vote_round=self.vote_round,
                    vote_value=self.vote_value,
                )
            )
        elif isinstance(msg, MmPhase2a):
            if msg.round < self.round:
                self.chan(src).send(MmAcceptorNack(round=self.round))
                return
            self.round = msg.round
            self.vote_round = msg.round
            self.vote_value = msg.value
            self.chan(src).send(
                MmPhase2b(round=msg.round, acceptor_index=self.index)
            )
        else:
            self.logger.fatal(f"unknown matchmaker acceptor message {msg!r}")


class MmClient(Actor):
    def __init__(self, address, transport, logger,
                 config: MatchmakerPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.chosen: Optional[str] = None
        self.promise: Optional[Promise] = None
        self._request: Optional[MmClientRequest] = None
        self.resend_timer = self.timer(
            "resendClientRequest", resend_period, self._resend
        )

    def propose(self, v: str) -> Promise:
        promise = Promise()
        if self.chosen is not None:
            promise.success(self.chosen)
            return promise
        if self.promise is not None:
            promise.failure(RuntimeError("proposal already pending"))
            return promise
        self.promise = promise
        self._request = MmClientRequest(v=v)
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))
        ]
        self.chan(leader).send(self._request)
        self.resend_timer.start()
        return promise

    def _resend(self) -> None:
        if self.chosen is None and self._request is not None:
            leader = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))
            ]
            self.chan(leader).send(self._request)
            self.resend_timer.start()

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, MmClientReply):
            self.logger.fatal(f"unknown matchmaker client message {msg!r}")
        if self.chosen is None:
            self.chosen = msg.chosen
            self.resend_timer.stop()
            if self.promise is not None:
                self.promise.success(self.chosen)
                self.promise = None
        else:
            self.logger.check_eq(self.chosen, msg.chosen)
