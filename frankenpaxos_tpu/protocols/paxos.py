"""Single-decree Paxos — the pedagogical protocol (reference ``paxos/``:
Client, Leader, Acceptor choosing exactly one value).

Leaders own rounds via ClassicRoundRobin; phase 1 collects promises from a
majority (with prior votes), phase 2 proposes the safe value (highest vote
round, else the client's), and a majority of phase-2b votes chooses it.
Nacks fast-forward a leader to a later round.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Sequence

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosProposeRequest:
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosProposeReply:
    chosen: str


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosPhase1a:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosPhase1b:
    round: int
    acceptor_index: int
    vote_round: int
    vote_value: Optional[str]


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosPhase2a:
    round: int
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosPhase2b:
    round: int
    acceptor_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosChosen:
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class PaxosNack:
    round: int


@dataclasses.dataclass(frozen=True)
class PaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple
    client_addresses: tuple = ()

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError(f"f must be >= 1, got {self.f}")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need at least f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")


class PaxosClient(Actor):
    def __init__(self, address, transport, logger, config: PaxosConfig,
                 resend_period: float = 10.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.chosen: Optional[str] = None
        self.promise: Optional[Promise] = None
        self._request: Optional[PaxosProposeRequest] = None
        self.resend_timer = self.timer(
            "resendProposeRequest", resend_period, self._resend
        )

    def propose(self, value: str) -> Promise:
        promise = Promise()
        if self.chosen is not None:
            promise.success(self.chosen)
            return promise
        if self.promise is not None:
            promise.failure(RuntimeError("propose already pending"))
            return promise
        self.promise = promise
        self._request = PaxosProposeRequest(value)
        self.chan(self.config.leader_addresses[0]).send(self._request)
        self.resend_timer.start()
        return promise

    def _resend(self) -> None:
        if self.chosen is None and self._request is not None:
            for leader in self.config.leader_addresses:
                self.chan(leader).send(self._request)
            self.resend_timer.start()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, PaxosChosen):
            if self.chosen is None:
                self.chosen = msg.value
                self.resend_timer.stop()
                if self.promise is not None:
                    self.promise.success(self.chosen)
                    self.promise = None
        else:
            self.logger.fatal(f"unknown client message {msg!r}")


@dataclasses.dataclass
class _Phase1:
    value: str  # the client value we want chosen
    phase1bs: Dict[int, PaxosPhase1b]


@dataclasses.dataclass
class _Phase2:
    value: str
    phase2bs: Dict[int, PaxosPhase2b]


class PaxosLeader(Actor):
    def __init__(self, address, transport, logger, config: PaxosConfig,
                 seed: int = 0, resend_period: float = 5.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.leader_addresses).index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = -1
        self.state = None  # None | _Phase1 | _Phase2
        self.chosen: Optional[str] = None
        self.clients: List[Address] = []
        self.rng = random.Random(seed)
        self.resend_timer = self.timer(
            "resendPhase1a", resend_period, self._resend_phase
        )

    def _acceptor_chans(self):
        return [self.chan(a) for a in self.config.acceptor_addresses]

    def _resend_phase(self) -> None:
        if self.chosen is not None:
            return
        if isinstance(self.state, _Phase1):
            for ch in self._acceptor_chans():
                ch.send(PaxosPhase1a(self.round))
            self.resend_timer.start()
        elif isinstance(self.state, _Phase2):
            for ch in self._acceptor_chans():
                ch.send(PaxosPhase2a(self.round, self.state.value))
            self.resend_timer.start()

    def _start_phase1(self, value: str) -> None:
        self.round = self.round_system.next_classic_round(self.index, self.round)
        self.state = _Phase1(value=value, phase1bs={})
        for ch in self._acceptor_chans():
            ch.send(PaxosPhase1a(self.round))
        self.resend_timer.reset()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, PaxosProposeRequest):
            self._handle_propose(src, msg)
        elif isinstance(msg, PaxosPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, PaxosPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, PaxosNack):
            self._handle_nack(msg)
        elif isinstance(msg, PaxosChosen):
            self._handle_chosen(msg)
        else:
            self.logger.fatal(f"unknown leader message {msg!r}")

    def _handle_propose(self, src: Address, msg: PaxosProposeRequest) -> None:
        if src not in self.clients:
            self.clients.append(src)
        if self.chosen is not None:
            self.chan(src).send(PaxosChosen(self.chosen))
            return
        if self.state is None:
            self._start_phase1(msg.value)

    def _handle_phase1b(self, msg: PaxosPhase1b) -> None:
        if not isinstance(self.state, _Phase1) or msg.round != self.round:
            return
        self.state.phase1bs[msg.acceptor_index] = msg
        if len(self.state.phase1bs) < self.config.f + 1:
            return
        # Choose the safe value: highest vote round's value, else ours.
        votes = [b for b in self.state.phase1bs.values() if b.vote_value is not None]
        value = (
            max(votes, key=lambda b: b.vote_round).vote_value
            if votes
            else self.state.value
        )
        self.state = _Phase2(value=value, phase2bs={})
        for ch in self._acceptor_chans():
            ch.send(PaxosPhase2a(self.round, value))
        self.resend_timer.reset()

    def _handle_phase2b(self, msg: PaxosPhase2b) -> None:
        if not isinstance(self.state, _Phase2) or msg.round != self.round:
            return
        self.state.phase2bs[msg.acceptor_index] = msg
        if len(self.state.phase2bs) < self.config.f + 1:
            return
        self.chosen = self.state.value
        self.state = None
        self.resend_timer.stop()
        for client in self.clients:
            self.chan(client).send(PaxosChosen(self.chosen))
        for leader in self.config.leader_addresses:
            if leader != self.address:
                self.chan(leader).send(PaxosChosen(self.chosen))

    def _handle_nack(self, msg: PaxosNack) -> None:
        if msg.round <= self.round or self.chosen is not None:
            return
        value = self.state.value if self.state is not None else None
        self.round = msg.round
        if value is not None:
            self._start_phase1(value)

    def _handle_chosen(self, msg: PaxosChosen) -> None:
        if self.chosen is None:
            self.chosen = msg.value
            self.state = None
            self.resend_timer.stop()
            for client in self.clients:
                self.chan(client).send(PaxosChosen(self.chosen))


class PaxosAcceptor(Actor):
    def __init__(self, address, transport, logger, config: PaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = list(config.acceptor_addresses).index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[str] = None

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, PaxosPhase1a):
            if msg.round < self.round:
                self.chan(src).send(PaxosNack(self.round))
                return
            self.round = msg.round
            self.chan(src).send(
                PaxosPhase1b(
                    round=self.round,
                    acceptor_index=self.index,
                    vote_round=self.vote_round,
                    vote_value=self.vote_value,
                )
            )
        elif isinstance(msg, PaxosPhase2a):
            if msg.round < self.round:
                self.chan(src).send(PaxosNack(self.round))
                return
            self.round = msg.round
            self.vote_round = msg.round
            self.vote_value = msg.value
            self.chan(src).send(
                PaxosPhase2b(round=msg.round, acceptor_index=self.index)
            )
        else:
            self.logger.fatal(f"unknown acceptor message {msg!r}")
