"""Horizontal MultiPaxos — log-segmented ("horizontal") reconfiguration
(reference ``horizontal/``; protocol cheatsheet in ``Horizontal.proto``).

The log is divided into CHUNKS, each owned by one acceptor
configuration. Reconfiguration is just another log value: choosing a
``Configuration`` at slot s creates a new chunk starting at slot
s + alpha (the pipeline depth), so at most alpha commands can be in
flight past the chosen watermark and every slot's owning configuration
is determined by the log itself (``Leader.scala:216-247, 575-640``).
The active leader runs phase 1 per chunk and phase 2 into the first
chunk with vacant slots; a chunk whose last slot is chosen becomes
defunct and is pruned. Replicas execute commands (skipping noops and
configurations) and recover holes through other replicas, then leaders.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.election import basic as election
from frankenpaxos_tpu.quorums import SimpleMajority
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, random_duration

COMMAND = "command"
NOOP = "noop"
CONFIGURATION = "configuration"


@wire.message
@dataclasses.dataclass(frozen=True)
class HzCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class HzCommand:
    command_id: HzCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class HzValue:
    kind: str
    command: Optional[HzCommand] = None
    members: Optional[tuple] = None  # CONFIGURATION: SimpleMajority members


@wire.message
@dataclasses.dataclass(frozen=True)
class HzPhase1a:
    round: int
    first_slot: int
    chosen_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class HzPhase1b:
    round: int
    first_slot: int
    acceptor_index: int
    info: tuple  # of (slot, vote_round, HzValue)


@wire.message
@dataclasses.dataclass(frozen=True)
class HzClientRequest:
    command: HzCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class HzPhase2a:
    slot: int
    round: int
    first_slot: int
    value: HzValue


@wire.message
@dataclasses.dataclass(frozen=True)
class HzPhase2b:
    slot: int
    round: int
    acceptor_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class HzChosen:
    slot: int
    value: HzValue


@wire.message
@dataclasses.dataclass(frozen=True)
class HzClientReply:
    command_id: HzCommandId
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class HzReconfigure:
    members: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class HzNotLeader:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class HzLeaderInfoRequest:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class HzLeaderInfoReply:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class HzNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class HzRecover:
    slot: int


@dataclasses.dataclass(frozen=True)
class HorizontalConfig:
    f: int
    leader_addresses: tuple
    leader_election_addresses: tuple
    acceptor_addresses: tuple  # >= 2f+1 (spares allow reconfiguration)
    replica_addresses: tuple  # >= f+1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.leader_election_addresses) != len(self.leader_addresses):
            raise ValueError("one election address per leader")
        if len(self.acceptor_addresses) < 2 * self.f + 1:
            raise ValueError("need >= 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")


# -- Leader -------------------------------------------------------------------


@dataclasses.dataclass
class _HzPhase1:
    phase1bs: Dict[int, HzPhase1b]
    resend: object


@dataclasses.dataclass
class _HzPhase2:
    next_slot: Optional[int]  # None = chunk is out of slots
    values: Dict[int, HzValue]
    phase2bs: Dict[int, Dict[int, HzPhase2b]]
    resend: object


@dataclasses.dataclass
class _Chunk:
    first_slot: int
    last_slot: Optional[int]
    quorum: SimpleMajority
    phase: object


@dataclasses.dataclass
class _HzActive:
    round: int
    chunks: List[_Chunk]


@dataclasses.dataclass
class _HzInactive:
    round: int


@dataclasses.dataclass(frozen=True)
class HzLeaderOptions:
    # A chosen configuration at slot s takes effect at slot s + alpha; at
    # most alpha commands may be pending past the chosen watermark
    # (Leader.scala options).
    alpha: int = 16
    resend_phase1as_period: float = 5.0
    resend_phase2as_period: float = 5.0
    log_grow_size: int = 5000
    election_options: election.ElectionOptions = election.ElectionOptions()


class HzLeader(Actor):
    """``horizontal/Leader.scala``."""

    def __init__(self, address, transport, logger, config: HorizontalConfig,
                 options: HzLeaderOptions = HzLeaderOptions(), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.log: BufferMap = BufferMap(options.log_grow_size)
        self.chosen_watermark = 0
        # The first slots of all active chunks; maintained by active AND
        # inactive leaders (Leader.scala:289-296).
        self.active_first_slots: List[int] = [0]
        self.election = election.Participant(
            config.leader_election_addresses[self.index],
            transport, logger, config.leader_election_addresses,
            initial_leader_index=0,
            options=options.election_options, seed=seed,
        )
        self.election.register(self._on_election)
        if self.index == 0:
            quorum = SimpleMajority(set(range(2 * config.f + 1)))
            self.state: object = _HzActive(
                round=0,
                chunks=[self._make_chunk(0, 0, quorum)],
            )
        else:
            self.state = _HzInactive(round=-1)

    def _on_election(self, leader_index: int) -> None:
        if leader_index == self.index:
            if isinstance(self.state, _HzInactive):
                self.become_leader(self._next_round())
        else:
            self.stop_being_leader()

    # -- Helpers -------------------------------------------------------------

    def _get_round(self) -> int:
        return self.state.round

    def _next_round(self) -> int:
        return self.round_system.next_classic_round(
            self.index, self._get_round()
        )

    def _get_chunk(self, chunks: List[_Chunk],
                   slot: int) -> Optional[Tuple[int, _Chunk]]:
        for i in range(len(chunks) - 1, -1, -1):
            if slot >= chunks[i].first_slot:
                return (i, chunks[i])
        return None

    def _stop_phase_timers(self, phase) -> None:
        phase.resend.stop()

    def _stop_timers(self, state) -> None:
        if isinstance(state, _HzActive):
            for chunk in state.chunks:
                self._stop_phase_timers(chunk.phase)

    def _make_chunk(self, round: int, first_slot: int,
                    quorum: SimpleMajority) -> _Chunk:
        phase1a = HzPhase1a(
            round=round, first_slot=first_slot,
            chosen_watermark=self.chosen_watermark,
        )

        def send() -> None:
            for i in quorum.nodes():
                self.chan(self.config.acceptor_addresses[i]).send(phase1a)

        send()

        def resend() -> None:
            send()
            timer.start()

        timer = self.timer(
            f"resendPhase1as{first_slot}",
            self.options.resend_phase1as_period, resend,
        )
        timer.start()
        return _Chunk(
            first_slot=first_slot, last_slot=None, quorum=quorum,
            phase=_HzPhase1(phase1bs={}, resend=timer),
        )

    def _make_phase2_timer(self, chunk_first_slot: int,
                           quorum: SimpleMajority, values: Dict[int, HzValue]):
        def resend() -> None:
            # Drive the first few unchosen slots (Leader.scala:358-394).
            for slot in range(self.chosen_watermark,
                              self.chosen_watermark + 10):
                value = values.get(slot)
                if value is None:
                    continue
                phase2a = HzPhase2a(
                    slot=slot, round=self._get_round(),
                    first_slot=chunk_first_slot, value=value,
                )
                for i in quorum.nodes():
                    self.chan(self.config.acceptor_addresses[i]).send(phase2a)
            timer.start()

        timer = self.timer(
            f"resendPhase2as{chunk_first_slot}",
            self.options.resend_phase2as_period, resend,
        )
        timer.start()
        return timer

    def _safe_value(self, phase1bs, slot: int) -> HzValue:
        infos = [
            info for b in phase1bs for info in b.info if info[0] == slot
        ]
        if not infos:
            return HzValue(kind=NOOP)
        return max(infos, key=lambda info: info[1])[2]

    def _choose(self, slot: int, value: HzValue) -> List[Tuple[int, tuple]]:
        """Record a chosen value and advance the watermark; returns any
        newly chosen configurations as (slot, members)
        (Leader.scala:460-505)."""
        self.log.put(slot, value)
        configurations = []
        while True:
            entry = self.log.get(self.chosen_watermark)
            if entry is None:
                return configurations
            s = self.chosen_watermark
            self.chosen_watermark += 1
            if entry.kind == CONFIGURATION:
                self.active_first_slots.append(s + self.options.alpha)
                configurations.append((s, entry.members))
            if (
                len(self.active_first_slots) >= 2
                and s == self.active_first_slots[1]
            ):
                self.active_first_slots.pop(0)

    def stop_being_leader(self) -> None:
        self._stop_timers(self.state)
        self.state = _HzInactive(round=self._get_round())

    def become_leader(self, new_round: int) -> None:
        self.logger.check_gt(new_round, self._get_round())
        self.logger.check_eq(self.round_system.leader(new_round), self.index)
        self._stop_timers(self.state)
        first_slot = self.active_first_slots[0]
        if first_slot == 0:
            quorum = SimpleMajority(set(range(2 * self.config.f + 1)))
        else:
            # The chunk's configuration was chosen at first_slot - alpha.
            entry = self.log.get(first_slot - self.options.alpha)
            self.logger.check(entry is not None)
            self.logger.check_eq(entry.kind, CONFIGURATION)
            quorum = SimpleMajority(set(entry.members))
        self.state = _HzActive(
            round=new_round,
            chunks=[self._make_chunk(new_round, first_slot, quorum)],
        )

    def _propose(self, active: _HzActive, value: HzValue) -> None:
        """Propose into the first phase-2 chunk with a vacant slot,
        respecting the alpha pipeline bound (Leader.scala:575-640)."""
        for chunk in active.chunks:
            phase = chunk.phase
            if not isinstance(phase, _HzPhase2):
                continue
            if phase.next_slot is None:
                continue
            next_slot = phase.next_slot
            if next_slot >= self.chosen_watermark + self.options.alpha:
                return  # alpha overflow: drop (client resends)
            phase2a = HzPhase2a(
                slot=next_slot, round=active.round,
                first_slot=chunk.first_slot, value=value,
            )
            for i in chunk.quorum.nodes():
                self.chan(self.config.acceptor_addresses[i]).send(phase2a)
            phase.values[next_slot] = value
            phase.phase2bs[next_slot] = {}
            if chunk.last_slot is not None and next_slot == chunk.last_slot:
                phase.next_slot = None
            else:
                phase.next_slot = next_slot + 1
            return

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, HzPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, HzClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, HzPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, HzChosen):
            if isinstance(self.state, _HzInactive):
                self._choose(msg.slot, msg.value)
        elif isinstance(msg, HzReconfigure):
            if isinstance(self.state, _HzActive):
                self._propose(
                    self.state,
                    HzValue(kind=CONFIGURATION, members=tuple(msg.members)),
                )
        elif isinstance(msg, HzLeaderInfoRequest):
            if isinstance(self.state, _HzActive):
                self.chan(src).send(
                    HzLeaderInfoReply(round=self.state.round)
                )
        elif isinstance(msg, HzNack):
            self._handle_nack(msg)
        elif isinstance(msg, HzRecover):
            self._handle_recover(msg)
        else:
            self.logger.fatal(f"unknown horizontal leader message {msg!r}")

    def _handle_phase1b(self, msg: HzPhase1b) -> None:
        state = self.state
        if not isinstance(state, _HzActive) or msg.round != state.round:
            return
        found = self._get_chunk(state.chunks, msg.first_slot)
        if found is None:
            return
        chunk_index, chunk = found
        if chunk.first_slot != msg.first_slot:
            return  # stale: from a chunk that no longer exists
        phase = chunk.phase
        if not isinstance(phase, _HzPhase1):
            return
        phase.phase1bs[msg.acceptor_index] = msg
        if not chunk.quorum.is_superset_of_read_quorum(set(phase.phase1bs)):
            return
        self._stop_phase_timers(phase)
        slots = [
            info[0] for b in phase.phase1bs.values() for info in b.info
        ]
        max_slot = max(slots, default=-1)
        values: Dict[int, HzValue] = {}
        phase2bs: Dict[int, Dict[int, HzPhase2b]] = {}
        for slot in range(max(msg.first_slot, self.chosen_watermark),
                          max_slot + 1):
            value = self._safe_value(phase.phase1bs.values(), slot)
            phase2a = HzPhase2a(
                slot=slot, round=state.round,
                first_slot=chunk.first_slot, value=value,
            )
            for i in chunk.quorum.nodes():
                self.chan(self.config.acceptor_addresses[i]).send(phase2a)
            values[slot] = value
            phase2bs[slot] = {}
        s = max(msg.first_slot, self.chosen_watermark, max_slot + 1)
        next_slot: Optional[int] = s
        if chunk.last_slot is not None and s > chunk.last_slot:
            next_slot = None
        chunk.phase = _HzPhase2(
            next_slot=next_slot, values=values, phase2bs=phase2bs,
            resend=self._make_phase2_timer(
                chunk.first_slot, chunk.quorum, values
            ),
        )

    def _handle_client_request(self, src: Address,
                               msg: HzClientRequest) -> None:
        if isinstance(self.state, _HzInactive):
            self.chan(src).send(HzNotLeader())
            return
        self._propose(self.state, HzValue(kind=COMMAND, command=msg.command))

    def _handle_phase2b(self, msg: HzPhase2b) -> None:
        state = self.state
        if not isinstance(state, _HzActive) or msg.round != state.round:
            return
        if msg.slot < self.chosen_watermark or self.log.get(msg.slot) is not None:
            return
        found = self._get_chunk(state.chunks, msg.slot)
        if found is None:
            return
        _, chunk = found
        phase = chunk.phase
        if not isinstance(phase, _HzPhase2):
            return
        in_slot = phase.phase2bs.setdefault(msg.slot, {})
        in_slot[msg.acceptor_index] = msg
        if not chunk.quorum.is_superset_of_write_quorum(set(in_slot)):
            return
        value = phase.values.get(msg.slot)
        if value is None:
            return
        chosen = HzChosen(slot=msg.slot, value=value)
        for a in self.config.replica_addresses:
            self.chan(a).send(chosen)
        for a in self.config.leader_addresses:
            if a != self.address:
                self.chan(a).send(chosen)
        phase.values.pop(msg.slot, None)
        phase.phase2bs.pop(msg.slot, None)
        old_watermark = self.chosen_watermark
        configurations = self._choose(msg.slot, value)
        if old_watermark != self.chosen_watermark:
            phase.resend.reset()
        # Open a new chunk per newly chosen configuration
        # (Leader.scala:930-975).
        for slot, members in configurations:
            last_slot = slot + self.options.alpha - 1
            previous = state.chunks[-1]
            previous.last_slot = last_slot
            if isinstance(previous.phase, _HzPhase2):
                if (
                    previous.phase.next_slot is not None
                    and previous.phase.next_slot > last_slot
                ):
                    previous.phase.next_slot = None
            state.chunks.append(
                self._make_chunk(
                    state.round, slot + self.options.alpha,
                    SimpleMajority(set(members)),
                )
            )
        # Prune defunct chunks.
        while state.chunks:
            chunk = state.chunks[0]
            if (
                chunk.last_slot is not None
                and chunk.last_slot < self.chosen_watermark
            ):
                self._stop_phase_timers(chunk.phase)
                state.chunks.pop(0)
            else:
                break

    def _handle_nack(self, msg: HzNack) -> None:
        if msg.round < self._get_round():
            return
        state = self.state
        if isinstance(state, _HzInactive):
            state.round = msg.round
        else:
            self.become_leader(
                self.round_system.next_classic_round(
                    self.index, max(msg.round, state.round)
                )
            )

    def _handle_recover(self, msg: HzRecover) -> None:
        state = self.state
        if isinstance(state, _HzInactive):
            return
        # Unlike Matchmaker MultiPaxos we cannot lower chosen_watermark
        # (active_first_slots and alpha depend on it); slots below it were
        # chosen and replicas recover them from each other
        # (Leader.scala:1069-1100).
        if self.chosen_watermark > msg.slot:
            return
        self.become_leader(self._next_round())


# -- Acceptor -----------------------------------------------------------------


class HzAcceptor(Actor):
    """``horizontal/Acceptor.scala``: one round across all slots; each
    vote remembers the first slot of its owning chunk so phase 1 only
    reports votes belonging to the requested chunk."""

    def __init__(self, address, transport, logger, config: HorizontalConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = -1
        # slot -> (first_slot, vote_round, value)
        self.states: Dict[int, Tuple[int, int, HzValue]] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, HzPhase1a):
            if msg.round < self.round:
                self.chan(src).send(HzNack(round=self.round))
                return
            self.round = msg.round
            start = max(msg.first_slot, msg.chosen_watermark)
            info = tuple(
                (slot, vote_round, value)
                for slot, (first_slot, vote_round, value) in sorted(
                    self.states.items()
                )
                if slot >= start and first_slot == msg.first_slot
            )
            self.chan(src).send(
                HzPhase1b(
                    round=self.round, first_slot=msg.first_slot,
                    acceptor_index=self.index, info=info,
                )
            )
        elif isinstance(msg, HzPhase2a):
            if msg.round < self.round:
                self.chan(src).send(HzNack(round=self.round))
                return
            self.round = msg.round
            self.states[msg.slot] = (msg.first_slot, msg.round, msg.value)
            self.chan(src).send(
                HzPhase2b(
                    slot=msg.slot, round=msg.round, acceptor_index=self.index
                )
            )
        else:
            self.logger.fatal(f"unknown horizontal acceptor message {msg!r}")


# -- Replica ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class HzReplicaOptions:
    log_grow_size: int = 5000
    recover_min_period: float = 10.0
    recover_max_period: float = 20.0
    unsafe_dont_recover: bool = False


class HzReplica(Actor):
    """``horizontal/Replica.scala``: executes commands in prefix order
    (noops and configurations are skipped), recovers holes via other
    replicas then leaders."""

    def __init__(self, address, transport, logger, config: HorizontalConfig,
                 state_machine: StateMachine,
                 options: HzReplicaOptions = HzReplicaOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.replica_addresses.index(address)
        self.log: BufferMap = BufferMap(options.log_grow_size)
        self.executed_watermark = 0
        self.num_chosen = 0
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}

        def recover() -> None:
            recover_msg = HzRecover(slot=self.executed_watermark)
            for a in self.config.replica_addresses:
                if a != self.address:
                    self.chan(a).send(recover_msg)
            for a in self.config.leader_addresses:
                self.chan(a).send(recover_msg)
            self.recover_timer.start()

        self.recover_timer = self.timer(
            "recover",
            random_duration(self.rng, options.recover_min_period,
                            options.recover_max_period),
            recover,
        )

    def _execute_command(self, slot: int, command: HzCommand) -> None:
        cid = command.command_id
        identity = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(identity)
        client = self.transport.address_from_bytes(cid.client_address)
        if cached is not None:
            if cid.client_id < cached[0]:
                return
            if cid.client_id == cached[0]:
                self.chan(client).send(
                    HzClientReply(command_id=cid, result=cached[1])
                )
                return
        result = self.state_machine.run(command.command)
        self.client_table[identity] = (cid.client_id, result)
        if slot % len(self.config.replica_addresses) == self.index:
            self.chan(client).send(
                HzClientReply(command_id=cid, result=result)
            )

    def _execute_log(self) -> None:
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return
            if value.kind == COMMAND:
                self._execute_command(self.executed_watermark, value.command)
            self.executed_watermark += 1

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, HzChosen):
            self._handle_chosen(msg)
        elif isinstance(msg, HzRecover):
            value = self.log.get(msg.slot)
            if value is not None:
                self.chan(src).send(HzChosen(slot=msg.slot, value=value))
        else:
            self.logger.fatal(f"unknown horizontal replica message {msg!r}")

    def _handle_chosen(self, msg: HzChosen) -> None:
        was_running = self.num_chosen != self.executed_watermark
        old_watermark = self.executed_watermark
        if self.log.get(msg.slot) is not None:
            return
        self.log.put(msg.slot, msg.value)
        self.num_chosen += 1
        self._execute_log()
        if self.options.unsafe_dont_recover:
            return
        should_run = self.num_chosen != self.executed_watermark
        moved = old_watermark != self.executed_watermark
        if was_running:
            if should_run and moved:
                self.recover_timer.reset()
            elif not should_run:
                self.recover_timer.stop()
        elif should_run:
            self.recover_timer.start()


# -- Client -------------------------------------------------------------------


@dataclasses.dataclass
class _HzPending:
    id: int
    command: bytes
    result: Promise
    resend: object


class HzClient(Actor):
    """``horizontal/Client.scala``."""

    def __init__(self, address, transport, logger, config: HorizontalConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = 0
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _HzPending] = {}

    def _request(self, pseudonym: int, pending: _HzPending):
        return HzClientRequest(
            command=HzCommand(
                command_id=HzCommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=pending.id,
                ),
                command=pending.command,
            )
        )

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1

        def resend() -> None:
            pending = self.pending.get(pseudonym)
            if pending is not None:
                request = self._request(pseudonym, pending)
                for a in self.config.leader_addresses:
                    self.chan(a).send(request)
            timer.start()

        timer = self.timer(f"resendHz{pseudonym}", self.resend_period, resend)
        timer.start()
        pending = _HzPending(
            id=id, command=command, result=promise, resend=timer
        )
        self.pending[pseudonym] = pending
        leader = self.config.leader_addresses[
            self.round_system.leader(self.round)
        ]
        self.chan(leader).send(self._request(pseudonym, pending))
        return promise

    def reconfigure(self, members: tuple) -> None:
        """Ask the current leader to reconfigure to a new acceptor set."""
        leader = self.config.leader_addresses[
            self.round_system.leader(self.round)
        ]
        self.chan(leader).send(HzReconfigure(members=tuple(members)))

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, HzClientReply):
            pending = self.pending.get(msg.command_id.client_pseudonym)
            if pending is None or msg.command_id.client_id != pending.id:
                return
            pending.resend.stop()
            del self.pending[msg.command_id.client_pseudonym]
            pending.result.success(msg.result)
        elif isinstance(msg, HzNotLeader):
            request = HzLeaderInfoRequest()
            for a in self.config.leader_addresses:
                self.chan(a).send(request)
        elif isinstance(msg, HzLeaderInfoReply):
            if msg.round > self.round:
                self.round = msg.round
                for pseudonym, pending in self.pending.items():
                    leader = self.config.leader_addresses[
                        self.round_system.leader(self.round)
                    ]
                    self.chan(leader).send(
                        self._request(pseudonym, pending)
                    )
        else:
            self.logger.fatal(f"unknown horizontal client message {msg!r}")


# -- Driver -------------------------------------------------------------------


class HzDriver(Actor):
    """``horizontal/Driver.scala``: injects reconfigurations and leader
    failures — on a repeating schedule when ``schedule=True`` (the
    reference's RepeatedReconfiguration workload), or manually via
    ``force_reconfiguration`` / ``force_leader_change``."""

    def __init__(self, address, transport, logger, config: HorizontalConfig,
                 period: float = 1.0, schedule: bool = False, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)

        def fire() -> None:
            self.force_reconfiguration()
            self.reconfigure_timer.start()

        self.reconfigure_timer = self.timer(
            "driverReconfigure", period, fire
        )
        if schedule:
            self.reconfigure_timer.start()

    def receive(self, src: Address, msg) -> None:
        self.logger.fatal("the driver does not receive messages")

    def force_reconfiguration(self, members: Optional[tuple] = None,
                              leader_index: int = 0) -> None:
        if members is None:
            members = tuple(
                self.rng.sample(range(len(self.config.acceptor_addresses)),
                                2 * self.config.f + 1)
            )
        self.chan(self.config.leader_addresses[leader_index]).send(
            HzReconfigure(members=members)
        )

    def force_leader_change(self, leader_index: Optional[int] = None) -> None:
        if leader_index is None:
            leader_index = self.rng.randrange(
                len(self.config.leader_election_addresses)
            )
        self.chan(
            self.config.leader_election_addresses[leader_index]
        ).send(election.ForceNoPing())
