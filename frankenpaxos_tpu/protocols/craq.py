"""CRAQ — chain replication with apportioned queries (reference ``craq/``:
ChainNode, Client).

Writes enter at the head and flow down the chain; the tail applies and
replies, then acks flow back up and each node applies on ack
(``craq/ChainNode.scala:120-299``). Reads go to ANY node: if none of the
read keys have writes pending at that node the read is served locally
("clean"); otherwise it is forwarded to the tail ("dirty"), preserving
linearizability.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqWrite:
    command_id: CraqCommandId
    key: str
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqWriteBatch:
    writes: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqRead:
    command_id: CraqCommandId
    key: str


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqReadBatch:
    reads: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqAck:
    write_batch: CraqWriteBatch


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqTailRead:
    read_batch: CraqReadBatch


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqClientReply:
    command_id: CraqCommandId


@wire.message
@dataclasses.dataclass(frozen=True)
class CraqReadReply:
    command_id: CraqCommandId
    value: str


DEFAULT = "default"  # value of unwritten keys (ChainNode.scala:163)


@dataclasses.dataclass(frozen=True)
class CraqConfig:
    f: int
    chain_node_addresses: tuple

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.chain_node_addresses) < self.f + 1:
            raise ValueError("need >= f+1 chain nodes")


class ChainNode(Actor):
    def __init__(self, address, transport, logger, config: CraqConfig,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.chain_node_addresses.index(address)
        self.is_head = self.index == 0
        self.is_tail = self.index == len(config.chain_node_addresses) - 1
        self.pending_writes: List[CraqWriteBatch] = []
        self.state_machine: Dict[str, str] = {}
        self.versions = 0

    def _client(self, command_id: CraqCommandId) -> Address:
        return self.transport.address_from_bytes(command_id.client_address)

    def _process_write_batch(self, batch: CraqWriteBatch) -> None:
        self.pending_writes.append(batch)
        if not self.is_tail:
            nxt = self.config.chain_node_addresses[self.index + 1]
            self.chan(nxt).send(batch)
            return
        # Tail: apply, reply, ack back up the chain.
        for write in batch.writes:
            self.state_machine[write.key] = write.value
            self.chan(self._client(write.command_id)).send(
                CraqClientReply(command_id=write.command_id)
            )
            self.versions += 1
        self.pending_writes.remove(batch)
        if not self.is_head:
            prev = self.config.chain_node_addresses[self.index - 1]
            self.chan(prev).send(CraqAck(write_batch=batch))

    def _process_read_batch(self, batch: CraqReadBatch) -> None:
        dirty_keys = {
            w.key for pw in self.pending_writes for w in pw.writes
        }
        dirty_reads = []
        for read in batch.reads:
            if read.key in dirty_keys:
                dirty_reads.append(read)
            else:
                value = self.state_machine.get(read.key, DEFAULT)
                self.chan(self._client(read.command_id)).send(
                    CraqReadReply(command_id=read.command_id, value=value)
                )
                self.versions += 1
        if dirty_reads:
            tail = self.config.chain_node_addresses[-1]
            self.chan(tail).send(
                CraqTailRead(read_batch=CraqReadBatch(tuple(dirty_reads)))
            )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, CraqWrite):
            self._process_write_batch(CraqWriteBatch((msg,)))
        elif isinstance(msg, CraqWriteBatch):
            self._process_write_batch(msg)
        elif isinstance(msg, CraqRead):
            self._process_read_batch(CraqReadBatch((msg,)))
        elif isinstance(msg, CraqReadBatch):
            self._process_read_batch(msg)
        elif isinstance(msg, CraqTailRead):
            for read in msg.read_batch.reads:
                value = self.state_machine.get(read.key, DEFAULT)
                self.chan(self._client(read.command_id)).send(
                    CraqReadReply(command_id=read.command_id, value=value)
                )
                self.versions += 1
        elif isinstance(msg, CraqAck):
            self._handle_ack(msg)
        else:
            self.logger.fatal(f"unknown chain node message {msg!r}")

    def _handle_ack(self, ack: CraqAck) -> None:
        if ack.write_batch in self.pending_writes:
            self.pending_writes.remove(ack.write_batch)
        for write in ack.write_batch.writes:
            self.state_machine[write.key] = write.value
        if not self.is_head:
            prev = self.config.chain_node_addresses[self.index - 1]
            self.chan(prev).send(ack)


@dataclasses.dataclass
class _CraqPending:
    id: int
    result: Promise
    resend: object


class CraqClient(Actor):
    def __init__(self, address, transport, logger, config: CraqConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _CraqPending] = {}

    def _start(self, pseudonym: int, send) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        command_id = CraqCommandId(self.address_bytes, pseudonym, id)
        send(command_id)

        def resend() -> None:
            send(command_id)
            timer.start()

        timer = self.timer(f"resend[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _CraqPending(id=id, result=promise, resend=timer)
        return promise

    def write(self, pseudonym: int, key: str, value: str) -> Promise:
        head = self.config.chain_node_addresses[0]
        return self._start(
            pseudonym,
            lambda cid: self.chan(head).send(
                CraqWrite(command_id=cid, key=key, value=value)
            ),
        )

    def read(self, pseudonym: int, key: str) -> Promise:
        node = self.config.chain_node_addresses[
            self.rng.randrange(len(self.config.chain_node_addresses))
        ]
        return self._start(
            pseudonym,
            lambda cid: self.chan(node).send(CraqRead(command_id=cid, key=key)),
        )

    def receive(self, src: Address, msg) -> None:
        pseudonym = msg.command_id.client_pseudonym
        pending = self.pending.get(pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[pseudonym]
        if isinstance(msg, CraqClientReply):
            pending.result.success(None)
        elif isinstance(msg, CraqReadReply):
            pending.result.success(msg.value)
        else:
            self.logger.fatal(f"unknown craq client message {msg!r}")
