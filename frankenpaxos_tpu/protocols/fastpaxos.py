"""Fast Paxos — single-decree with a fast round (reference ``fastpaxos/``:
Client, Leader, Acceptor).

Clients propose straight to acceptors in fast round 0 and count Phase2bs
themselves; a fast quorum (f + ⌊(f+1)/2⌋ + 1 of n = 2f+1) chooses the
value (``fastpaxos/Client.scala:118-135``). On timeout the client falls
back to leaders, which run classic rounds (round += n keeps ownership,
``fastpaxos/Leader.scala``): phase 1 collects a classic quorum and picks
the value by max vote round; for round-0 votes the value must be one
voted by a majority-of-quorum (``Util.popularItems``), else any value is
safe. Deliberate divergence: where the reference proposes ``None`` when no
round-0 value is popular (stalling), we propose the leader's own value —
the standard coordinated-recovery rule.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.util import popular_items


@wire.message
@dataclasses.dataclass(frozen=True)
class FpProposeRequest:
    v: str


@wire.message
@dataclasses.dataclass(frozen=True)
class FpProposeReply:
    chosen: str


@wire.message
@dataclasses.dataclass(frozen=True)
class FpPhase1a:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FpPhase1b:
    round: int
    acceptor_id: int
    vote_round: int
    vote_value: Optional[str]


@wire.message
@dataclasses.dataclass(frozen=True)
class FpPhase2a:
    round: int
    value: str


@wire.message
@dataclasses.dataclass(frozen=True)
class FpPhase2b:
    acceptor_id: int
    round: int


@dataclasses.dataclass(frozen=True)
class FastPaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def quorum_majority_size(self) -> int:
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.f + self.quorum_majority_size

    def check_valid(self) -> None:
        if not self.f + 1 <= len(self.leader_addresses) <= self.n:
            # Upper bound matters: classic rounds stride by n from a start
            # of the leader index, so indices must be unique mod n or two
            # leaders would own the same rounds.
            raise ValueError(f"need between f+1 and {self.n} leaders")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError(f"need exactly {self.n} acceptors")


class FpClient(Actor):
    def __init__(self, address, transport, logger, config: FastPaxosConfig,
                 repropose_period: float = 5.0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.proposed_value: Optional[str] = None
        self.chosen_value: Optional[str] = None
        self.phase2bs: Set[FpPhase2b] = set()
        self.promises: List[Promise] = []
        self.repropose_timer = self.timer(
            "reproposeTimer", repropose_period, self._repropose
        )

    def _repropose(self) -> None:
        # Fall back to the classic path through the leaders.
        for leader in self.config.leader_addresses:
            self.chan(leader).send(FpProposeRequest(v=self.proposed_value))
        self.repropose_timer.start()

    def propose(self, v: str) -> Promise:
        promise = Promise()
        if self.chosen_value is not None:
            promise.success(self.chosen_value)
            return promise
        if self.proposed_value is not None:
            self.promises.append(promise)
            return promise
        self.proposed_value = v
        # Fast path: straight to the acceptors in round 0.
        for acceptor in self.config.acceptor_addresses:
            self.chan(acceptor).send(FpProposeRequest(v=v))
        self.repropose_timer.start()
        self.promises.append(promise)
        return promise

    def _choose(self, chosen: str) -> None:
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, chosen)
            return
        self.chosen_value = chosen
        for promise in self.promises:
            promise.success(chosen)
        self.promises.clear()
        self.repropose_timer.stop()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FpProposeReply):
            self._choose(msg.chosen)
        elif isinstance(msg, FpPhase2b):
            self.logger.check_eq(msg.round, 0)
            self.phase2bs.add(msg)
            if len(self.phase2bs) >= self.config.fast_quorum_size:
                self._choose(self.proposed_value)
        else:
            self.logger.fatal(f"unknown fastpaxos client message {msg!r}")


class FpLeader(Actor):
    IDLE, PHASE1, PHASE2, CHOSEN = range(4)

    def __init__(self, address, transport, logger, config: FastPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.leader_addresses.index(address)
        self.round = self.index  # rounds advance by n, keeping ownership
        self.status = self.IDLE
        self.proposed_value: Optional[str] = None
        self.chosen_value: Optional[str] = None
        self.phase1bs: Dict[int, FpPhase1b] = {}
        self.phase2bs: Dict[int, FpPhase2b] = {}
        self.clients: List[Address] = []

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FpProposeRequest):
            self._handle_propose(src, msg)
        elif isinstance(msg, FpPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, FpPhase2b):
            self._handle_phase2b(msg)
        else:
            self.logger.fatal(f"unknown fastpaxos leader message {msg!r}")

    def _handle_propose(self, src: Address, msg: FpProposeRequest) -> None:
        if self.chosen_value is not None:
            self.chan(src).send(FpProposeReply(chosen=self.chosen_value))
            return
        self.round += self.config.n
        self.proposed_value = msg.v
        self.status = self.PHASE1
        self.phase1bs.clear()
        self.phase2bs.clear()
        for acceptor in self.config.acceptor_addresses:
            self.chan(acceptor).send(FpPhase1a(round=self.round))
        if src not in self.clients:
            self.clients.append(src)

    def _handle_phase1b(self, msg: FpPhase1b) -> None:
        if self.status != self.PHASE1 or msg.round != self.round:
            return
        self.phase1bs[msg.acceptor_id] = msg
        if len(self.phase1bs) < self.config.classic_quorum_size:
            return
        k = max(b.vote_round for b in self.phase1bs.values())
        if k == -1:
            v = self.proposed_value
        elif k > 0:
            vs = {
                b.vote_value
                for b in self.phase1bs.values()
                if b.vote_round == k
            }
            self.logger.check_eq(len(vs), 1)
            v = next(iter(vs))
            self.proposed_value = v
        else:  # k == 0: fast-round votes; a majority-of-quorum value binds.
            votes = [
                b.vote_value
                for b in self.phase1bs.values()
                if b.vote_round == 0
            ]
            popular = popular_items(votes, self.config.quorum_majority_size)
            if popular:
                self.logger.check_eq(len(popular), 1)
                v = next(iter(popular))
                self.proposed_value = v
            else:
                v = self.proposed_value  # free choice (see module docstring)
        for acceptor in self.config.acceptor_addresses:
            self.chan(acceptor).send(FpPhase2a(round=self.round, value=v))
        self.status = self.PHASE2

    def _handle_phase2b(self, msg: FpPhase2b) -> None:
        if self.status != self.PHASE2 or msg.round != self.round:
            return
        self.phase2bs[msg.acceptor_id] = msg
        if len(self.phase2bs) < self.config.classic_quorum_size:
            return
        chosen = self.proposed_value
        if self.chosen_value is not None:
            self.logger.check_eq(self.chosen_value, chosen)
        self.chosen_value = chosen
        self.status = self.CHOSEN
        for client in self.clients:
            self.chan(client).send(FpProposeReply(chosen=chosen))
        self.clients.clear()


class FpAcceptor(Actor):
    def __init__(self, address, transport, logger, config: FastPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = 0
        self.vote_round = -1
        self.vote_value: Optional[str] = None
        # Fast voting is enabled for round 0 until a classic round begins
        # (the reference's voteValue._2 flag).
        self.fast_round: Optional[int] = 0

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FpProposeRequest):
            # Fast-path vote (at most one per fast round).
            if self.fast_round is not None:
                r = self.fast_round
                if self.round <= r and self.vote_round < r:
                    self.round = r
                    self.vote_round = r
                    self.vote_value = msg.v
                    self.chan(src).send(
                        FpPhase2b(acceptor_id=self.index, round=r)
                    )
        elif isinstance(msg, FpPhase1a):
            if msg.round <= self.round:
                return
            self.round = msg.round
            self.fast_round = None  # classic rounds disable fast voting
            self.chan(src).send(
                FpPhase1b(
                    round=msg.round,
                    acceptor_id=self.index,
                    vote_round=self.vote_round,
                    vote_value=self.vote_value,
                )
            )
        elif isinstance(msg, FpPhase2a):
            if msg.round < self.round:
                return
            if msg.round == self.round and msg.round == self.vote_round:
                return  # already voted this round
            self.round = msg.round
            self.vote_round = msg.round
            self.vote_value = msg.value
            self.chan(src).send(
                FpPhase2b(acceptor_id=self.index, round=msg.round)
            )
        else:
            self.logger.fatal(f"unknown fastpaxos acceptor message {msg!r}")
