"""Vanilla Mencius — classic multi-leader Paxos with round-robin slot
ownership (reference ``vanillamencius/``: Client, Server).

Server i owns slots ≡ i (mod n) and coordinates them in round 0. All
servers are also the acceptors and replicas. Three mechanisms from the
reference (``vanillamencius/Server.scala``):

  * SKIPS: when a server observes a Phase2a for a slot ahead of its own
    next slot, it fills its intervening owned slots with noops so the
    global log doesn't stall behind idle leaders (flushed in ranges by a
    timer). Skips here are quorum-voted noop Phase2as batched as a range
    (safe under revocation races; the reference's unacked skip fast path
    is an optimization on top).
  * REVOCATION: a heartbeat failure detector watches the other servers; a
    randomized revocation timer runs phase 1 in a higher round over a dead
    server's slot range (up to ``beta`` slots ahead) and fills unchosen
    slots with noops (``Server.scala`` makeRevocationTimer /
    handlePhase1a/b).
  * Execution: chosen entries retire through a BufferMap log in global
    slot order; the slot's owner replies to the client.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.heartbeat import HeartbeatOptions
from frankenpaxos_tpu.heartbeat import Participant as HeartbeatParticipant
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, random_duration


@wire.message
@dataclasses.dataclass(frozen=True)
class VmCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmClientRequest:
    command_id: VmCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class VmClientReply:
    command_id: VmCommandId
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class VmPhase1a:
    owner: int  # revocation targets ONE server's slots in the range
    slot_start: int  # revocation runs phase 1 over a whole range
    slot_end: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmPhase1b:
    server_index: int
    slot_start: int
    slot_end: int
    round: int
    votes: tuple  # of (slot, vote_round, command|None)
    # Slots in the range this acceptor already knows are chosen, with their
    # values; the revoker must adopt these, not re-propose over them.
    chosen: tuple  # of (slot, command|None)


@wire.message
@dataclasses.dataclass(frozen=True)
class VmPhase2a:
    slot: int
    round: int
    value: Optional[VmClientRequest]  # None = noop


@wire.message
@dataclasses.dataclass(frozen=True)
class VmSkipRange:
    """Noop Phase2as for every owned slot in [start, end), batched."""

    owner: int
    start: int
    end: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmPhase2b:
    server_index: int
    slot: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmSkipRange2b:
    server_index: int
    owner: int
    start: int
    end: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmChosen:
    slot: int
    value: Optional[VmClientRequest]


@wire.message
@dataclasses.dataclass(frozen=True)
class VmChosenRange:
    owner: int
    start: int
    end: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmNack:
    slot: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class VmPhase1Nack:
    slot_start: int
    slot_end: int
    round: int
    higher_round: int


@dataclasses.dataclass(frozen=True)
class VanillaMenciusConfig:
    f: int
    server_addresses: tuple
    heartbeat_addresses: tuple

    @property
    def n(self) -> int:
        return len(self.server_addresses)

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if self.n != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 servers")
        if len(self.heartbeat_addresses) != self.n:
            raise ValueError("one heartbeat address per server")


@dataclasses.dataclass(frozen=True)
class VmServerOptions:
    beta: int = 100  # revoke this many slots ahead of the dead server
    revoke_min_period: float = 1.0
    revoke_max_period: float = 5.0
    resend_phase1as_period: float = 5.0
    log_grow_size: int = 1000
    heartbeat_options: HeartbeatOptions = HeartbeatOptions()


@dataclasses.dataclass
class _VmSlotState:
    round: int = 0
    vote_round: int = -1
    vote_value: Optional[VmClientRequest] = None


class VmServer(Actor):
    def __init__(self, address, transport, logger,
                 config: VanillaMenciusConfig, state_machine: StateMachine,
                 options: VmServerOptions = VmServerOptions(), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.server_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.server_addresses.index(address)
        self.heartbeat = HeartbeatParticipant(
            config.heartbeat_addresses[self.index], transport, logger,
            config.heartbeat_addresses, options.heartbeat_options,
        )
        # Global log of chosen entries; acceptor state per slot.
        self.log: BufferMap[Tuple[Optional[VmClientRequest]]] = BufferMap(
            options.log_grow_size
        )
        self.acceptor_states: Dict[int, _VmSlotState] = {}
        self.executed_watermark = 0
        self.next_slot = self.index  # next OWNED slot (stride n)
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}
        # Coordinator state: slot -> {round, value, votes}
        self.phase2s: Dict[int, dict] = {}
        # Revocation (phase 1) state per (owner): range + votes.
        self.phase1s: Dict[int, dict] = {}
        # Monotone lower bound on this server's revocation rounds.
        self.recover_round = 0
        # Randomized revocation timers: each periodically checks the
        # heartbeat's alive set and revokes dead peers' slots
        # (Server.scala makeRevocationTimer).
        self.revocation_timers: Dict[int, object] = {}
        for peer in range(self.config.n):
            if peer != self.index:
                self.revocation_timers[peer] = self._make_revocation_timer(peer)

    def _make_revocation_timer(self, peer: int):
        def fire() -> None:
            dead = (
                self.config.heartbeat_addresses[peer]
                not in self.heartbeat.unsafe_alive()
            )
            if dead:
                self.start_revocation(peer)
            timer.start()

        timer = self.timer(
            f"revoke{peer}",
            random_duration(
                self.rng,
                self.options.revoke_min_period,
                self.options.revoke_max_period,
            ),
            fire,
        )
        timer.start()
        return timer

    # -- Helpers -------------------------------------------------------------

    def owner(self, slot: int) -> int:
        return slot % self.config.n

    def _broadcast(self, msg) -> None:
        for a in self.config.server_addresses:
            self.chan(a).send(msg)

    def _acceptor_state(self, slot: int) -> _VmSlotState:
        return self.acceptor_states.setdefault(slot, _VmSlotState())

    # -- Execution -----------------------------------------------------------

    def _execute_log(self) -> None:
        while True:
            entry = self.log.get(self.executed_watermark)
            if entry is None:
                return
            (value,) = entry
            slot = self.executed_watermark
            self.executed_watermark += 1
            if value is None:
                continue  # noop / skip
            cid = value.command_id
            key = (cid.client_address, cid.client_pseudonym)
            cached = self.client_table.get(key)
            if cached is not None and cid.client_id < cached[0]:
                continue
            if cached is not None and cid.client_id == cached[0]:
                result = cached[1]
            else:
                result = self.state_machine.run(value.command)
                self.client_table[key] = (cid.client_id, result)
            if self.owner(slot) == self.index:
                client = self.transport.address_from_bytes(cid.client_address)
                self.chan(client).send(
                    VmClientReply(command_id=cid, result=result)
                )

    def _choose(self, slot: int, value: Optional[VmClientRequest]) -> None:
        if self.log.get(slot) is None:
            self.log.put(slot, (value,))
        self.acceptor_states.pop(slot, None)
        self.phase2s.pop(slot, None)
        self._execute_log()

    # -- Skips ---------------------------------------------------------------

    def _maybe_skip_to(self, observed_slot: int) -> None:
        """Another server reached observed_slot; fill our owned slots below
        it with noops so the global log doesn't stall on us."""
        if self.owner(observed_slot) == self.index:
            return
        if self.next_slot >= observed_slot:
            return
        start, end = self.next_slot, observed_slot
        self.next_slot = end + ((self.index - end) % self.config.n)
        self._broadcast(
            VmSkipRange(owner=self.index, start=start, end=end, round=0)
        )

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, VmClientRequest):
            self._handle_client_request(msg)
        elif isinstance(msg, VmPhase2a):
            self._handle_phase2a(src, msg)
        elif isinstance(msg, VmSkipRange):
            self._handle_skip_range(src, msg)
        elif isinstance(msg, VmPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, VmSkipRange2b):
            self._handle_skip_range_2b(msg)
        elif isinstance(msg, VmChosen):
            self._choose(msg.slot, msg.value)
        elif isinstance(msg, VmChosenRange):
            for slot in range(msg.start, msg.end):
                if self.owner(slot) == msg.owner:
                    self._choose(slot, None)
        elif isinstance(msg, VmPhase1a):
            self._handle_phase1a(src, msg)
        elif isinstance(msg, VmPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, VmPhase1Nack):
            self._handle_phase1_nack(msg)
        elif isinstance(msg, VmNack):
            pass  # a revocation beat us; the revoker re-runs phase 1
        else:
            self.logger.fatal(f"unknown mencius message {msg!r}")

    def _handle_client_request(self, msg: VmClientRequest) -> None:
        cid = msg.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None and cid.client_id == cached[0]:
            client = self.transport.address_from_bytes(cid.client_address)
            self.chan(client).send(
                VmClientReply(command_id=cid, result=cached[1])
            )
            return
        # Advance past slots already chosen (e.g. noop-filled by a
        # revocation that falsely suspected us) or already in flight —
        # proposing into a chosen slot would be silently black-holed
        # (cf. Server.scala's check that the log doesn't contain nextSlot).
        slot = self.next_slot
        while self.log.get(slot) is not None or slot in self.phase2s:
            slot += self.config.n
        self.next_slot = slot + self.config.n
        self.phase2s[slot] = {"round": 0, "value": msg, "votes": set()}
        self._broadcast(VmPhase2a(slot=slot, round=0, value=msg))

    def _handle_phase2a(self, src: Address, msg: VmPhase2a) -> None:
        if self.log.get(msg.slot) is not None:
            return  # already chosen
        state = self._acceptor_state(msg.slot)
        if msg.round < state.round:
            self.chan(src).send(VmNack(slot=msg.slot, round=state.round))
            return
        state.round = msg.round
        state.vote_round = msg.round
        state.vote_value = msg.value
        self.chan(src).send(
            VmPhase2b(server_index=self.index, slot=msg.slot, round=msg.round)
        )
        self._maybe_skip_to(msg.slot)

    def _handle_skip_range(self, src: Address, msg: VmSkipRange) -> None:
        # Vote noop for every owned slot in the range (batched Phase2a).
        for slot in range(msg.start, msg.end):
            if self.owner(slot) != msg.owner:
                continue
            if self.log.get(slot) is not None:
                continue
            state = self._acceptor_state(slot)
            if msg.round < state.round:
                continue
            state.round = msg.round
            state.vote_round = msg.round
            state.vote_value = None
        self.chan(src).send(
            VmSkipRange2b(
                server_index=self.index, owner=msg.owner,
                start=msg.start, end=msg.end, round=msg.round,
            )
        )

    def _handle_phase2b(self, msg: VmPhase2b) -> None:
        phase2 = self.phase2s.get(msg.slot)
        if phase2 is None or msg.round != phase2["round"]:
            return
        phase2["votes"].add(msg.server_index)
        if len(phase2["votes"]) < self.config.quorum_size:
            return
        value = phase2["value"]
        self._broadcast(VmChosen(slot=msg.slot, value=value))
        self._choose(msg.slot, value)

    def _handle_skip_range_2b(self, msg: VmSkipRange2b) -> None:
        key = -(msg.start + 1)  # range phase2s keyed negatively
        phase2 = self.phase2s.setdefault(
            key, {"round": msg.round, "votes": set(), "range": (msg.owner, msg.start, msg.end)}
        )
        phase2["votes"].add(msg.server_index)
        if len(phase2["votes"]) < self.config.quorum_size:
            return
        owner, start, end = phase2["range"]
        self.phase2s.pop(key, None)
        self._broadcast(VmChosenRange(owner=owner, start=start, end=end))
        for slot in range(start, end):
            if self.owner(slot) == owner:
                self._choose(slot, None)

    # -- Revocation ----------------------------------------------------------

    def _revocation_round(self, min_round: int) -> int:
        """A FRESH round > min_round owned by this server: rounds r > 0
        with r ≡ index+1 (mod n) belong to server `index`, so concurrent
        revokers never collide (round 0 is the slot owner's). Rounds are
        also monotone across this server's own revocations
        (self.recover_round), so re-revoking the same peer never reuses a
        round — reusing one would let stale Phase2bs from the previous
        attempt count toward a different value's quorum."""
        min_round = max(min_round, self.recover_round)
        r = self.index + 1
        while r <= min_round:
            r += self.config.n
        self.recover_round = r
        return r

    def start_revocation(self, dead_index: int) -> None:
        """Run phase 1 over the dead server's unchosen slots up to beta
        ahead of our executed watermark (makeRevocationTimer)."""
        if dead_index in self.phase1s:
            return  # already revoking this server
        start = self.executed_watermark
        end = start + self.options.beta
        self._start_phase1(dead_index, start, end, min_round=0)

    def _start_phase1(self, owner: int, start: int, end: int,
                      min_round: int) -> None:
        round = self._revocation_round(min_round)
        phase1a = VmPhase1a(
            owner=owner, slot_start=start, slot_end=end, round=round
        )

        def resend() -> None:
            self._broadcast(phase1a)
            timer.start()

        timer = self.timer(
            f"resendPhase1a[{owner};{round}]",
            self.options.resend_phase1as_period, resend,
        )
        timer.start()
        self.phase1s[owner] = {
            "round": round, "start": start, "end": end, "votes": {},
            "resend": timer,
        }
        self._broadcast(phase1a)

    def _handle_phase1a(self, src: Address, msg: VmPhase1a) -> None:
        # All-or-nothing range promise: a Phase1b counts toward a full-range
        # quorum, so if ANY slot in the range has promised a higher round we
        # must nack the whole range rather than silently skip that slot
        # (otherwise the revoker could choose a noop over a chosen value).
        chosen = []
        unchosen = []
        for slot in range(msg.slot_start, msg.slot_end):
            if self.owner(slot) != msg.owner:
                continue  # only the revoked server's slots are touched
            entry = self.log.get(slot)
            if entry is not None:
                chosen.append((slot, entry[0]))
            else:
                unchosen.append(slot)
        higher = max(
            (self._acceptor_state(s).round for s in unchosen), default=-1
        )
        if higher > msg.round:
            self.chan(src).send(
                VmPhase1Nack(
                    slot_start=msg.slot_start, slot_end=msg.slot_end,
                    round=msg.round, higher_round=higher,
                )
            )
            return
        votes = []
        for slot in unchosen:
            state = self._acceptor_state(slot)
            state.round = msg.round
            if state.vote_round >= 0:
                votes.append((slot, state.vote_round, state.vote_value))
        self.chan(src).send(
            VmPhase1b(
                server_index=self.index, slot_start=msg.slot_start,
                slot_end=msg.slot_end, round=msg.round, votes=tuple(votes),
                chosen=tuple(chosen),
            )
        )

    def _handle_phase1b(self, msg: VmPhase1b) -> None:
        # Adopt chosen slots the acceptor told us about, regardless of any
        # ongoing phase 1.
        for slot, value in msg.chosen:
            self._broadcast(VmChosen(slot=slot, value=value))
            self._choose(slot, value)
        phase1_key = None
        for key, state in self.phase1s.items():
            if (
                state["round"] == msg.round
                and state["start"] == msg.slot_start
                and state["end"] == msg.slot_end
            ):
                phase1_key = key
        if phase1_key is None:
            return
        phase1 = self.phase1s[phase1_key]
        phase1["votes"][msg.server_index] = msg.votes
        if len(phase1["votes"]) < self.config.quorum_size:
            return
        # Quorum reached: finish phase 1 EXACTLY once (a late Phase1b must
        # not re-run phase 2 with a different value in the same round).
        del self.phase1s[phase1_key]
        phase1["resend"].stop()
        # Safe value per slot: highest vote round's value, else noop. Only
        # the revoked server's slots are proposed (phase1_key is the owner).
        best: Dict[int, Tuple[int, Optional[VmClientRequest]]] = {}
        for votes in phase1["votes"].values():
            for slot, vote_round, value in votes:
                if slot not in best or vote_round > best[slot][0]:
                    best[slot] = (vote_round, value)
        for slot in range(phase1["start"], phase1["end"]):
            if self.owner(slot) != phase1_key:
                continue
            if self.log.get(slot) is not None:
                continue
            value = best.get(slot, (-1, None))[1]
            self.phase2s[slot] = {
                "round": phase1["round"], "value": value, "votes": set(),
            }
            self._broadcast(
                VmPhase2a(slot=slot, round=phase1["round"], value=value)
            )

    def _handle_phase1_nack(self, msg: VmPhase1Nack) -> None:
        for key, state in list(self.phase1s.items()):
            if (
                state["round"] == msg.round
                and state["start"] == msg.slot_start
                and state["end"] == msg.slot_end
            ):
                state["resend"].stop()
                del self.phase1s[key]
                # Retry in a round above the nacked one, still unique to us.
                self._start_phase1(
                    key, state["start"], state["end"], msg.higher_round
                )


@dataclasses.dataclass
class _VmPending:
    id: int
    result: Promise
    resend: object


class VmClient(Actor):
    def __init__(self, address, transport, logger,
                 config: VanillaMenciusConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _VmPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = VmClientRequest(
            command_id=VmCommandId(self.address_bytes, pseudonym, id),
            command=command,
        )
        server = self.config.server_addresses[
            self.rng.randrange(self.config.n)
        ]
        self.chan(server).send(request)

        def resend() -> None:
            target = self.config.server_addresses[
                self.rng.randrange(self.config.n)
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(f"resendVm[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _VmPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, VmClientReply):
            self.logger.fatal(f"unknown mencius client message {msg!r}")
        pending = self.pending.get(msg.command_id.client_pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[msg.command_id.client_pseudonym]
        pending.result.success(msg.result)
