"""Scalog (reference ``scalog/``: Client, Server, Aggregator, Leader,
Acceptor, Replica, ProxyReplica).

Scalog decouples ordering from replication: clients append to any shard
server's LOCAL log (backed up to the shard's other servers); servers
periodically push their log watermarks (ShardInfo) to the Aggregator,
which assembles a global CUT — a vector of per-server watermarks — and
has a small Paxos group (Leader + 2f+1 Acceptors) choose a log of cuts.
Chosen cuts flow back (RawCutChosen) to the Aggregator, which orders and
prunes non-monotone cuts, then broadcasts CutChosen to the servers. Each
server PROJECTS the delta between consecutive cuts onto the global log
(``Server.scala:30-60``'s worked example: global order is server-major
within a cut delta) and sends its own segment to the replicas as ordinary
Chosen(globalSlot, batch) messages — so the replica layer is EXACTLY the
MultiPaxos replica (reused here), with holes recovered through the
Aggregator, which locates the server owning a global slot.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.protocols.multipaxos.config import DistributionScheme
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    Chosen,
    Command,
    CommandBatch,
    CommandBatchOrNoop,
    CommandId,
    Recover,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.util import BufferMap


@wire.message
@dataclasses.dataclass(frozen=True)
class ScClientRequest:
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class ScBackup:
    server_index: int  # GLOBAL server index
    slot: int
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class ScBackupAck:
    server_index: int  # GLOBAL index of the ORIGINATING server
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScShardInfo:
    shard_index: int
    server_index: int  # index within the shard
    watermark: tuple  # per-server local-log watermarks within the shard


@wire.message
@dataclasses.dataclass(frozen=True)
class ScProposeCut:
    cut: tuple  # flattened per-(global)server watermarks


@wire.message
@dataclasses.dataclass(frozen=True)
class ScPhase1a:
    round: int
    chosen_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScPhase1b:
    acceptor_index: int
    round: int
    votes: tuple  # of (slot, vote_round, cut|None)


@wire.message
@dataclasses.dataclass(frozen=True)
class ScPhase2a:
    slot: int
    round: int
    cut: Optional[tuple]  # None = noop


@wire.message
@dataclasses.dataclass(frozen=True)
class ScPhase2b:
    acceptor_index: int
    slot: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScRawCutChosen:
    slot: int
    cut: Optional[tuple]


@wire.message
@dataclasses.dataclass(frozen=True)
class ScCutChosen:
    slot: int
    cut: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class ScNack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScRecoverRawCut:
    # The aggregator's raw-cut watermark: the first cut-log slot it is
    # missing. Doubles as a GC hint — leaders prune cached cuts below it.
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScCutChosenAck:
    # A server acknowledges it stored cut-log slot ``slot``; the
    # aggregator stops re-broadcasting the newest cut to it.
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScRawWatermark:
    # The aggregator's processed raw-cut watermark, pushed periodically to
    # leaders and acceptors: raw slots below it can never be requested
    # again, so vote state and cut caches below it are garbage.
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ScLeaderInfo:
    # A leader announces it finished phase 1 and owns this round, so the
    # aggregator routes future ScProposeCuts to it instead of a dead
    # predecessor.
    round: int


@dataclasses.dataclass(frozen=True)
class ScalogConfig:
    f: int
    # servers grouped into shards; each shard has f+1 servers.
    server_addresses: tuple  # of tuples (shards)
    aggregator_address: object
    leader_addresses: tuple  # the cut-ordering Paxos leaders
    acceptor_addresses: tuple  # 2f+1 cut acceptors
    replica_addresses: tuple
    proxy_replica_addresses: tuple = ()
    distribution_scheme: DistributionScheme = DistributionScheme.HASH

    @property
    def num_shards(self) -> int:
        return len(self.server_addresses)

    @property
    def flat_servers(self) -> tuple:
        return tuple(a for shard in self.server_addresses for a in shard)

    @property
    def num_servers(self) -> int:
        return len(self.flat_servers)

    def shard_of(self, global_index: int) -> int:
        base = 0
        for s, shard in enumerate(self.server_addresses):
            if global_index < base + len(shard):
                return s
            base += len(shard)
        raise IndexError(global_index)

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if self.num_shards < 1:
            raise ValueError("need at least one shard")
        for shard in self.server_addresses:
            if len(shard) < self.f + 1:
                raise ValueError("each shard needs >= f+1 servers")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")
        if self.num_replicas < self.f + 1:
            raise ValueError("need >= f+1 replicas")


# The replica layer reuses multipaxos.Replica, which broadcasts its
# Recover/ChosenWatermark to config.leader_addresses — for Scalog those
# must reach the AGGREGATOR (which locates the server owning a slot), so
# the replica-facing config exposes the aggregator as the sole "leader".
def replica_config(config: ScalogConfig):
    return _ScReplicaConfig(config)


class _ScReplicaConfig:
    def __init__(self, config: ScalogConfig):
        self._c = config
        self.f = config.f
        self.leader_addresses = (config.aggregator_address,)
        self.replica_addresses = config.replica_addresses
        self.proxy_replica_addresses = config.proxy_replica_addresses
        self.distribution_scheme = config.distribution_scheme

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def check_valid(self) -> None:
        self._c.check_valid()


@dataclasses.dataclass(frozen=True)
class ScServerOptions:
    push_size: int = 1  # push watermarks after this many appends
    push_period: float = 1.0


class ScServer(Actor):
    def __init__(self, address, transport, logger, config: ScalogConfig,
                 options: ScServerOptions = ScServerOptions(), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.shard_index = next(
            i for i, s in enumerate(config.server_addresses) if address in s
        )
        self.index = config.server_addresses[self.shard_index].index(address)
        self.shard = config.server_addresses[self.shard_index]
        # Global index of this shard's first server in the flattened order.
        self.global_base = sum(
            len(s) for s in config.server_addresses[: self.shard_index]
        )
        # Local logs for every server IN THIS SHARD (own + backups).
        self.logs: List[BufferMap] = [BufferMap() for _ in self.shard]
        self.watermarks: List[int] = [0] * len(self.shard)
        # Chosen cuts: cut-slot -> flattened watermark vector.
        self.cuts: Dict[int, tuple] = {}
        # Cut slots below this are fully executed and GC'd: never
        # re-projected (their log prefixes are gone).
        self.min_cut_slot = 0
        self._pushed_since = 0
        # Per shard member (local index): backed-up entries not yet acked,
        # re-sent on every push tick so one lost ScBackup can't freeze the
        # min-cut below the entry forever.
        self._backup_unacked: List[Dict[int, Command]] = [
            {} for _ in self.shard
        ]

        def push() -> None:
            self.push()
            for local, unacked in enumerate(self._backup_unacked):
                for slot, command in unacked.items():
                    self.chan(self.shard[local]).send(
                        ScBackup(
                            server_index=self.global_base + self.index,
                            slot=slot,
                            command=command,
                        )
                    )
            self.push_timer.start()

        self.push_timer = self.timer("push", options.push_period, push)
        self.push_timer.start()

    def push(self) -> None:
        self.chan(self.config.aggregator_address).send(
            ScShardInfo(
                shard_index=self.shard_index,
                server_index=self.index,
                watermark=tuple(self.watermarks),
            )
        )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ScClientRequest):
            self._handle_client_request(msg)
        elif isinstance(msg, ScBackup):
            local = msg.server_index - self.global_base
            self.logs[local].put(msg.slot, msg.command)
            self.watermarks[local] = self._watermark(local)
            self.chan(src).send(
                ScBackupAck(server_index=msg.server_index, slot=msg.slot)
            )
            # Cuts only cover fully-replicated prefixes (element-wise MIN
            # at the aggregator), so a backed-up entry can't enter a cut
            # until the backups' views reach the aggregator — push.
            self._maybe_push()
        elif isinstance(msg, ScBackupAck):
            local = self.shard.index(src)
            self._backup_unacked[local].pop(msg.slot, None)
        elif isinstance(msg, ScCutChosen):
            self._handle_cut_chosen(msg)
        elif isinstance(msg, Recover):
            self._handle_recover(src, msg)
        elif isinstance(msg, ChosenWatermark):
            self._garbage_collect(msg.slot)
        else:
            self.logger.fatal(f"unknown scalog server message {msg!r}")

    def _watermark(self, local: int) -> int:
        w = self.watermarks[local]
        while self.logs[local].get(w) is not None:
            w += 1
        return w

    def _handle_client_request(self, msg: ScClientRequest) -> None:
        slot = self.watermarks[self.index]
        self.logs[self.index].put(slot, msg.command)
        self.watermarks[self.index] = self._watermark(self.index)
        for i, server in enumerate(self.shard):
            if i != self.index:
                self._backup_unacked[i][slot] = msg.command
                self.chan(server).send(
                    ScBackup(
                        server_index=self.global_base + self.index,
                        slot=slot,
                        command=msg.command,
                    )
                )
        self._maybe_push()

    def _maybe_push(self) -> None:
        self._pushed_since += 1
        if self.options.push_size > 0 and self._pushed_since >= self.options.push_size:
            self.push()
            self._pushed_since = 0
            self.push_timer.reset()

    def _project(self, cut_slot: int) -> Optional[List[Tuple[int, List[Command]]]]:
        """The global-log segments this server's OWN log contributes for
        the delta between cut cut_slot-1 and cut_slot (Server.projectCut).
        Global order within a delta is server-major by global index."""
        cut = self.cuts.get(cut_slot)
        if cut is None:
            return None
        prev = self.cuts.get(cut_slot - 1)
        if prev is None:
            if cut_slot != 0:
                return None
            prev = tuple([0] * self.config.num_servers)
        my_global = self.global_base + self.index
        global_start = sum(prev) + sum(
            cut[i] - prev[i] for i in range(my_global)
        )
        lo, hi = prev[my_global], cut[my_global]
        commands = []
        for slot in range(lo, hi):
            command = self.logs[self.index].get(slot)
            if command is None:
                self.logger.fatal(
                    f"server {my_global} missing local slot {slot} chosen in a cut"
                )
            commands.append(command)
        return [(global_start, commands)] if commands else []

    def _handle_cut_chosen(self, msg: ScCutChosen) -> None:
        self.chan(self.config.aggregator_address).send(
            ScCutChosenAck(slot=msg.slot)
        )
        if msg.slot < self.min_cut_slot:
            return  # duplicate of a fully-executed, GC'd cut
        already = msg.slot in self.cuts
        self.cuts[msg.slot] = msg.cut
        slots = [msg.slot] if already else [msg.slot, msg.slot + 1]
        for s in slots:
            if s < self.min_cut_slot:
                continue
            segments = self._project(s)
            if not segments:
                continue
            for global_start, commands in segments:
                # One Chosen per command keeps the replica's contiguous
                # BufferMap semantics simple (a batch per global slot).
                for replica in self.config.replica_addresses:
                    for i, command in enumerate(commands):
                        self.chan(replica).send(
                            Chosen(
                                slot=global_start + i,
                                value=CommandBatchOrNoop(
                                    CommandBatch((command,))
                                ),
                            )
                        )

    def _locate(self, global_slot: int) -> Optional[Tuple[int, int, int]]:
        """Map a global-log slot to (cut_slot, owner_global_index,
        owner_local_log_slot) from the retained cut history; None if the
        covering cut (or its predecessor, needed for the delta) is
        missing."""
        for cut_slot in sorted(self.cuts):
            cut = self.cuts[cut_slot]
            prev = self.cuts.get(cut_slot - 1)
            if prev is None:
                if cut_slot != 0:
                    continue
                prev = tuple([0] * self.config.num_servers)
            if not (sum(prev) <= global_slot < sum(cut)):
                continue
            offset = global_slot - sum(prev)
            for i in range(self.config.num_servers):
                delta = cut[i] - prev[i]
                if offset < delta:
                    return (cut_slot, i, prev[i] + offset)
                offset -= delta
        return None

    def _handle_recover(self, src: Address, msg: Recover) -> None:
        """The aggregator located this server's SHARD as the owner of a
        global slot; any member holding the entry (the owner or a backup)
        re-sends it to EVERY replica (the Recover was relayed, so src is
        the aggregator, not the stuck replica)."""
        located = self._locate(msg.slot)
        if located is None:
            return
        _, owner, local_slot = located
        local = owner - self.global_base
        if not (0 <= local < len(self.shard)):
            return
        command = self.logs[local].get(local_slot)
        if command is None:
            return
        chosen = Chosen(
            slot=msg.slot,
            value=CommandBatchOrNoop(CommandBatch((command,))),
        )
        for replica in self.config.replica_addresses:
            self.chan(replica).send(chosen)

    def _garbage_collect(self, executed: int) -> None:
        """All replicas executed global slots < ``executed``: drop local
        log prefixes and cut history that only cover executed deltas.
        The newest fully-executed cut is RETAINED — it is the ``prev`` of
        the next delta's projection."""
        newest_done = None
        for cut_slot in sorted(self.cuts):
            if sum(self.cuts[cut_slot]) <= executed:
                newest_done = cut_slot
            else:
                break
        if newest_done is None:
            return
        cut = self.cuts[newest_done]
        for local in range(len(self.shard)):
            self.logs[local].garbage_collect(cut[self.global_base + local])
        for cut_slot in [s for s in self.cuts if s < newest_done]:
            del self.cuts[cut_slot]
        self.min_cut_slot = max(self.min_cut_slot, newest_done + 1)


@dataclasses.dataclass(frozen=True)
class ScAggregatorOptions:
    num_shard_cuts_per_proposal: int = 2
    recover_period: float = 1.0


class ScAggregator(Actor):
    def __init__(self, address, transport, logger, config: ScalogConfig,
                 options: ScAggregatorOptions = ScAggregatorOptions()):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        # Per shard, per server-in-shard: that server's view of the shard's
        # watermark vector; the shard cut is the pairwise max.
        self.shard_cuts: List[List[tuple]] = [
            [tuple([0] * len(shard)) for _ in shard]
            for shard in config.server_addresses
        ]
        self.round = 0
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        # Out-of-order chosen raw cuts waiting to be processed; entries are
        # popped as the watermark advances, so a non-empty dict means a
        # HOLE — a lost RawCutChosen — which the recover timer re-requests
        # from the leaders (they cache chosen cuts for exactly this).
        self.raw_cuts: Dict[int, Optional[tuple]] = {}
        self.raw_cuts_watermark = 0
        self.raw_cuts_processed = 0
        # The ordered, pruned cut log. GC (driven by replica
        # ChosenWatermarks) drops fully-executed cuts; the newest dropped
        # cut is retained as ``cuts_base_prev`` — it is the delta
        # predecessor of cuts[0] — and ``cuts_base_slot`` is cuts[0]'s
        # absolute slot in the pruned cut log.
        self.cuts: List[tuple] = []
        self.cuts_base_slot = 0
        self.cuts_base_prev = tuple([0] * config.num_servers)
        self._since_proposal = 0
        self.replica_watermarks: Dict[object, int] = {}
        self._forwarded_watermark = 0
        # Per server: the newest cut-log slot it has acknowledged.
        self.server_cut_acks: Dict[object, int] = {}

        def recover() -> None:
            # A hole in the raw cut log (a lost leader->aggregator
            # RawCutChosen): re-request it from the leaders' caches.
            if self.raw_cuts:
                msg = ScRecoverRawCut(slot=self.raw_cuts_watermark)
                for leader in self.config.leader_addresses:
                    self.chan(leader).send(msg)
            # Re-broadcast the NEWEST cut to servers that haven't acked
            # it: a trailing lost ScCutChosen has no later cut to chain
            # from and no replica hole to trigger recovery, so this
            # periodic nudge is its only repair path. Once every server
            # acks, the quiescent system sends nothing.
            if self.cuts:
                slot = self.cuts_base_slot + len(self.cuts) - 1
                chosen = ScCutChosen(slot=slot, cut=self.cuts[-1])
                for server in self.config.flat_servers:
                    if self.server_cut_acks.get(server, -1) < slot:
                        self.chan(server).send(chosen)
            # Push the processed raw-cut watermark so leaders/acceptors
            # can drop vote state and cut caches that can never be
            # requested again.
            if self.raw_cuts_watermark > 0:
                wm = ScRawWatermark(slot=self.raw_cuts_watermark)
                for leader in self.config.leader_addresses:
                    self.chan(leader).send(wm)
                for acceptor in self.config.acceptor_addresses:
                    self.chan(acceptor).send(wm)
            self.recover_timer.start()

        self.recover_timer = self.timer(
            "recoverRawCut", options.recover_period, recover
        )
        self.recover_timer.start()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ScShardInfo):
            self._handle_shard_info(msg)
        elif isinstance(msg, ScRawCutChosen):
            self._handle_raw_cut_chosen(msg)
        elif isinstance(msg, Recover):
            self._handle_recover(src, msg)
        elif isinstance(msg, ChosenWatermark):
            self._handle_chosen_watermark(src, msg)
        elif isinstance(msg, ScCutChosenAck):
            self.server_cut_acks[src] = max(
                self.server_cut_acks.get(src, -1), msg.slot
            )
        elif isinstance(msg, ScLeaderInfo):
            if msg.round > self.round:
                self.round = msg.round
        else:
            self.logger.fatal(f"unknown aggregator message {msg!r}")

    def _handle_shard_info(self, msg: ScShardInfo) -> None:
        current = self.shard_cuts[msg.shard_index][msg.server_index]
        self.shard_cuts[msg.shard_index][msg.server_index] = tuple(
            max(a, b) for a, b in zip(current, msg.watermark)
        )
        self._since_proposal += 1
        if self._since_proposal >= self.options.num_shard_cuts_per_proposal:
            # A shard's cut entry is the element-wise MIN over its members'
            # views: only a fully-replicated log prefix may enter a cut, so
            # losing any single server never loses a chosen entry.
            cut = tuple(
                x
                for shard in self.shard_cuts
                for x in (tuple(min(v) for v in zip(*shard)))
            )
            # Only propose cuts that would ADVANCE the newest chosen cut:
            # gating on the chosen (not the last proposed) cut means a
            # lost proposal is re-proposed on the next ShardInfo tick, but
            # a quiescent system runs no Paxos rounds at all.
            newest = self.cuts[-1] if self.cuts else self.cuts_base_prev
            if any(a > b for a, b in zip(cut, newest)):
                leader = self.config.leader_addresses[
                    self.round_system.leader(self.round)
                ]
                self.chan(leader).send(ScProposeCut(cut=cut))
            self._since_proposal = 0

    def _handle_raw_cut_chosen(self, msg: ScRawCutChosen) -> None:
        if msg.slot < self.raw_cuts_watermark or msg.slot in self.raw_cuts:
            return
        self.raw_cuts[msg.slot] = msg.cut
        while self.raw_cuts_watermark in self.raw_cuts:
            cut = self.raw_cuts.pop(self.raw_cuts_watermark)
            self.raw_cuts_processed += 1
            if cut is not None:
                # Order and prune: only strictly-monotone cuts advance the
                # global log (Aggregator.handleRawCutChosen).
                last = self.cuts[-1] if self.cuts else self.cuts_base_prev
                if all(a <= b for a, b in zip(last, cut)) and last != cut:
                    slot = self.cuts_base_slot + len(self.cuts)
                    self.cuts.append(cut)
                    chosen = ScCutChosen(slot=slot, cut=cut)
                    for server in self.config.flat_servers:
                        self.chan(server).send(chosen)
            self.raw_cuts_watermark += 1

    def _handle_recover(self, src: Address, msg: Recover) -> None:
        """A replica is missing global slot msg.slot: find the owning
        server from the cut log (Aggregator.findSlot) and ask its WHOLE
        shard to re-send — any member (owner or backup) holds the entry,
        so a crashed owner doesn't wedge recovery. The covering cut and
        its predecessor are re-sent too, in case the hole exists because
        the ScCutChosen itself was lost."""
        prev = self.cuts_base_prev
        for idx, cut in enumerate(self.cuts):
            if not (sum(prev) <= msg.slot < sum(cut)):
                prev = cut
                continue
            offset = msg.slot - sum(prev)
            for i in range(self.config.num_servers):
                delta = cut[i] - prev[i]
                if offset < delta:
                    shard = self.config.server_addresses[
                        self.config.shard_of(i)
                    ]
                    slot = self.cuts_base_slot + idx
                    for server in shard:
                        if slot > 0:
                            self.chan(server).send(
                                ScCutChosen(slot=slot - 1, cut=prev)
                            )
                        self.chan(server).send(ScCutChosen(slot=slot, cut=cut))
                        self.chan(server).send(Recover(slot=msg.slot))
                    return
                offset -= delta
            return

    def _handle_chosen_watermark(self, src: Address, msg: ChosenWatermark) -> None:
        """Replicas broadcast their executed watermark; once EVERY replica
        has executed past a cut, that cut's entries can never be recovered
        again, so servers may drop the covered log prefixes and the
        aggregator may prune its own cut history."""
        self.replica_watermarks[src] = max(
            self.replica_watermarks.get(src, 0), msg.slot
        )
        if len(self.replica_watermarks) < self.config.num_replicas:
            return
        executed = min(self.replica_watermarks.values())
        if executed <= self._forwarded_watermark:
            return
        self._forwarded_watermark = executed
        for server in self.config.flat_servers:
            self.chan(server).send(ChosenWatermark(slot=executed))
        newest_done = None
        for idx, cut in enumerate(self.cuts):
            if sum(cut) <= executed:
                newest_done = idx
            else:
                break
        if newest_done is not None:
            self.cuts_base_prev = self.cuts[newest_done]
            self.cuts_base_slot += newest_done + 1
            del self.cuts[: newest_done + 1]


class ScLeader(Actor):
    """The cut-ordering Paxos leader: a log of cuts chosen with 2f+1
    acceptors, ClassicRoundRobin rounds, phase-1 repair on failover."""

    def __init__(self, address, transport, logger, config: ScalogConfig,
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.round = 0 if self.index == 0 else -1
        self.active = self.index == 0
        self.next_slot = 0
        # slot -> {round, cut, votes}
        self.phase2s: Dict[int, dict] = {}
        # In-flight phase 1 responses; None when no phase 1 is running.
        self.phase1bs: Optional[Dict[int, ScPhase1b]] = None
        # Aggregator-reported processed watermark: phase 1 on failover
        # skips raw slots below it (the aggregator discards them anyway).
        self.raw_watermark = 0
        # Chosen cuts cached so a lost RawCutChosen can be re-sent when the
        # aggregator asks (ScRecoverRawCut); GC'd below its watermark.
        self.chosen_cuts: Dict[int, Optional[tuple]] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ScProposeCut):
            self._handle_propose_cut(msg)
        elif isinstance(msg, ScPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, ScPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, ScNack):
            self._handle_nack(msg)
        elif isinstance(msg, ScRawCutChosen):
            self.chosen_cuts[msg.slot] = msg.cut
            if msg.slot >= self.next_slot:
                self.next_slot = msg.slot + 1
        elif isinstance(msg, ScRecoverRawCut):
            self._handle_recover_raw_cut(msg)
        elif isinstance(msg, ScRawWatermark):
            self.raw_watermark = max(self.raw_watermark, msg.slot)
            for slot in [s for s in self.chosen_cuts if s < msg.slot]:
                del self.chosen_cuts[slot]
        else:
            self.logger.fatal(f"unknown scalog leader message {msg!r}")

    def _handle_recover_raw_cut(self, msg: ScRecoverRawCut) -> None:
        for slot in [s for s in self.chosen_cuts if s < msg.slot]:
            del self.chosen_cuts[slot]
        if msg.slot in self.chosen_cuts:
            self.chan(self.config.aggregator_address).send(
                ScRawCutChosen(slot=msg.slot, cut=self.chosen_cuts[msg.slot])
            )
        elif self.active:
            # Not chosen yet — lost Phase2a/2bs stalled the slot. Re-drive
            # phase 2 in our CURRENT round: a cached phase-2 round may be
            # stale after preemption + re-election, and acceptors would
            # nack it forever. Phase 1 of the current round guarantees no
            # value was chosen at this slot in any lower round, so
            # re-proposing the cached cut — or a noop when we have no
            # record (we were re-elected with no vote history) — is safe.
            if msg.slot in self.phase2s:
                cut = self.phase2s[msg.slot]["cut"]
            elif msg.slot < self.next_slot:
                cut = None
            else:
                return  # normal proposals will reach this slot
            self.phase2s[msg.slot] = {
                "round": self.round, "cut": cut, "votes": set()
            }
            phase2a = ScPhase2a(slot=msg.slot, round=self.round, cut=cut)
            for a in self.config.acceptor_addresses:
                self.chan(a).send(phase2a)

    def _handle_propose_cut(self, msg: ScProposeCut) -> None:
        if not self.active:
            return
        slot = self.next_slot
        self.next_slot += 1
        self.phase2s[slot] = {"round": self.round, "cut": msg.cut, "votes": set()}
        phase2a = ScPhase2a(slot=slot, round=self.round, cut=msg.cut)
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase2a)

    def _handle_phase2b(self, msg: ScPhase2b) -> None:
        phase2 = self.phase2s.get(msg.slot)
        if phase2 is None or msg.round != phase2["round"]:
            return
        phase2["votes"].add(msg.acceptor_index)
        if len(phase2["votes"]) < self.config.f + 1:
            return
        del self.phase2s[msg.slot]
        self.chosen_cuts[msg.slot] = phase2["cut"]
        raw = ScRawCutChosen(slot=msg.slot, cut=phase2["cut"])
        self.chan(self.config.aggregator_address).send(raw)
        for leader in self.config.leader_addresses:
            if leader != self.address:
                self.chan(leader).send(raw)

    def become_leader(self) -> None:
        """Failover entry point: take over the cut log in a higher round.
        The leader stays INACTIVE (drops ScProposeCuts) until phase 1
        completes — proposing fresh cuts at slots the old leader may have
        already gotten chosen would violate Paxos."""
        self.round = self.round_system.next_classic_round(self.index, self.round)
        self.active = False
        self.phase1bs = {}
        phase1a = ScPhase1a(
            round=self.round, chosen_watermark=self.raw_watermark
        )
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase1a)

    def _handle_phase1b(self, msg: ScPhase1b) -> None:
        if self.phase1bs is None or msg.round != self.round:
            return
        self.phase1bs[msg.acceptor_index] = msg
        if len(self.phase1bs) < self.config.f + 1:
            return
        best: Dict[int, Tuple[int, Optional[tuple]]] = {}
        for b in self.phase1bs.values():
            for slot, vote_round, cut in b.votes:
                if slot not in best or vote_round > best[slot][0]:
                    best[slot] = (vote_round, cut)
        max_slot = max(best, default=-1)
        for slot in range(self.raw_watermark, max_slot + 1):
            cut = best.get(slot, (-1, None))[1]
            self.phase2s[slot] = {"round": self.round, "cut": cut, "votes": set()}
            phase2a = ScPhase2a(slot=slot, round=self.round, cut=cut)
            for a in self.config.acceptor_addresses:
                self.chan(a).send(phase2a)
        self.next_slot = max(self.next_slot, max_slot + 1)
        self.phase1bs = None
        self.active = True
        # Route the aggregator's future proposals to this leader.
        self.chan(self.config.aggregator_address).send(
            ScLeaderInfo(round=self.round)
        )

    def _handle_nack(self, msg: ScNack) -> None:
        if msg.round <= self.round:
            return
        if self.active or self.phase1bs is not None:
            # Adopt the nacked round, then advance once to our own next
            # round (become_leader does the single advance).
            self.round = msg.round
            self.become_leader()


class ScAcceptor(Actor):
    def __init__(self, address, transport, logger, config: ScalogConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = -1
        # slot -> (vote_round, cut)
        self.votes: Dict[int, Tuple[int, Optional[tuple]]] = {}

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ScPhase1a):
            if msg.round < self.round:
                self.chan(src).send(ScNack(round=self.round))
                return
            self.round = msg.round
            self.chan(src).send(
                ScPhase1b(
                    acceptor_index=self.index,
                    round=msg.round,
                    votes=tuple(
                        (slot, vr, cut)
                        for slot, (vr, cut) in sorted(self.votes.items())
                        if slot >= msg.chosen_watermark
                    ),
                )
            )
        elif isinstance(msg, ScPhase2a):
            if msg.round < self.round:
                self.chan(src).send(ScNack(round=self.round))
                return
            self.round = msg.round
            self.votes[msg.slot] = (msg.round, msg.cut)
            self.chan(src).send(
                ScPhase2b(
                    acceptor_index=self.index, slot=msg.slot, round=msg.round
                )
            )
        elif isinstance(msg, ScRawWatermark):
            # The aggregator processed raw slots below msg.slot and will
            # discard any re-choice of them: the votes are garbage.
            for slot in [s for s in self.votes if s < msg.slot]:
                del self.votes[slot]
        else:
            self.logger.fatal(f"unknown scalog acceptor message {msg!r}")


@dataclasses.dataclass
class _ScPending:
    id: int
    result: Promise
    resend: object


class ScClient(Actor):
    def __init__(self, address, transport, logger, config: ScalogConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _ScPending] = {}

    def _server(self) -> Address:
        servers = self.config.flat_servers
        return servers[self.rng.randrange(len(servers))]

    def write(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = ScClientRequest(
            Command(
                command_id=CommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=id,
                ),
                command=command,
            )
        )
        self.chan(self._server()).send(request)

        def resend() -> None:
            self.chan(self._server()).send(request)
            timer.start()

        timer = self.timer(f"resendSc[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _ScPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        # Replies come from the reused multipaxos Replica (ClientReply) or
        # its ReadReply; only ClientReply occurs in Scalog.
        from frankenpaxos_tpu.protocols.multipaxos.messages import ClientReply

        if not isinstance(msg, ClientReply):
            self.logger.fatal(f"unknown scalog client message {msg!r}")
        pseudonym = msg.command_id.client_pseudonym
        pending = self.pending.get(pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[pseudonym]
        pending.result.success(msg.result)
