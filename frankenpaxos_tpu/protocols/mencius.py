"""Compartmentalized Mencius (reference ``mencius/``: Client, Batcher,
Leader, ProxyLeader, Acceptor, Replica, ProxyReplica).

Mencius is MultiPaxos with the log striped round-robin across MULTIPLE
active leaders: leader i owns global slots ≡ i (mod numLeaders), so
every leader proposes concurrently without contention. Two Mencius-
specific mechanisms (``mencius/Leader.scala`` options doc):

  * lagging leaders keep the global log executable by noop-filling their
    owned slots up to the highest slot they observe from other leaders —
    leaders broadcast HighWatermark messages every ``send_watermark_every_n``
    proposals, and a leader behind a watermark proposes noop ranges;
  * per-leader-index failover: each leader index has a co-located
    election; a replacement leader bumps the round FOR ITS INDEX ONLY
    (acceptors track one round per leader index, so other leaders' round-0
    proposals are unaffected) and phase-1-repairs its owned slots.

The compartmentalized machinery is shared with MultiPaxos: this module
reuses ``multipaxos``'s ProxyLeader, Replica, and ProxyReplica role
implementations and message schemas via a structurally compatible config
(slots route to acceptor groups by ``slot % G`` and Chosen fan-out is
identical). The Batcher is Mencius-specific (``MenciusBatcher``): batches
spread across leader GROUPS rather than following a single leader's
round.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.election import basic as election
from frankenpaxos_tpu.protocols.multipaxos.config import DistributionScheme
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    ClientRequest,
    ClientRequestBatch,
    ClientReply,
    Command,
    CommandBatch,
    CommandBatchOrNoop,
    CommandId,
    Nack,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2b,
    Recover,
)
from frankenpaxos_tpu.core.promise import Promise


@wire.message
@dataclasses.dataclass(frozen=True)
class MenciusHighWatermark:
    """Leader ``leader_index`` has proposed up to (exclusive) ``slot``."""

    leader_index: int
    slot: int


@dataclasses.dataclass(frozen=True)
class MenciusConfig:
    """Structurally compatible with multipaxos.Config so ProxyLeader,
    Replica, and ProxyReplica work unchanged."""

    f: int
    batcher_addresses: tuple
    # Each log stripe (leader index) is a GROUP of f+1 leader processes
    # with its own election; the elected member actively runs the stripe
    # (mencius/Config.scala: leaderAddresses: Seq[Seq[Address]]).
    leader_groups: tuple  # of tuples of addresses
    leader_election_groups: tuple  # of tuples of addresses
    proxy_leader_addresses: tuple
    acceptor_addresses: tuple  # groups of 2f+1; slot % G routing
    replica_addresses: tuple
    proxy_replica_addresses: tuple
    flexible: bool = False  # grid quorums are a MultiPaxos-only feature
    distribution_scheme: DistributionScheme = DistributionScheme.HASH

    @property
    def num_batchers(self) -> int:
        return len(self.batcher_addresses)

    @property
    def num_leaders(self) -> int:
        """Number of log stripes (leader groups)."""
        return len(self.leader_groups)

    @property
    def leader_addresses(self) -> tuple:
        """Flattened leader processes — the broadcast targets for the
        reused MultiPaxos Replica/ProxyReplica (ChosenWatermark/Recover
        go to every leader process; each filters by stripe ownership)."""
        return tuple(a for group in self.leader_groups for a in group)

    @property
    def num_proxy_leaders(self) -> int:
        return len(self.proxy_leader_addresses)

    @property
    def num_acceptor_groups(self) -> int:
        return len(self.acceptor_addresses)

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if self.flexible:
            raise ValueError("mencius uses round-robin groups, not grids")
        if self.num_leaders < 1:
            raise ValueError("need at least one leader group")
        if self.num_acceptor_groups < 1:
            raise ValueError("need at least one acceptor group")
        if len(self.leader_election_groups) != self.num_leaders:
            raise ValueError("one election group per leader group")
        for lg, eg in zip(self.leader_groups, self.leader_election_groups):
            if len(lg) != len(eg):
                raise ValueError("election group size must match leader group")
        if self.num_proxy_leaders < 1:
            raise ValueError("need at least one proxy leader")
        for group in self.acceptor_addresses:
            if len(group) != 2 * self.f + 1:
                raise ValueError("acceptor groups must be 2f+1")
        if self.num_replicas < self.f + 1:
            raise ValueError("need >= f+1 replicas")


@dataclasses.dataclass(frozen=True)
class MenciusLeaderOptions:
    send_watermark_every_n: int = 4
    resend_phase1as_period: float = 5.0
    election_options: election.ElectionOptions = election.ElectionOptions()


_INACTIVE = "inactive"


@dataclasses.dataclass
class _MnPhase1:
    phase1bs: List[Dict[int, Phase1b]]  # per acceptor group
    pending_batches: List[ClientRequestBatch]
    resend: object


_PHASE2 = "phase2"


class MenciusLeader(Actor):
    """One member of the leader GROUP that owns one log stripe. Within a
    stripe, round r belongs to group member r % group_size; the group's
    election picks the active member (mencius/Leader.scala:244-262), and a
    replacement bumps the stripe's round and phase-1-repairs its slots."""

    def __init__(self, address, transport, logger, config: MenciusConfig,
                 options: MenciusLeaderOptions = MenciusLeaderOptions(),
                 collectors=None, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.group_index = next(
            i for i, g in enumerate(config.leader_groups) if address in g
        )
        self.index = config.leader_groups[self.group_index].index(address)
        self.group_size = len(config.leader_groups[self.group_index])
        self.round = 0
        # The stripe's owned slots stride num_leaders (= num stripes).
        self.next_slot = self.group_index
        self.chosen_watermark = 0
        self._proposals_since_watermark = 0
        self._current_proxy_leader = 0
        # Highest owned slot a replica asked us to recover; phase-1 repair
        # must propose (noop) at least up to here even if no votes exist.
        self._recover_slot = -1
        # The group's election decides which member actively runs the
        # stripe (the analog of Leader.scala:250-262).
        self.election = election.Participant(
            config.leader_election_groups[self.group_index][self.index],
            transport,
            logger,
            config.leader_election_groups[self.group_index],
            initial_leader_index=0,
            options=options.election_options,
            seed=seed,
        )
        self.election.register(
            lambda leader_index: self.leader_change(leader_index == self.index)
        )
        self.state = _PHASE2 if self.index == 0 else _INACTIVE

    def _next_owned_round(self, min_round: int) -> int:
        """The smallest round > min_round owned by this group member:
        exactly ClassicRoundRobin over the group (round r belongs to
        member r % group_size)."""
        from frankenpaxos_tpu.roundsystem import ClassicRoundRobin

        return ClassicRoundRobin(self.group_size).next_classic_round(
            self.index, min_round
        )

    def leader_change(self, is_new_leader: bool) -> None:
        if is_new_leader:
            self.round = self._next_owned_round(self.round)
            self._start_phase1()
        else:
            if isinstance(self.state, _MnPhase1):
                self.state.resend.stop()
            self.state = _INACTIVE

    # -- Helpers -------------------------------------------------------------

    def _proxy_leader(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            addr = self.config.proxy_leader_addresses[self._current_proxy_leader]
            self._current_proxy_leader = (
                self._current_proxy_leader + 1
            ) % self.config.num_proxy_leaders
            return addr
        return self.config.proxy_leader_addresses[
            self.index % self.config.num_proxy_leaders
        ]

    def _propose(self, slot: int, value: CommandBatchOrNoop) -> None:
        self.chan(self._proxy_leader()).send(
            Phase2a(slot=slot, round=self.round, value=value)
        )

    def _broadcast_watermark(self) -> None:
        watermark = MenciusHighWatermark(
            leader_index=self.group_index, slot=self.next_slot
        )
        for i, group in enumerate(self.config.leader_groups):
            if i != self.group_index:
                for leader in group:
                    self.chan(leader).send(watermark)

    def _skip_to(self, observed_slot: int) -> None:
        """Noop-fill our owned slots below another leader's watermark."""
        while self.next_slot < observed_slot:
            self._propose(self.next_slot, CommandBatchOrNoop.noop())
            self.next_slot += self.config.num_leaders

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, (ClientRequest, ClientRequestBatch)):
            if self.state == _INACTIVE:
                # Forward to the member the election currently favors.
                active = self.config.leader_groups[self.group_index][
                    self.election.leader_index % self.group_size
                ]
                self.chan(active).send(msg)
                return
            if isinstance(msg, ClientRequest):
                msg = ClientRequestBatch(CommandBatch((msg.command,)))
            self._handle_batch(msg)
        elif isinstance(msg, MenciusHighWatermark):
            if self.state == _PHASE2:
                self._skip_to(msg.slot)
        elif isinstance(msg, Phase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, Nack):
            if msg.round > self.round and self.state != _INACTIVE:
                self.round = self._next_owned_round(msg.round)
                self._start_phase1()
        elif isinstance(msg, ChosenWatermark):
            self.chosen_watermark = max(self.chosen_watermark, msg.slot)
        elif isinstance(msg, Recover):
            # A replica is stuck at msg.slot; if our stripe owns it, re-run
            # phase 1 covering it; otherwise noop-fill our residue past it.
            if self.state == _PHASE2:
                if msg.slot % self.config.num_leaders == self.group_index:
                    self._recover_slot = max(self._recover_slot, msg.slot)
                    self.round = self._next_owned_round(self.round)
                    self._start_phase1()
                else:
                    self._skip_to(msg.slot + 1)
        else:
            self.logger.fatal(f"unknown mencius leader message {msg!r}")

    def _handle_batch(self, batch: ClientRequestBatch) -> None:
        if isinstance(self.state, _MnPhase1):
            self.state.pending_batches.append(batch)
            return
        slot = self.next_slot
        self.next_slot += self.config.num_leaders
        self._propose(slot, CommandBatchOrNoop(batch.batch))
        self._proposals_since_watermark += 1
        if self._proposals_since_watermark >= self.options.send_watermark_every_n:
            self._broadcast_watermark()
            self._proposals_since_watermark = 0

    # -- Failover: phase 1 over OWNED slots ----------------------------------

    def _start_phase1(self) -> None:
        # A phase 1 may replace a still-running phase 1 (nack-driven round
        # bump): stop its resend timer or it re-broadcasts the stale-round
        # Phase1a forever.
        if isinstance(self.state, _MnPhase1):
            self.state.resend.stop()
        phase1a = Phase1a(round=self.round, chosen_watermark=self.chosen_watermark)

        def resend() -> None:
            for group in self.config.acceptor_addresses:
                for a in group:
                    self.chan(a).send(phase1a)
            timer.start()

        timer = self.timer(
            "resendPhase1as", self.options.resend_phase1as_period, resend
        )
        timer.start()
        for group in self.config.acceptor_addresses:
            quorum = self.rng.sample(range(len(group)), self.config.f + 1)
            for i in quorum:
                self.chan(group[i]).send(phase1a)
        self.state = _MnPhase1(
            phase1bs=[{} for _ in range(self.config.num_acceptor_groups)],
            pending_batches=[],
            resend=timer,
        )

    def _handle_phase1b(self, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _MnPhase1):
            return
        if phase1b.round != self.round:
            return
        phase1 = self.state
        phase1.phase1bs[phase1b.group_index][phase1b.acceptor_index] = phase1b
        if any(len(g) < self.config.f + 1 for g in phase1.phase1bs):
            return
        # Repair OWNED slots only: max voted owned slot across groups.
        owned = [
            info
            for group in phase1.phase1bs
            for b in group.values()
            for info in b.info
            if info.slot % self.config.num_leaders == self.group_index
        ]
        max_slot = max(
            (info.slot for info in owned), default=-1
        )
        # Repair every owned slot we might ever have proposed: up to the
        # max VOTED slot, up to any slot a replica asked us to recover, and
        # up to our own previous next_slot — in-flight proposals whose
        # round-0 Phase2as got nacked away have no votes, and skipping them
        # here would leave one slow Recover cycle per hole.
        top = max(max_slot, self._recover_slot,
                  self.next_slot - self.config.num_leaders)
        start = self.chosen_watermark + (
            (self.group_index - self.chosen_watermark) % self.config.num_leaders
        )
        for slot in range(start, top + 1, self.config.num_leaders):
            infos = [i for i in owned if i.slot == slot]
            value = (
                max(infos, key=lambda i: i.vote_round).vote_value
                if infos
                else CommandBatchOrNoop.noop()
            )
            self._propose(slot, value)
        # Resume proposing just past the repaired range, staying on this
        # stripe's residue (with nothing to repair, at the first owned slot
        # from the watermark).
        if top < start:
            candidate = start
        else:
            candidate = top + self.config.num_leaders
        self.next_slot = max(self.next_slot, candidate)
        phase1.resend.stop()
        pending = phase1.pending_batches
        self.state = _PHASE2
        for batch in pending:
            self._handle_batch(batch)


class MenciusAcceptor(Actor):
    """Acceptor with ONE round per leader index: leader i's failover bumps
    rounds[i] without disturbing other leaders' round-0 fast path."""

    def __init__(self, address, transport, logger, config: MenciusConfig,
                 collectors=None):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.group_index = next(
            i for i, g in enumerate(config.acceptor_addresses) if address in g
        )
        self.index = config.acceptor_addresses[self.group_index].index(address)
        self.rounds: List[int] = [-1] * config.num_leaders
        # slot -> (vote_round, value)
        self.votes: Dict[int, Tuple[int, CommandBatchOrNoop]] = {}

    def _owner(self, slot: int) -> int:
        return slot % self.config.num_leaders

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, Phase2a):
            owner = self._owner(msg.slot)
            if msg.round < self.rounds[owner]:
                # Nack the slot's OWNER group (src is a proxy leader,
                # which doesn't handle nacks — cf. Acceptor.scala:184-199);
                # inactive members ignore stale rounds.
                for leader in self.config.leader_groups[owner]:
                    self.chan(leader).send(Nack(round=self.rounds[owner]))
                return
            self.rounds[owner] = msg.round
            self.votes[msg.slot] = (msg.round, msg.value)
            self.chan(src).send(
                Phase2b(
                    group_index=self.group_index,
                    acceptor_index=self.index,
                    slot=msg.slot,
                    round=msg.round,
                )
            )
        elif isinstance(msg, Phase1a):
            # The sender is a (new) leader for ITS index; promise that
            # index's round and report votes for its owned slots.
            owner = next(
                (
                    i
                    for i, g in enumerate(self.config.leader_groups)
                    if src in g
                ),
                None,
            )
            if owner is None:
                return
            if msg.round < self.rounds[owner]:
                self.chan(src).send(Nack(round=self.rounds[owner]))
                return
            self.rounds[owner] = msg.round
            info = tuple(
                Phase1bSlotInfo(slot=slot, vote_round=vr, vote_value=value)
                for slot, (vr, value) in sorted(self.votes.items())
                if slot >= msg.chosen_watermark and self._owner(slot) == owner
            )
            self.chan(src).send(
                Phase1b(
                    group_index=self.group_index,
                    acceptor_index=self.index,
                    round=msg.round,
                    info=info,
                )
            )
        else:
            self.logger.fatal(f"unknown mencius acceptor message {msg!r}")


@dataclasses.dataclass
class _MnPending:
    id: int
    result: Promise
    resend: object


class MenciusClient(Actor):
    """Client spreading writes across the active leaders (each leader owns
    its own slot residue, so any leader serves any write)."""

    def __init__(self, address, transport, logger, config: MenciusConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _MnPending] = {}

    def _target(self) -> Address:
        if self.config.num_batchers > 0:
            return self.config.batcher_addresses[
                self.rng.randrange(self.config.num_batchers)
            ]
        group = self.config.leader_groups[
            self.rng.randrange(self.config.num_leaders)
        ]
        return group[self.rng.randrange(len(group))]

    def write(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = ClientRequest(
            Command(
                command_id=CommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=id,
                ),
                command=command,
            )
        )
        self.chan(self._target()).send(request)

        def resend() -> None:
            self.chan(self._target()).send(request)
            timer.start()

        timer = self.timer(
            f"resendMencius[{pseudonym};{id}]", self.resend_period, resend
        )
        timer.start()
        self.pending[pseudonym] = _MnPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, ClientReply):
            self.logger.fatal(f"unknown mencius client message {msg!r}")
        pseudonym = msg.command_id.client_pseudonym
        pending = self.pending.get(pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[pseudonym]
        pending.result.success(msg.result)


@dataclasses.dataclass(frozen=True)
class MenciusBatcherOptions:
    batch_size: int = 100


class MenciusBatcher(Actor):
    """Accumulates client commands and spreads full batches round-robin
    over the leader GROUPS (any stripe serves any write; the multipaxos
    Batcher would pin every batch to one leader's round)."""

    def __init__(self, address, transport, logger, config: MenciusConfig,
                 options: MenciusBatcherOptions = MenciusBatcherOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.growing_batch: List[Command] = []
        self._next_group = 0

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, ClientRequest):
            self.logger.fatal(f"unknown mencius batcher message {msg!r}")
        self.growing_batch.append(msg.command)
        if len(self.growing_batch) < self.options.batch_size:
            return
        group = self.config.leader_groups[self._next_group]
        self._next_group = (self._next_group + 1) % self.config.num_leaders
        # Any member: inactive members forward to the elected one.
        target = group[self.rng.randrange(len(group))]
        self.chan(target).send(
            ClientRequestBatch(CommandBatch(tuple(self.growing_batch)))
        )
        self.growing_batch.clear()
