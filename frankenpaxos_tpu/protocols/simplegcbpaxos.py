"""Simple GC BPaxos — Simple BPaxos with garbage collection and
snapshots (reference ``simplegcbpaxos/``; protocol cheatsheet in
``SimpleGcBPaxos.proto``).

The problem with (Simple)BPaxos is that every piece of state — the
replica's command log, the dependency service's conflict index, the
proposers' vertex states, dependency sets themselves — grows forever.
This variant compacts all of it:

  * Dependency sets are ``VertexIdPrefixSet``s: per-leader watermark +
    overflow (``VertexIdPrefixSet.scala``). Leaders assign vertex ids
    CONTIGUOUSLY so prefixes compress well.
  * Replicas store commands in a ``VertexIdBufferMap`` and periodically
    broadcast their committed frontier through a co-located
    GarbageCollector, which relays to proposers and acceptors
    (``GarbageCollector.scala:99-120``); those drop state below the
    f+1-quorum watermark (``Proposer.scala:594-627``).
  * Dependency service nodes keep a two-generation
    ``CompactConflictIndex`` whose GC'd prefix is folded into every
    dependency answer (``CompactConflictIndex.scala``).
  * Replicas periodically have a leader choose a SNAPSHOT vertex that
    depends on everything; executing it snapshots the state machine +
    client table. Recovery of a GC'd vertex is answered with
    ``CommitSnapshot`` instead (``Replica.scala:739-877``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Set, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.clienttable import ClientTable, Executed
from frankenpaxos_tpu.compact import IntPrefixSet
from frankenpaxos_tpu.depgraph import TarjanDependencyGraph
from frankenpaxos_tpu.roundsystem import RotatedClassicRoundRobin
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, QuorumWatermarkVector, random_duration

# Vertex ids are (leader_index, id) tuples; ids are assigned contiguously
# per leader, which is what makes prefix compression effective.

COMMAND = "command"
NOOP = "noop"
SNAPSHOT = "snapshot"


class VertexIdPrefixSet:
    """A compact set of vertex ids: one IntPrefixSet per leader
    (``VertexIdPrefixSet.scala``)."""

    def __init__(self, num_leaders: int,
                 sets: Optional[List[IntPrefixSet]] = None):
        self.num_leaders = num_leaders
        self.sets = sets if sets is not None else [
            IntPrefixSet() for _ in range(num_leaders)
        ]

    @staticmethod
    def from_vertices(num_leaders: int, vertex_ids) -> "VertexIdPrefixSet":
        out = VertexIdPrefixSet(num_leaders)
        for leader_index, id in vertex_ids:
            out.sets[leader_index].add(id)
        return out

    @staticmethod
    def from_watermarks(watermarks) -> "VertexIdPrefixSet":
        return VertexIdPrefixSet(
            len(watermarks),
            [IntPrefixSet.from_watermark(w) for w in watermarks],
        )

    def __repr__(self) -> str:
        return f"VertexIdPrefixSet({self.sets!r})"

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, VertexIdPrefixSet) and self.sets == other.sets
        )

    def clone(self) -> "VertexIdPrefixSet":
        return VertexIdPrefixSet(
            self.num_leaders,
            [IntPrefixSet(s.watermark, set(s.values)) for s in self.sets],
        )

    def add(self, vertex_id) -> bool:
        return self.sets[vertex_id[0]].add(vertex_id[1])

    def contains(self, vertex_id) -> bool:
        return self.sets[vertex_id[0]].contains(vertex_id[1])

    def union(self, other: "VertexIdPrefixSet") -> "VertexIdPrefixSet":
        return VertexIdPrefixSet(
            self.num_leaders,
            [a.union(b) for a, b in zip(self.sets, other.sets)],
        )

    def add_all(self, other: "VertexIdPrefixSet") -> "VertexIdPrefixSet":
        for a, b in zip(self.sets, other.sets):
            a.add_all(b)
        return self

    def subtract_one(self, vertex_id) -> "VertexIdPrefixSet":
        self.sets[vertex_id[0]].subtract_one(vertex_id[1])
        return self

    def get_watermark(self) -> List[int]:
        return [s.watermark for s in self.sets]

    @property
    def size(self) -> int:
        return sum(s.size for s in self.sets)

    def materialize(self) -> Set[tuple]:
        return {
            (i, id)
            for i, s in enumerate(self.sets)
            for id in s.materialize()
        }

    def materialized_diff(self, other: "VertexIdPrefixSet") -> Set[tuple]:
        """self - other, materialized. Cost is proportional to the DIFF,
        not to the full prefix — the point of compact sets."""
        return {
            (i, id)
            for i, (mine, theirs) in enumerate(zip(self.sets, other.sets))
            for id in mine.materialized_diff(theirs)
        }

    # Wire form: tuple of (watermark, sorted-overflow-tuple) per leader.
    def to_tuple(self) -> tuple:
        return tuple(
            (s.watermark, tuple(sorted(s.values))) for s in self.sets
        )

    @staticmethod
    def from_tuple(data: tuple) -> "VertexIdPrefixSet":
        return VertexIdPrefixSet(
            len(data),
            [IntPrefixSet(w, set(values)) for w, values in data],
        )


class VertexIdBufferMap:
    """One watermark-GC'd BufferMap per leader
    (``VertexIdBufferMap.scala``)."""

    def __init__(self, num_leaders: int, grow_size: int = 5000):
        self.maps = [BufferMap(grow_size) for _ in range(num_leaders)]

    def get(self, vertex_id):
        return self.maps[vertex_id[0]].get(vertex_id[1])

    def put(self, vertex_id, value) -> None:
        self.maps[vertex_id[0]].put(vertex_id[1], value)

    def garbage_collect(self, watermark: List[int]) -> None:
        for m, w in zip(self.maps, watermark):
            m.garbage_collect(w)


class CompactConflictIndex:
    """Two-generation conflict index with a GC watermark folded into
    every answer (``CompactConflictIndex.scala``). ``garbage_collect``
    retires the old generation: everything it covered is answered via
    the watermark from then on."""

    def __init__(self, num_leaders: int, state_machine: StateMachine):
        self.num_leaders = num_leaders
        self.state_machine = state_machine
        self.new_index = state_machine.conflict_index()
        self.new_watermark = [0] * num_leaders
        self.old_index = state_machine.conflict_index()
        self.old_watermark = [0] * num_leaders
        self.gc_watermark = [0] * num_leaders

    def put(self, vertex_id, command: bytes) -> None:
        self.new_index.put(vertex_id, command)
        leader_index, id = vertex_id
        self.new_watermark[leader_index] = max(
            self.new_watermark[leader_index], id + 1
        )

    def put_snapshot(self, vertex_id) -> None:
        self.new_index.put_snapshot(vertex_id)
        leader_index, id = vertex_id
        self.new_watermark[leader_index] = max(
            self.new_watermark[leader_index], id + 1
        )

    def get_conflicts(self, command: bytes) -> VertexIdPrefixSet:
        conflicts = VertexIdPrefixSet.from_vertices(
            self.num_leaders,
            set(self.new_index.get_conflicts(command))
            | set(self.old_index.get_conflicts(command)),
        )
        return conflicts.add_all(
            VertexIdPrefixSet.from_watermarks(self.gc_watermark)
        )

    def garbage_collect(self) -> None:
        for i in range(self.num_leaders):
            self.gc_watermark[i] = max(self.gc_watermark[i],
                                       self.old_watermark[i])
            self.old_watermark[i] = self.new_watermark[i]
            self.new_watermark[i] = 0
        self.old_index = self.new_index
        self.new_index = self.state_machine.conflict_index()

    def high_watermark(self) -> VertexIdPrefixSet:
        return VertexIdPrefixSet.from_watermarks([
            max(self.gc_watermark[i], self.old_watermark[i],
                self.new_watermark[i])
            for i in range(self.num_leaders)
        ])


# -- Messages -----------------------------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class GcCommand:
    client_address: bytes
    client_pseudonym: int
    client_id: int
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class GcClientRequest:
    command: GcCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class GcClientReply:
    client_pseudonym: int
    client_id: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class GcSnapshotRequest:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class GcDependencyRequest:
    vertex_id: tuple
    kind: str  # COMMAND or SNAPSHOT
    command: Optional[GcCommand] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class GcDependencyReply:
    vertex_id: tuple
    dep_service_node_index: int
    dependencies: tuple  # VertexIdPrefixSet.to_tuple()


@wire.message
@dataclasses.dataclass(frozen=True)
class GcPropose:
    vertex_id: tuple
    kind: str
    command: Optional[GcCommand]
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class GcPhase1a:
    vertex_id: tuple
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class GcPhase1b:
    vertex_id: tuple
    acceptor_id: int
    round: int
    vote_round: int
    vote_value: Optional[tuple]  # (kind, command|None, dependencies)


@wire.message
@dataclasses.dataclass(frozen=True)
class GcPhase2a:
    vertex_id: tuple
    round: int
    vote_value: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class GcPhase2b:
    vertex_id: tuple
    acceptor_id: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class GcNack:
    vertex_id: tuple
    higher_round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class GcCommit:
    vertex_id: tuple
    kind: str
    command: Optional[GcCommand]
    dependencies: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class GcRecover:
    vertex_id: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class GcCommitSnapshot:
    id: int
    watermark: tuple  # VertexIdPrefixSet.to_tuple()
    state_machine: bytes
    client_table: tuple  # of (client_address, pseudonym, client_id, output)


@wire.message
@dataclasses.dataclass(frozen=True)
class GcGarbageCollect:
    replica_index: int
    frontier: tuple  # per-leader committed watermark


@dataclasses.dataclass(frozen=True)
class SimpleGcBPaxosConfig:
    f: int
    leader_addresses: tuple
    proposer_addresses: tuple  # co-located with leaders, same length
    dep_service_node_addresses: tuple  # 2f+1
    acceptor_addresses: tuple  # 2f+1
    replica_addresses: tuple  # f+1
    garbage_collector_addresses: tuple  # co-located with replicas

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    @property
    def num_leaders(self) -> int:
        return len(self.leader_addresses)

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.proposer_addresses) != len(self.leader_addresses):
            raise ValueError("one proposer per leader")
        if len(self.dep_service_node_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 dep service nodes")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")
        if len(self.replica_addresses) < self.f + 1:
            raise ValueError("need >= f+1 replicas")
        if len(self.garbage_collector_addresses) != len(self.replica_addresses):
            raise ValueError("one garbage collector per replica")


# -- Leader -------------------------------------------------------------------


@dataclasses.dataclass
class _GcLeaderState:
    kind: str
    command: Optional[GcCommand]
    replies: Dict[int, GcDependencyReply]
    resend: object


class GcLeader(Actor):
    """``simplegcbpaxos/Leader.scala``: contiguous vertex ids, dependency
    aggregation by prefix-set union, hand-off to the co-located
    proposer. Also accepts SnapshotRequests from replicas."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig,
                 resend_period: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.index = config.leader_addresses.index(address)
        self.next_vertex_id = 0
        self.states: Dict[tuple, _GcLeaderState] = {}

    def _thrifty_dep_nodes(self):
        nodes = self.config.dep_service_node_addresses
        return [
            nodes[i]
            for i in self.rng.sample(range(len(nodes)), self.config.quorum_size)
        ]

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, GcClientRequest):
            self._handle_request(COMMAND, msg.command)
        elif isinstance(msg, GcSnapshotRequest):
            self._handle_request(SNAPSHOT, None)
        elif isinstance(msg, GcDependencyReply):
            self._handle_dependency_reply(msg)
        else:
            self.logger.fatal(f"unknown gc leader message {msg!r}")

    def _handle_request(self, kind: str, command: Optional[GcCommand]) -> None:
        vertex_id = (self.index, self.next_vertex_id)
        self.next_vertex_id += 1
        request = GcDependencyRequest(
            vertex_id=vertex_id, kind=kind, command=command
        )
        # Thrifty first send to a random quorum (Leader.scala
        # thriftyDepServiceNodes); the resend timer goes wide.
        for a in self._thrifty_dep_nodes():
            self.chan(a).send(request)

        def resend() -> None:
            for a in self.config.dep_service_node_addresses:
                self.chan(a).send(request)
            timer.start()

        timer = self.timer(
            f"resendDeps{vertex_id}", self.resend_period, resend
        )
        timer.start()
        self.states[vertex_id] = _GcLeaderState(
            kind=kind, command=command, replies={}, resend=timer
        )

    def _handle_dependency_reply(self, msg: GcDependencyReply) -> None:
        state = self.states.get(msg.vertex_id)
        if state is None:
            return
        state.replies[msg.dep_service_node_index] = msg
        if len(state.replies) < self.config.quorum_size:
            return
        dependencies = VertexIdPrefixSet(self.config.num_leaders)
        for reply in state.replies.values():
            dependencies.add_all(
                VertexIdPrefixSet.from_tuple(reply.dependencies)
            )
        state.resend.stop()
        del self.states[msg.vertex_id]
        self.chan(self.config.proposer_addresses[self.index]).send(
            GcPropose(
                vertex_id=msg.vertex_id,
                kind=state.kind,
                command=state.command,
                dependencies=dependencies.to_tuple(),
            )
        )


# -- Dependency service -------------------------------------------------------


class GcDepServiceNode(Actor):
    """``simplegcbpaxos/DepServiceNode.scala`` with the compacted
    conflict index: every answer folds in the GC watermark, and every
    ``garbage_collect_every_n_commands`` commands the old generation is
    retired. Snapshot requests depend on EVERYTHING seen so far (the
    index's high watermark)."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig, state_machine: StateMachine,
                 garbage_collect_every_n_commands: int = 100):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.dep_service_node_addresses)
        self.config = config
        self.index = config.dep_service_node_addresses.index(address)
        self.conflict_index = CompactConflictIndex(
            config.num_leaders, state_machine
        )
        self.garbage_collect_every_n_commands = garbage_collect_every_n_commands
        self._commands_since_gc = 0

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, GcDependencyRequest):
            self.logger.fatal(f"unknown dep service message {msg!r}")
        if msg.kind == SNAPSHOT:
            dependencies = self.conflict_index.high_watermark()
            dependencies.subtract_one(msg.vertex_id)
            self.conflict_index.put_snapshot(msg.vertex_id)
        else:
            dependencies = self.conflict_index.get_conflicts(
                msg.command.command
            )
            dependencies.subtract_one(msg.vertex_id)
            self.conflict_index.put(msg.vertex_id, msg.command.command)
        self.chan(src).send(
            GcDependencyReply(
                vertex_id=msg.vertex_id,
                dep_service_node_index=self.index,
                dependencies=dependencies.to_tuple(),
            )
        )
        self._commands_since_gc += 1
        if self._commands_since_gc >= self.garbage_collect_every_n_commands:
            self.conflict_index.garbage_collect()
            self._commands_since_gc = 0


# -- Proposer -----------------------------------------------------------------


@dataclasses.dataclass
class _GcPhase1:
    round: int
    value: tuple
    phase1bs: Dict[int, GcPhase1b]
    resend: object


@dataclasses.dataclass
class _GcPhase2:
    round: int
    value: tuple
    phase2bs: Dict[int, GcPhase2b]
    resend: object


@dataclasses.dataclass
class _GcChosen:
    value: tuple


class GcProposer(Actor):
    """``simplegcbpaxos/Proposer.scala``: per-vertex Paxos with a
    GC watermark — any message about a vertex below the f+1-quorum
    replica frontier is dropped, and chosen state below it is
    discarded."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig,
                 resend_period: float = 5.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.proposer_addresses)
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.index = config.proposer_addresses.index(address)
        self.states: Dict[tuple, object] = {}
        self.gc_vector = QuorumWatermarkVector(
            n=len(config.replica_addresses), depth=config.num_leaders
        )
        self.gc_watermark: List[int] = self.gc_vector.watermark(
            quorum_size=config.f + 1
        )

    def _gcd(self, vertex_id: tuple) -> bool:
        return vertex_id[1] < self.gc_watermark[vertex_id[0]]

    def _round_system(self, vertex_id: tuple):
        return RotatedClassicRoundRobin(
            self.config.num_leaders, vertex_id[0]
        )

    def _thrifty_acceptors(self):
        acceptors = self.config.acceptor_addresses
        return [
            acceptors[i]
            for i in self.rng.sample(
                range(len(acceptors)), self.config.quorum_size
            )
        ]

    def _make_resend(self, name, msg):
        def fire() -> None:
            for a in self.config.acceptor_addresses:
                self.chan(a).send(msg)
            timer.start()

        timer = self.timer(name, self.resend_period, fire)
        timer.start()
        return timer

    def _propose_impl(self, vertex_id: tuple, value: tuple) -> None:
        if vertex_id in self.states:
            return
        round = self._round_system(vertex_id).next_classic_round(
            self.index, -1
        )
        if round == 0:
            phase2a = GcPhase2a(vertex_id=vertex_id, round=0, vote_value=value)
            for a in self._thrifty_acceptors():
                self.chan(a).send(phase2a)
            self.states[vertex_id] = _GcPhase2(
                round=0, value=value, phase2bs={},
                resend=self._make_resend(f"resendPhase2a{vertex_id}", phase2a),
            )
        else:
            phase1a = GcPhase1a(vertex_id=vertex_id, round=round)
            for a in self._thrifty_acceptors():
                self.chan(a).send(phase1a)
            self.states[vertex_id] = _GcPhase1(
                round=round, value=value, phase1bs={},
                resend=self._make_resend(f"resendPhase1a{vertex_id}", phase1a),
            )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, GcGarbageCollect):
            self._handle_garbage_collect(msg)
            return
        if hasattr(msg, "vertex_id") and self._gcd(msg.vertex_id):
            return  # below the GC watermark: ignore (Proposer.scala:312)
        if isinstance(msg, GcPropose):
            self._propose_impl(
                msg.vertex_id, (msg.kind, msg.command, msg.dependencies)
            )
        elif isinstance(msg, GcPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, GcPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, GcNack):
            self._handle_nack(msg)
        elif isinstance(msg, GcRecover):
            self._handle_recover(src, msg)
        else:
            self.logger.fatal(f"unknown gc proposer message {msg!r}")

    def _handle_phase1b(self, msg: GcPhase1b) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _GcPhase1) or msg.round != state.round:
            return
        state.phase1bs[msg.acceptor_id] = msg
        if len(state.phase1bs) < self.config.quorum_size:
            return
        max_vote_round = max(b.vote_round for b in state.phase1bs.values())
        if max_vote_round == -1:
            value = state.value
        else:
            value = next(
                b.vote_value for b in state.phase1bs.values()
                if b.vote_round == max_vote_round
            )
        phase2a = GcPhase2a(
            vertex_id=msg.vertex_id, round=state.round, vote_value=value
        )
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase2a)
        state.resend.stop()
        self.states[msg.vertex_id] = _GcPhase2(
            round=state.round, value=value, phase2bs={},
            resend=self._make_resend(f"resendPhase2a{msg.vertex_id}", phase2a),
        )

    def _handle_phase2b(self, msg: GcPhase2b) -> None:
        state = self.states.get(msg.vertex_id)
        if not isinstance(state, _GcPhase2) or msg.round != state.round:
            return
        state.phase2bs[msg.acceptor_id] = msg
        if len(state.phase2bs) < self.config.quorum_size:
            return
        state.resend.stop()
        self.states[msg.vertex_id] = _GcChosen(value=state.value)
        kind, command, dependencies = state.value
        commit = GcCommit(
            vertex_id=msg.vertex_id, kind=kind, command=command,
            dependencies=dependencies,
        )
        for replica in self.config.replica_addresses:
            self.chan(replica).send(commit)

    def _handle_nack(self, msg: GcNack) -> None:
        state = self.states.get(msg.vertex_id)
        if state is None or isinstance(state, _GcChosen):
            return
        if msg.higher_round <= state.round:
            return
        round = self._round_system(msg.vertex_id).next_classic_round(
            self.index, msg.higher_round
        )
        phase1a = GcPhase1a(vertex_id=msg.vertex_id, round=round)
        for a in self.config.acceptor_addresses:
            self.chan(a).send(phase1a)
        state.resend.stop()
        self.states[msg.vertex_id] = _GcPhase1(
            round=round, value=state.value, phase1bs={},
            resend=self._make_resend(f"resendPhase1a{msg.vertex_id}", phase1a),
        )

    def _handle_recover(self, src: Address, msg: GcRecover) -> None:
        state = self.states.get(msg.vertex_id)
        if state is None:
            # Propose a noop with no dependencies to fill the hole.
            self._propose_impl(
                msg.vertex_id,
                (NOOP, None,
                 VertexIdPrefixSet(self.config.num_leaders).to_tuple()),
            )
        elif isinstance(state, _GcChosen):
            kind, command, dependencies = state.value
            self.chan(src).send(
                GcCommit(
                    vertex_id=msg.vertex_id, kind=kind, command=command,
                    dependencies=dependencies,
                )
            )

    def _handle_garbage_collect(self, msg: GcGarbageCollect) -> None:
        self.gc_vector.update(msg.replica_index, list(msg.frontier))
        self.gc_watermark = self.gc_vector.watermark(
            quorum_size=self.config.f + 1
        )
        # Drop (and silence) all state below the watermark. NOTE: the
        # reference stops timers for vertices ABOVE the watermark
        # (Proposer.scala:612-620), which looks inverted; we stop timers
        # for the vertices being dropped.
        for vertex_id in [v for v in self.states if self._gcd(v)]:
            state = self.states.pop(vertex_id)
            if isinstance(state, (_GcPhase1, _GcPhase2)):
                state.resend.stop()


# -- Acceptor -----------------------------------------------------------------


class GcAcceptor(Actor):
    """Per-vertex (round, voteRound, voteValue), with GC
    (``simplegcbpaxos/Acceptor.scala``)."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        # vertex -> [round, vote_round, vote_value]
        self.states: Dict[tuple, list] = {}
        self.gc_vector = QuorumWatermarkVector(
            n=len(config.replica_addresses), depth=config.num_leaders
        )
        self.gc_watermark: List[int] = self.gc_vector.watermark(
            quorum_size=config.f + 1
        )

    def _gcd(self, vertex_id: tuple) -> bool:
        return vertex_id[1] < self.gc_watermark[vertex_id[0]]

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, GcGarbageCollect):
            self.gc_vector.update(msg.replica_index, list(msg.frontier))
            self.gc_watermark = self.gc_vector.watermark(
                quorum_size=self.config.f + 1
            )
            for vertex_id in [v for v in self.states if self._gcd(v)]:
                del self.states[vertex_id]
            return
        if self._gcd(msg.vertex_id):
            return
        if isinstance(msg, GcPhase1a):
            state = self.states.setdefault(msg.vertex_id, [-1, -1, None])
            if msg.round < state[0]:
                self.chan(src).send(
                    GcNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            self.chan(src).send(
                GcPhase1b(
                    vertex_id=msg.vertex_id, acceptor_id=self.index,
                    round=msg.round, vote_round=state[1], vote_value=state[2],
                )
            )
        elif isinstance(msg, GcPhase2a):
            state = self.states.setdefault(msg.vertex_id, [-1, -1, None])
            if msg.round < state[0]:
                self.chan(src).send(
                    GcNack(vertex_id=msg.vertex_id, higher_round=state[0])
                )
                return
            state[0] = msg.round
            state[1] = msg.round
            state[2] = msg.vote_value
            self.chan(src).send(
                GcPhase2b(
                    vertex_id=msg.vertex_id, acceptor_id=self.index,
                    round=msg.round,
                )
            )
        else:
            self.logger.fatal(f"unknown gc acceptor message {msg!r}")


# -- Replica ------------------------------------------------------------------


@dataclasses.dataclass
class _GcSnapshot:
    id: int
    watermark: VertexIdPrefixSet
    state_machine: bytes
    client_table: tuple


@dataclasses.dataclass(frozen=True)
class GcReplicaOptions:
    send_watermark_every_n_commands: int = 10
    send_snapshot_every_n_commands: int = 100
    recover_min_period: float = 5.0
    recover_max_period: float = 10.0
    commands_grow_size: int = 5000


class GcReplica(Actor):
    """``simplegcbpaxos/Replica.scala``: committed commands live in a
    GC'd VertexIdBufferMap; ``committed_vertices`` / ``executed_vertices``
    prefix sets never forget. Executing a SNAPSHOT vertex captures the
    state machine + client table; recovery of a GC'd vertex is served
    from the snapshot (CommitSnapshot)."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig, state_machine: StateMachine,
                 options: GcReplicaOptions = GcReplicaOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.replica_addresses.index(address)
        self.dependency_graph = TarjanDependencyGraph()
        self.commands = VertexIdBufferMap(
            config.num_leaders, options.commands_grow_size
        )
        self.committed_vertices = VertexIdPrefixSet(config.num_leaders)
        self.executed_vertices = VertexIdPrefixSet(config.num_leaders)
        self.snapshot: Optional[_GcSnapshot] = None
        self.history: List[tuple] = []
        self.client_table: ClientTable = ClientTable()
        self.recover_timers: Dict[tuple, object] = {}
        self._pending_watermark = 0
        # Stagger snapshot requests across replicas (Replica.scala:278).
        self._pending_snapshot = options.send_snapshot_every_n_commands * \
            self.index

    # -- Execution ------------------------------------------------------------

    def _execute(self) -> None:
        executables, blockers = self.dependency_graph.execute()
        for vertex_id in blockers:
            if vertex_id not in self.recover_timers:
                self.recover_timers[vertex_id] = self._make_recover_timer(
                    vertex_id
                )
        for vertex_id in executables:
            committed = self.commands.get(vertex_id)
            if committed is None:
                self.logger.fatal(
                    f"vertex {vertex_id} executable but not present"
                )
            self._execute_proposal(vertex_id, committed[0], committed[1])

    def _execute_proposal(self, vertex_id: tuple, kind: str,
                          command: Optional[GcCommand]) -> None:
        self.executed_vertices.add(vertex_id)
        if kind == NOOP:
            return
        if kind == SNAPSHOT:
            self.snapshot = _GcSnapshot(
                id=(self.snapshot.id + 1) if self.snapshot else 0,
                watermark=self.executed_vertices.clone(),
                state_machine=self.state_machine.to_bytes(),
                client_table=self._client_table_tuple(),
            )
            self.history.clear()
            self.commands.garbage_collect(
                self.executed_vertices.get_watermark()
            )
            return
        # COMMAND
        identity = (command.client_address, command.client_pseudonym)
        cached = self.client_table.executed(identity, command.client_id)
        if isinstance(cached, Executed):
            if cached.output is not None and self._replies(vertex_id):
                self._reply(command, cached.output)
            return
        output = self.state_machine.run(command.command)
        self.client_table.execute(identity, command.client_id, output)
        self.history.append(vertex_id)
        if self._replies(vertex_id):
            self._reply(command, output)

    def _replies(self, vertex_id: tuple) -> bool:
        # One designated replier per leader index (Replica.scala:573).
        return self.index == vertex_id[0] % len(self.config.replica_addresses)

    def _reply(self, command: GcCommand, output: bytes) -> None:
        client = self.transport.address_from_bytes(command.client_address)
        self.chan(client).send(
            GcClientReply(
                client_pseudonym=command.client_pseudonym,
                client_id=command.client_id,
                result=output,
            )
        )

    def _client_table_tuple(self):
        # Identities are (address_bytes, pseudonym); encode via the wire
        # codec so the full table (incl. executed-id prefix sets) survives.
        return self.client_table.to_proto(
            address_to_bytes=lambda ident: wire.encode(ident),
            output_to_bytes=lambda output: output,
        )

    def _client_table_from_tuple(self, proto) -> ClientTable:
        return ClientTable.from_proto(
            proto,
            address_from_bytes=lambda data: tuple(wire.decode(data)),
            output_from_bytes=lambda output: output,
        )

    # -- GC / snapshot triggers ----------------------------------------------

    def _send_watermark_if_needed(self) -> None:
        self._pending_watermark += 1
        if self._pending_watermark % \
                self.options.send_watermark_every_n_commands == 0:
            self.chan(
                self.config.garbage_collector_addresses[self.index]
            ).send(
                GcGarbageCollect(
                    replica_index=self.index,
                    frontier=tuple(self.committed_vertices.get_watermark()),
                )
            )
            self._pending_watermark = 0

    def _send_snapshot_if_needed(self) -> None:
        self._pending_snapshot += 1
        n = self.options.send_snapshot_every_n_commands * \
            len(self.config.replica_addresses)
        if self._pending_snapshot % n == 0:
            leader = self.config.leader_addresses[
                self.rng.randrange(self.config.num_leaders)
            ]
            self.chan(leader).send(GcSnapshotRequest())
            self._pending_snapshot = 0

    # -- Timers ---------------------------------------------------------------

    def _make_recover_timer(self, vertex_id: tuple):
        def fire() -> None:
            proposer = self.config.proposer_addresses[
                self.rng.randrange(len(self.config.proposer_addresses))
            ]
            self.chan(proposer).send(GcRecover(vertex_id=vertex_id))
            # Proposers may have GC'd the vertex; replicas haven't
            # (Replica.scala:640-646).
            for replica in self.config.replica_addresses:
                if replica != self.address:
                    self.chan(replica).send(GcRecover(vertex_id=vertex_id))
            timer.start()

        timer = self.timer(
            f"recoverVertex{vertex_id}",
            random_duration(
                self.rng, self.options.recover_min_period,
                self.options.recover_max_period,
            ),
            fire,
        )
        timer.start()
        return timer

    # -- Handlers -------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, GcCommit):
            self._handle_commit(msg)
        elif isinstance(msg, GcRecover):
            self._handle_recover(src, msg)
        elif isinstance(msg, GcCommitSnapshot):
            self._handle_commit_snapshot(msg)
        else:
            self.logger.fatal(f"unknown gc replica message {msg!r}")

    def _handle_commit(self, msg: GcCommit) -> None:
        if self.committed_vertices.contains(msg.vertex_id):
            return
        dependencies = VertexIdPrefixSet.from_tuple(msg.dependencies)
        self.commands.put(msg.vertex_id, (msg.kind, msg.command, dependencies))
        self.committed_vertices.add(msg.vertex_id)
        timer = self.recover_timers.pop(msg.vertex_id, None)
        if timer is not None:
            timer.stop()
        # Only the NOT-yet-executed dependencies matter to the graph
        # (executed ones are already ordered before us), and the diff
        # against the executed prefix stays small even though the folded
        # GC watermark makes the full dependency set O(history).
        self.dependency_graph.commit(
            msg.vertex_id, 0,
            dependencies.materialized_diff(self.executed_vertices),
        )
        self._execute()
        self._send_watermark_if_needed()
        self._send_snapshot_if_needed()

    def _handle_recover(self, src: Address, msg: GcRecover) -> None:
        if (
            self.snapshot is not None
            and self.snapshot.watermark.contains(msg.vertex_id)
        ):
            self.chan(src).send(
                GcCommitSnapshot(
                    id=self.snapshot.id,
                    watermark=self.snapshot.watermark.to_tuple(),
                    state_machine=self.snapshot.state_machine,
                    client_table=self.snapshot.client_table,
                )
            )
            return
        committed = self.commands.get(msg.vertex_id)
        if committed is not None:
            kind, command, dependencies = committed
            self.chan(src).send(
                GcCommit(
                    vertex_id=msg.vertex_id, kind=kind, command=command,
                    dependencies=dependencies.to_tuple(),
                )
            )

    def _handle_commit_snapshot(self, msg: GcCommitSnapshot) -> None:
        if self.snapshot is not None and msg.id <= self.snapshot.id:
            return
        self.state_machine.from_bytes(msg.state_machine)
        self.client_table = self._client_table_from_tuple(msg.client_table)
        watermark = VertexIdPrefixSet.from_tuple(msg.watermark)
        newly_executed = watermark.materialized_diff(self.executed_vertices)
        self.commands.garbage_collect(watermark.get_watermark())
        self.committed_vertices.add_all(watermark)
        self.executed_vertices.add_all(watermark)
        self.snapshot = _GcSnapshot(
            id=msg.id, watermark=watermark,
            state_machine=msg.state_machine, client_table=msg.client_table,
        )
        for vertex_id in [
            v for v in self.recover_timers if watermark.contains(v)
        ]:
            self.recover_timers.pop(vertex_id).stop()
        # Re-execute unsnapshotted history on top of the snapshot state
        # (Replica.scala:820-850). Detach first: _execute_proposal appends
        # to self.history, so iterating it in place would double entries
        # (and re-send cached replies) on every install.
        old_history, self.history = self.history, []
        for vertex_id in old_history:
            if watermark.contains(vertex_id):
                continue
            committed = self.commands.get(vertex_id)
            self.logger.check(committed is not None)
            self._execute_proposal(vertex_id, committed[0], committed[1])
        self.dependency_graph.update_executed(newly_executed)
        self._execute()


# -- Garbage collector --------------------------------------------------------


class GcGarbageCollector(Actor):
    """``simplegcbpaxos/GarbageCollector.scala``: relays a replica's
    committed frontier to every proposer and acceptor."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, GcGarbageCollect):
            self.logger.fatal(f"unknown garbage collector message {msg!r}")
        for a in self.config.proposer_addresses:
            self.chan(a).send(msg)
        for a in self.config.acceptor_addresses:
            self.chan(a).send(msg)


# -- Client -------------------------------------------------------------------


@dataclasses.dataclass
class _GcPending:
    id: int
    command: bytes
    result: Promise
    resend: object


class GcClient(Actor):
    """``simplegcbpaxos/Client.scala``: proposes through a random
    leader; a fresh vertex id is assigned on every retransmission, so
    replica-side dedup (client table) provides at-most-once."""

    def __init__(self, address, transport, logger,
                 config: SimpleGcBPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _GcPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = GcClientRequest(
            command=GcCommand(
                client_address=self.address_bytes,
                client_pseudonym=pseudonym,
                client_id=id,
                command=command,
            )
        )

        def send() -> None:
            leader = self.config.leader_addresses[
                self.rng.randrange(self.config.num_leaders)
            ]
            self.chan(leader).send(request)

        def resend() -> None:
            send()
            timer.start()

        timer = self.timer(f"resendGc{pseudonym}", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _GcPending(
            id=id, command=command, result=promise, resend=timer
        )
        send()
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, GcClientReply):
            self.logger.fatal(f"unknown gc client message {msg!r}")
        pending = self.pending.get(msg.client_pseudonym)
        if pending is None or msg.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[msg.client_pseudonym]
        pending.result.success(msg.result)
