"""Batched unreplicated state machine (reference ``batchedunreplicated/``:
Client, Batcher, Server, ProxyServer) — the decoupled-batching pattern in
its simplest setting: batchers accumulate commands into batches, one
server executes batches, and proxy servers fan the replies back out to
clients."""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.statemachine import StateMachine


@wire.message
@dataclasses.dataclass(frozen=True)
class BuCommandId:
    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BuCommand:
    command_id: BuCommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class BuClientRequest:
    command: BuCommand


@wire.message
@dataclasses.dataclass(frozen=True)
class BuClientRequestBatch:
    commands: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class BuClientReply:
    command_id: BuCommandId
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class BuClientReplyBatch:
    replies: tuple


@dataclasses.dataclass(frozen=True)
class BatchedUnreplicatedConfig:
    batcher_addresses: tuple
    server_address: object
    proxy_server_addresses: tuple

    def check_valid(self) -> None:
        if not self.batcher_addresses:
            raise ValueError("need at least one batcher")
        if not self.proxy_server_addresses:
            raise ValueError("need at least one proxy server")


@dataclasses.dataclass(frozen=True)
class BuBatcherOptions:
    batch_size: int = 100


class BuBatcher(Actor):
    def __init__(self, address, transport, logger,
                 config: BatchedUnreplicatedConfig,
                 options: BuBatcherOptions = BuBatcherOptions()):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.growing_batch: List[BuCommand] = []

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BuClientRequest):
            self.logger.fatal(f"unknown batcher message {msg!r}")
        self.growing_batch.append(msg.command)
        if len(self.growing_batch) >= self.options.batch_size:
            self.chan(self.config.server_address).send(
                BuClientRequestBatch(tuple(self.growing_batch))
            )
            self.growing_batch.clear()


class BuServer(Actor):
    def __init__(self, address, transport, logger,
                 config: BatchedUnreplicatedConfig,
                 state_machine: StateMachine, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self._current_proxy = 0

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BuClientRequestBatch):
            self.logger.fatal(f"unknown server message {msg!r}")
        replies = tuple(
            BuClientReply(
                command_id=c.command_id,
                result=self.state_machine.run(c.command),
            )
            for c in msg.commands
        )
        # Round-robin over proxy servers (the compartmentalized fan-out).
        proxy = self.config.proxy_server_addresses[self._current_proxy]
        self._current_proxy = (
            self._current_proxy + 1
        ) % len(self.config.proxy_server_addresses)
        self.chan(proxy).send(BuClientReplyBatch(replies))


class BuProxyServer(Actor):
    def __init__(self, address, transport, logger,
                 config: BatchedUnreplicatedConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self._clients: Dict[bytes, Address] = {}

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BuClientReplyBatch):
            self.logger.fatal(f"unknown proxy server message {msg!r}")
        for reply in msg.replies:
            addr_bytes = reply.command_id.client_address
            client = self._clients.get(addr_bytes)
            if client is None:
                client = self.transport.address_from_bytes(addr_bytes)
                self._clients[addr_bytes] = client
            self.chan(client).send(reply)


@dataclasses.dataclass
class _BuPending:
    id: int
    result: Promise
    resend: object


class BuClient(Actor):
    def __init__(self, address, transport, logger,
                 config: BatchedUnreplicatedConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _BuPending] = {}

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1
        request = BuClientRequest(
            BuCommand(
                command_id=BuCommandId(self.address_bytes, pseudonym, id),
                command=command,
            )
        )
        batcher = self.config.batcher_addresses[
            self.rng.randrange(len(self.config.batcher_addresses))
        ]
        self.chan(batcher).send(request)

        def resend() -> None:
            target = self.config.batcher_addresses[
                self.rng.randrange(len(self.config.batcher_addresses))
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(f"resendBu[{pseudonym};{id}]", self.resend_period, resend)
        timer.start()
        self.pending[pseudonym] = _BuPending(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, BuClientReply):
            self.logger.fatal(f"unknown client message {msg!r}")
        pseudonym = msg.command_id.client_pseudonym
        pending = self.pending.get(pseudonym)
        if pending is None or msg.command_id.client_id != pending.id:
            return
        pending.resend.stop()
        del self.pending[pseudonym]
        pending.result.success(msg.result)
