"""MultiPaxos ProxyReplica (reference ``multipaxos/ProxyReplica.scala``):
fans replica output (client replies, read replies) out to clients, and
forwards ChosenWatermark/Recover notifications to all leaders."""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import Config
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    ClientReplyBatch,
    ReadReplyBatch,
    Recover,
)


@dataclasses.dataclass(frozen=True)
class ProxyReplicaOptions:
    flush_every_n: int = 1
    batch_flush: bool = False
    measure_latencies: bool = True


class ProxyReplica(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: ProxyReplicaOptions = ProxyReplicaOptions(),
        collectors: Optional[Collectors] = None,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        collectors = collectors or FakeCollectors()
        self.requests_total = collectors.counter(
            "multipaxos_proxy_replica_requests_total", "requests", labels=("type",)
        )
        self._num_unflushed = 0
        self._client_addrs: Dict[bytes, Address] = {}

    def _client(self, client_address_bytes: bytes) -> Address:
        addr = self._client_addrs.get(client_address_bytes)
        if addr is None:
            addr = self.transport.address_from_bytes(client_address_bytes)
            self._client_addrs[client_address_bytes] = addr
        return addr

    def receive(self, src: Address, msg) -> None:
        self.requests_total.labels(type(msg).__name__).inc()
        if isinstance(msg, ClientReplyBatch):
            self._fan_out(msg.batch)
        elif isinstance(msg, ReadReplyBatch):
            self._fan_out(msg.batch)
        elif isinstance(msg, (ChosenWatermark, Recover)):
            for leader in self.config.leader_addresses:
                self.chan(leader).send(msg)
        else:
            self.logger.fatal(f"unknown proxy replica message {msg!r}")

    def _fan_out(self, replies) -> None:
        for reply in replies:
            client = self._client(reply.command_id.client_address)
            if self.options.batch_flush:
                self.chan(client).send_no_flush(reply)
            elif self.options.flush_every_n == 1:
                self.chan(client).send(reply)
            else:
                self.chan(client).send_no_flush(reply)
                self._num_unflushed += 1
                if self._num_unflushed >= self.options.flush_every_n:
                    for addr in self._client_addrs.values():
                        self.flush(addr)
                    self._num_unflushed = 0
        if self.options.batch_flush:
            for addr in self._client_addrs.values():
                self.flush(addr)
