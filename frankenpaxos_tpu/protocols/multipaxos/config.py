"""MultiPaxos cluster configuration (the analog of
``multipaxos/Config.scala:6-148`` and ``DistributionScheme.scala``)."""

from __future__ import annotations

import dataclasses
import enum
from typing import Sequence, Tuple

from frankenpaxos_tpu.core import Address


class DistributionScheme(enum.Enum):
    """Hash = spread work over decoupled roles; Colocated = co-locate one
    batcher/proxy-leader per leader and one proxy-replica per replica to
    simulate coupled MultiPaxos (DistributionScheme.scala)."""

    HASH = "hash"
    COLOCATED = "colocated"


@dataclasses.dataclass(frozen=True)
class Config:
    f: int
    batcher_addresses: Tuple[Address, ...]
    read_batcher_addresses: Tuple[Address, ...]
    leader_addresses: Tuple[Address, ...]
    leader_election_addresses: Tuple[Address, ...]
    proxy_leader_addresses: Tuple[Address, ...]
    # Non-flexible: each inner tuple is one 2f+1 acceptor group and slots are
    # round-robined over groups. Flexible: the inner tuples are the rows of
    # one grid quorum system (rows = phase-1 read quorums, columns = phase-2
    # write quorums).
    acceptor_addresses: Tuple[Tuple[Address, ...], ...]
    replica_addresses: Tuple[Address, ...]
    proxy_replica_addresses: Tuple[Address, ...]
    flexible: bool = False
    distribution_scheme: DistributionScheme = DistributionScheme.HASH

    @property
    def num_batchers(self) -> int:
        return len(self.batcher_addresses)

    @property
    def num_read_batchers(self) -> int:
        return len(self.read_batcher_addresses)

    @property
    def num_leaders(self) -> int:
        return len(self.leader_addresses)

    @property
    def num_proxy_leaders(self) -> int:
        return len(self.proxy_leader_addresses)

    @property
    def num_acceptor_groups(self) -> int:
        return len(self.acceptor_addresses)

    @property
    def num_replicas(self) -> int:
        return len(self.replica_addresses)

    @property
    def num_proxy_replicas(self) -> int:
        return len(self.proxy_replica_addresses)

    def check_valid(self) -> None:
        """Mirror of Config.checkValid (Config.scala:32-148)."""
        f = self.f
        if f < 1:
            raise ValueError(f"f must be >= 1. It's {f}.")
        if self.distribution_scheme == DistributionScheme.HASH:
            if not (self.num_batchers == 0 or self.num_batchers >= f + 1):
                raise ValueError("numBatchers must be 0 or >= f + 1.")
        else:
            if not (
                self.num_batchers == 0 or self.num_batchers == self.num_leaders
            ):
                raise ValueError("numBatchers must be 0 or equal numLeaders.")
        if not (self.num_read_batchers == 0 or self.num_read_batchers >= f + 1):
            raise ValueError("numReadBatchers must be 0 or >= f + 1.")
        if self.num_leaders < f + 1:
            raise ValueError("numLeaders must be >= f + 1.")
        if len(self.leader_election_addresses) != self.num_leaders:
            raise ValueError("need one election address per leader.")
        if self.num_proxy_leaders < f + 1:
            raise ValueError("numProxyLeaders must be >= f + 1.")
        if (
            self.distribution_scheme == DistributionScheme.COLOCATED
            and self.num_proxy_leaders != self.num_leaders
        ):
            raise ValueError("Colocated: numProxyLeaders must equal numLeaders.")
        if self.num_acceptor_groups < 1:
            raise ValueError("numAcceptorGroups must be >= 1.")
        if not self.flexible:
            for group in self.acceptor_addresses:
                if len(group) != 2 * f + 1:
                    raise ValueError(
                        f"acceptor group size must be 2f+1 ({2 * f + 1}); "
                        f"it's {len(group)}."
                    )
        else:
            m = len(self.acceptor_addresses[0])
            for row in self.acceptor_addresses:
                if len(row) != m:
                    raise ValueError("grid rows must be the same size.")
            n = self.num_acceptor_groups
            if min(n, m) - 1 < f:
                raise ValueError(
                    f"a {n}x{m} grid tolerates {min(n, m) - 1} failures < f={f}."
                )
        if self.num_replicas < f + 1:
            raise ValueError("numReplicas must be >= f + 1.")
        if not (self.num_proxy_replicas == 0 or self.num_proxy_replicas >= f + 1):
            raise ValueError("numProxyReplicas must be 0 or >= f + 1.")
        if (
            self.distribution_scheme == DistributionScheme.COLOCATED
            and self.num_proxy_replicas != 0
            and self.num_proxy_replicas != self.num_replicas
        ):
            raise ValueError("Colocated: numProxyReplicas must equal numReplicas.")
