"""MultiPaxos message schemas (the analog of
``multipaxos/MultiPaxos.proto``). The wire codec dispatches on message
class, so the per-role ``<Role>Inbound`` oneof wrappers of the reference
are unnecessary; ``receive`` dispatches on isinstance."""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from frankenpaxos_tpu.core import wire


@wire.message
@dataclasses.dataclass(frozen=True)
class CommandId:
    """Uniquely identifies a command: (client address bytes, pseudonym, id)."""

    client_address: bytes
    client_pseudonym: int
    client_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Command:
    command_id: CommandId
    command: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class CommandBatch:
    commands: tuple  # of Command


@wire.message
@dataclasses.dataclass(frozen=True)
class CommandBatchOrNoop:
    """batch=None means noop (the analog of the CommandBatchOrNoop oneof)."""

    batch: Optional[CommandBatch]

    @staticmethod
    def noop() -> "CommandBatchOrNoop":
        return CommandBatchOrNoop(None)

    @property
    def is_noop(self) -> bool:
        return self.batch is None


# -- Write path --------------------------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class ClientRequest:
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class ClientRequestBatch:
    batch: CommandBatch


@wire.message
@dataclasses.dataclass(frozen=True)
class Phase1a:
    round: int
    chosen_watermark: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Phase1bSlotInfo:
    slot: int
    vote_round: int
    vote_value: CommandBatchOrNoop


@wire.message
@dataclasses.dataclass(frozen=True)
class Phase1b:
    group_index: int
    acceptor_index: int
    round: int
    info: tuple  # of Phase1bSlotInfo


@wire.message
@dataclasses.dataclass(frozen=True)
class Phase2a:
    slot: int
    round: int
    value: CommandBatchOrNoop


@wire.message
@dataclasses.dataclass(frozen=True)
class Phase2b:
    group_index: int
    acceptor_index: int
    slot: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Chosen:
    slot: int
    value: CommandBatchOrNoop


@wire.message
@dataclasses.dataclass(frozen=True)
class Nack:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ChosenWatermark:
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class Recover:
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ClientReply:
    command_id: CommandId
    slot: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class ClientReplyBatch:
    batch: tuple  # of ClientReply


# -- Leader info / redirection -----------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class NotLeaderClient:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestClient:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyClient:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class NotLeaderBatcher:
    client_request_batch: ClientRequestBatch


@wire.message
@dataclasses.dataclass(frozen=True)
class LeaderInfoRequestBatcher:
    pass


@wire.message
@dataclasses.dataclass(frozen=True)
class LeaderInfoReplyBatcher:
    round: int


# -- Read path ---------------------------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class MaxSlotRequest:
    command_id: CommandId


@wire.message
@dataclasses.dataclass(frozen=True)
class MaxSlotReply:
    command_id: CommandId
    group_index: int
    acceptor_index: int
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BatchMaxSlotRequest:
    read_batcher_index: int
    read_batcher_id: int


@wire.message
@dataclasses.dataclass(frozen=True)
class BatchMaxSlotReply:
    read_batcher_index: int
    read_batcher_id: int
    acceptor_index: int
    slot: int


@wire.message
@dataclasses.dataclass(frozen=True)
class ReadRequest:
    slot: int
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class SequentialReadRequest:
    slot: int
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class EventualReadRequest:
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class ReadRequestBatch:
    slot: int
    commands: tuple  # of Command


@wire.message
@dataclasses.dataclass(frozen=True)
class SequentialReadRequestBatch:
    slot: int
    commands: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class EventualReadRequestBatch:
    commands: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class ReadReply:
    command_id: CommandId
    slot: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class ReadReplyBatch:
    batch: tuple  # of ReadReply
