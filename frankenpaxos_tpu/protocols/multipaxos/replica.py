"""MultiPaxos Replica (reference ``multipaxos/Replica.scala``).

Stores chosen entries in a watermark-GC'd BufferMap log
(Replica.scala:168-170); ``execute_log`` executes entries in slot order
from the executed watermark (the hot loop, Replica.scala:394-453), dedupes
via a largest-id client table (Replica.scala:305-344), drains deferred
reads at each slot, and periodically broadcasts ChosenWatermark. A
randomized recover timer fires when the log has a hole and asks leaders to
re-run phase 1 (Replica.scala:239-260). Read handling implements
linearizable (deferrable, Replica.scala:455-529), sequential, and eventual
reads.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import (
    Config,
    DistributionScheme,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Chosen,
    ChosenWatermark,
    ClientReply,
    ClientReplyBatch,
    Command,
    CommandBatchOrNoop,
    EventualReadRequest,
    EventualReadRequestBatch,
    ReadReply,
    ReadReplyBatch,
    ReadRequest,
    ReadRequestBatch,
    Recover,
    SequentialReadRequest,
    SequentialReadRequestBatch,
)
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.util import BufferMap, random_duration


@dataclasses.dataclass(frozen=True)
class ReplicaOptions:
    log_grow_size: int = 5000
    unsafe_dont_use_client_table: bool = False
    send_chosen_watermark_every_n_entries: int = 1000
    recover_log_entry_min_period: float = 5.0
    recover_log_entry_max_period: float = 10.0
    unsafe_dont_recover: bool = False
    measure_latencies: bool = True


class Replica(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        state_machine: StateMachine,
        config: Config,
        options: ReplicaOptions = ReplicaOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.replica_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.requests_total = collectors.counter(
            "multipaxos_replica_requests_total", "requests", labels=("type",)
        )
        self.executed_commands_total = collectors.counter(
            "multipaxos_replica_executed_commands_total", "executed commands"
        )
        self.index = config.replica_addresses.index(address)
        self.log: BufferMap[CommandBatchOrNoop] = BufferMap(options.log_grow_size)
        self.deferred_reads: BufferMap[List[Command]] = BufferMap(
            options.log_grow_size
        )
        self.executed_watermark = 0
        self.num_chosen = 0
        # (client address bytes, pseudonym) -> (largest executed id, output).
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}
        self.recover_timer = (
            None
            if options.unsafe_dont_recover
            else self.timer(
                "recover",
                random_duration(
                    self.rng,
                    options.recover_log_entry_min_period,
                    options.recover_log_entry_max_period,
                ),
                self._recover,
            )
        )

    # -- Helpers -------------------------------------------------------------

    def _recover(self) -> None:
        recover = Recover(slot=self.executed_watermark)
        proxy = self._proxy_replica()
        if proxy is not None:
            self.chan(proxy).send(recover)
        else:
            for leader in self.config.leader_addresses:
                self.chan(leader).send(recover)

    def _proxy_replica(self) -> Optional[Address]:
        if self.config.num_proxy_replicas == 0:
            return None
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_replica_addresses[
                self.rng.randrange(self.config.num_proxy_replicas)
            ]
        return self.config.proxy_replica_addresses[self.index]

    def _client_addr(self, command_id) -> Address:
        return self.transport.address_from_bytes(command_id.client_address)

    def _execute_command(
        self, slot: int, command: Command, client_replies: List[ClientReply]
    ) -> None:
        cid = command.command_id
        key = (cid.client_address, cid.client_pseudonym)
        cached = self.client_table.get(key)
        if cached is not None and cid.client_id < cached[0]:
            return  # redundantly chosen; already executed
        if cached is not None and cid.client_id == cached[0]:
            client_replies.append(
                ClientReply(command_id=cid, slot=slot, result=cached[1])
            )
            return
        result = self.state_machine.run(command.command)
        if not self.options.unsafe_dont_use_client_table:
            self.client_table[key] = (cid.client_id, result)
        # Replies are striped over replicas so only one replica replies per
        # slot (Replica.scala:323-327).
        if slot % self.config.num_replicas == self.index:
            client_replies.append(
                ClientReply(command_id=cid, slot=slot, result=result)
            )
        self.executed_commands_total.inc()

    def _execute_log(self) -> List[ClientReply]:
        client_replies: List[ClientReply] = []
        while True:
            value = self.log.get(self.executed_watermark)
            if value is None:
                return client_replies
            slot = self.executed_watermark
            if not value.is_noop:
                for command in value.batch.commands:
                    self._execute_command(slot, command, client_replies)
            reads = self.deferred_reads.get(slot)
            if reads is not None:
                self._process_deferred_reads(reads)
            self.executed_watermark += 1
            n = self.options.send_chosen_watermark_every_n_entries
            mod, div = self.executed_watermark % n, self.executed_watermark // n
            if mod == 0 and div % self.config.num_replicas == self.index:
                watermark = ChosenWatermark(slot=self.executed_watermark)
                proxy = self._proxy_replica()
                if proxy is not None:
                    self.chan(proxy).send(watermark)
                else:
                    for leader in self.config.leader_addresses:
                        self.chan(leader).send(watermark)

    def _execute_read(self, command: Command) -> ReadReply:
        result = self.state_machine.run(command.command)
        return ReadReply(
            command_id=command.command_id,
            slot=self.executed_watermark - 1,
            result=result,
        )

    def _process_deferred_reads(self, reads: List[Command]) -> None:
        proxy = self._proxy_replica()
        if len(reads) == 1 or proxy is None:
            for command in reads:
                self.chan(self._client_addr(command.command_id)).send(
                    self._execute_read(command)
                )
        else:
            self.chan(proxy).send(
                ReadReplyBatch(tuple(self._execute_read(c) for c in reads))
            )

    def _handle_deferrable_read(
        self, src: Address, slot: int, command: Command
    ) -> None:
        if slot >= self.executed_watermark:
            reads = self.deferred_reads.get(slot)
            if reads is None:
                self.deferred_reads.put(slot, [command])
            else:
                reads.append(command)
            return
        self.chan(src).send(self._execute_read(command))

    def _handle_deferrable_reads(self, slot: int, commands) -> None:
        if slot >= self.executed_watermark:
            reads = self.deferred_reads.get(slot)
            if reads is None:
                self.deferred_reads.put(slot, list(commands))
            else:
                reads.extend(commands)
            return
        proxy = self._proxy_replica()
        if proxy is not None:
            self.chan(proxy).send(
                ReadReplyBatch(tuple(self._execute_read(c) for c in commands))
            )
        else:
            for command in commands:
                self.chan(self._client_addr(command.command_id)).send(
                    self._execute_read(command)
                )

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        self.requests_total.labels(type(msg).__name__).inc()
        if isinstance(msg, Chosen):
            self._handle_chosen(msg)
        elif isinstance(msg, ReadRequest):
            self._handle_deferrable_read(src, msg.slot, msg.command)
        elif isinstance(msg, SequentialReadRequest):
            self._handle_deferrable_read(src, msg.slot, msg.command)
        elif isinstance(msg, EventualReadRequest):
            self.chan(src).send(self._execute_read(msg.command))
        elif isinstance(msg, ReadRequestBatch):
            self._handle_deferrable_reads(msg.slot, msg.commands)
        elif isinstance(msg, SequentialReadRequestBatch):
            self._handle_deferrable_reads(msg.slot, msg.commands)
        elif isinstance(msg, EventualReadRequestBatch):
            replies = tuple(self._execute_read(c) for c in msg.commands)
            proxy = self._proxy_replica()
            if proxy is not None:
                self.chan(proxy).send(ReadReplyBatch(replies))
            else:
                for reply in replies:
                    self.chan(self._client_addr(reply.command_id)).send(reply)
        else:
            self.logger.fatal(f"unknown replica message {msg!r}")

    def _handle_chosen(self, chosen: Chosen) -> None:
        was_recovering = self.num_chosen != self.executed_watermark
        old_watermark = self.executed_watermark
        if self.log.get(chosen.slot) is not None:
            return  # redundantly chosen
        self.log.put(chosen.slot, chosen.value)
        self.num_chosen += 1
        client_replies = self._execute_log()
        if client_replies:
            proxy = self._proxy_replica()
            if proxy is not None:
                self.chan(proxy).send(ClientReplyBatch(tuple(client_replies)))
            else:
                for reply in client_replies:
                    self.chan(self._client_addr(reply.command_id)).send(reply)
        # Recover timer bookkeeping (Replica.scala:514-527): run it exactly
        # when there is a hole (some chosen entry is not yet executable).
        if self.recover_timer is None:
            return
        should_run = self.num_chosen != self.executed_watermark
        advanced = old_watermark != self.executed_watermark
        if was_recovering:
            if should_run and advanced:
                self.recover_timer.reset()
            elif not should_run:
                self.recover_timer.stop()
        elif should_run:
            self.recover_timer.start()
