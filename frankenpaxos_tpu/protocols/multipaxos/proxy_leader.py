"""MultiPaxos ProxyLeader (reference ``multipaxos/ProxyLeader.scala:175-258``).

Relieves the leader of phase-2 broadcast/collect: forwards each Phase2a to
a write quorum (f+1 random members of the slot's acceptor group, or a grid
write quorum in flexible mode), counts Phase2bs, and broadcasts Chosen to
all replicas on quorum.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import Config
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    Chosen,
    Phase2a,
    Phase2b,
)
from frankenpaxos_tpu.quorums import Grid


@dataclasses.dataclass(frozen=True)
class ProxyLeaderOptions:
    flush_phase2as_every_n: int = 1
    measure_latencies: bool = True


_DONE = "done"


@dataclasses.dataclass
class _Pending:
    phase2a: Phase2a
    phase2bs: Dict[Tuple[int, int], Phase2b]


class ProxyLeader(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: ProxyLeaderOptions = ProxyLeaderOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.requests_total = collectors.counter(
            "multipaxos_proxy_leader_requests_total", "requests", labels=("type",)
        )
        self.grid = Grid(
            [
                [(row, col) for col in range(len(config.acceptor_addresses[row]))]
                for row in range(config.num_acceptor_groups)
            ],
            seed=seed,
        )
        # (slot, round) -> _Pending | _DONE
        self.states: Dict[Tuple[int, int], object] = {}
        self._unflushed_phase2as = 0

    def _acceptor(self, group: int, index: int) -> Address:
        return self.config.acceptor_addresses[group][index]

    def receive(self, src: Address, msg) -> None:
        self.requests_total.labels(type(msg).__name__).inc()
        if isinstance(msg, Phase2a):
            self._handle_phase2a(src, msg)
        elif isinstance(msg, Phase2b):
            self._handle_phase2b(src, msg)
        else:
            self.logger.fatal(f"unknown proxy leader message {msg!r}")

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        key = (phase2a.slot, phase2a.round)
        if key in self.states:
            return  # duplicate Phase2a
        if not self.config.flexible:
            group_index = phase2a.slot % self.config.num_acceptor_groups
            group = self.config.acceptor_addresses[group_index]
            quorum = self.rng.sample(range(len(group)), self.config.f + 1)
            targets = [group[i] for i in quorum]
        else:
            targets = [
                self._acceptor(row, col)
                for (row, col) in self.grid.random_write_quorum()
            ]
        if self.options.flush_phase2as_every_n == 1:
            for t in targets:
                self.chan(t).send(phase2a)
        else:
            for t in targets:
                self.chan(t).send_no_flush(phase2a)
            self._unflushed_phase2as += 1
            if self._unflushed_phase2as >= self.options.flush_phase2as_every_n:
                for group in self.config.acceptor_addresses:
                    for a in group:
                        self.flush(a)
                self._unflushed_phase2as = 0
        self.states[key] = _Pending(phase2a=phase2a, phase2bs={})

    def _handle_phase2b(self, src: Address, phase2b: Phase2b) -> None:
        key = (phase2b.slot, phase2b.round)
        state = self.states.get(key)
        if state is None:
            self.logger.fatal(
                f"ProxyLeader got Phase2b for {key} without sending a Phase2a"
            )
        if state == _DONE:
            return
        state.phase2bs[(phase2b.group_index, phase2b.acceptor_index)] = phase2b
        if not self.config.flexible and len(state.phase2bs) < self.config.f + 1:
            return
        if self.config.flexible and not self.grid.is_write_quorum(
            set(state.phase2bs.keys())
        ):
            return
        chosen = Chosen(slot=phase2b.slot, value=state.phase2a.value)
        for replica in self.config.replica_addresses:
            self.chan(replica).send(chosen)
        self.states[key] = _DONE
