"""Compartmentalized MultiPaxos — the flagship protocol (reference
``multipaxos/``, ~4,900 LoC Scala; see SURVEY.md §3.2-3.4 for the call
stacks this package reproduces).

Roles: Client, Batcher, ReadBatcher, Leader (+ co-located election
Participant), ProxyLeader, Acceptor (round-robin groups or one flexible
grid), Replica, ProxyReplica. Regular MultiPaxos is the Colocated
distribution scheme of the decoupled protocol
(``DistributionScheme.scala``). Reads are linearizable (quorum max-slot
reads), sequential, or eventual ("Evelyn Paxos").
"""

from frankenpaxos_tpu.protocols.multipaxos.config import (
    Config,
    DistributionScheme,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import *  # noqa: F401,F403
from frankenpaxos_tpu.protocols.multipaxos.acceptor import Acceptor, AcceptorOptions
from frankenpaxos_tpu.protocols.multipaxos.batcher import Batcher, BatcherOptions
from frankenpaxos_tpu.protocols.multipaxos.client import Client, ClientOptions
from frankenpaxos_tpu.protocols.multipaxos.leader import Leader, LeaderOptions
from frankenpaxos_tpu.protocols.multipaxos.proxy_leader import (
    ProxyLeader,
    ProxyLeaderOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.proxy_replica import (
    ProxyReplica,
    ProxyReplicaOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.read_batcher import (
    ReadBatcher,
    ReadBatcherOptions,
)
from frankenpaxos_tpu.protocols.multipaxos.replica import Replica, ReplicaOptions
