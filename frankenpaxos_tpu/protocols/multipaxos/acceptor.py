"""MultiPaxos Acceptor (reference ``multipaxos/Acceptor.scala:122-237``).

One round per acceptor (not per slot); votes stored per slot in a sorted
map; Phase1b returns votes at or above the leader's chosen watermark;
MaxSlot requests serve linearizable reads with the largest voted slot.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import Config
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    BatchMaxSlotReply,
    BatchMaxSlotRequest,
    CommandBatchOrNoop,
    MaxSlotReply,
    MaxSlotRequest,
    Nack,
    Phase1a,
    Phase1b,
    Phase1bSlotInfo,
    Phase2a,
    Phase2b,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@dataclasses.dataclass(frozen=True)
class AcceptorOptions:
    measure_latencies: bool = True


@dataclasses.dataclass
class _SlotState:
    vote_round: int
    vote_value: CommandBatchOrNoop


class Acceptor(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: AcceptorOptions = AcceptorOptions(),
        collectors: Optional[Collectors] = None,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        collectors = collectors or FakeCollectors()
        self.requests_total = collectors.counter(
            "multipaxos_acceptor_requests_total", "requests", labels=("type",)
        )
        self.group_index = next(
            i for i, g in enumerate(config.acceptor_addresses) if address in g
        )
        self.index = config.acceptor_addresses[self.group_index].index(address)
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = -1
        # slot -> _SlotState (the analog of the mutable.SortedMap; BufferMap
        # semantics are unnecessary here because Phase1b iterates from the
        # chosen watermark).
        self.states: Dict[int, _SlotState] = {}
        self.max_voted_slot = -1

    def receive(self, src: Address, msg) -> None:
        self.requests_total.labels(type(msg).__name__).inc()
        if isinstance(msg, Phase1a):
            self._handle_phase1a(src, msg)
        elif isinstance(msg, Phase2a):
            self._handle_phase2a(src, msg)
        elif isinstance(msg, MaxSlotRequest):
            self._handle_max_slot_request(src, msg)
        elif isinstance(msg, BatchMaxSlotRequest):
            self._handle_batch_max_slot_request(src, msg)
        else:
            self.logger.fatal(f"unknown acceptor message {msg!r}")

    def _handle_phase1a(self, src: Address, phase1a: Phase1a) -> None:
        if phase1a.round < self.round:
            self.chan(src).send(Nack(round=self.round))
            return
        self.round = phase1a.round
        info = tuple(
            Phase1bSlotInfo(slot=slot, vote_round=s.vote_round, vote_value=s.vote_value)
            for slot, s in sorted(self.states.items())
            if slot >= phase1a.chosen_watermark
        )
        self.chan(src).send(
            Phase1b(
                group_index=self.group_index,
                acceptor_index=self.index,
                round=self.round,
                info=info,
            )
        )

    def _handle_phase2a(self, src: Address, phase2a: Phase2a) -> None:
        if phase2a.round < self.round:
            # Nack goes to the round's leader, not the proxy leader
            # (Acceptor.scala:184-199).
            leader = self.config.leader_addresses[
                self.round_system.leader(phase2a.round)
            ]
            self.chan(leader).send(Nack(round=self.round))
            return
        self.round = phase2a.round
        self.states[phase2a.slot] = _SlotState(
            vote_round=self.round, vote_value=phase2a.value
        )
        self.max_voted_slot = max(self.max_voted_slot, phase2a.slot)
        self.chan(src).send(
            Phase2b(
                group_index=self.group_index,
                acceptor_index=self.index,
                slot=phase2a.slot,
                round=self.round,
            )
        )

    def _handle_max_slot_request(self, src: Address, req: MaxSlotRequest) -> None:
        self.chan(src).send(
            MaxSlotReply(
                command_id=req.command_id,
                group_index=self.group_index,
                acceptor_index=self.index,
                slot=self.max_voted_slot,
            )
        )

    def _handle_batch_max_slot_request(
        self, src: Address, req: BatchMaxSlotRequest
    ) -> None:
        self.chan(src).send(
            BatchMaxSlotReply(
                read_batcher_index=req.read_batcher_index,
                read_batcher_id=req.read_batcher_id,
                acceptor_index=self.index,
                slot=self.max_voted_slot,
            )
        )
