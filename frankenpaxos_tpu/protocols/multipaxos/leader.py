"""MultiPaxos Leader (reference ``multipaxos/Leader.scala``).

State machine: Inactive | Phase1 | Phase2 (Leader.scala:107-127). Phase 1
reads f+1 of every acceptor group (or a grid read quorum) from the chosen
watermark up, repairs the log with safe values (max vote round, else noop;
Leader.scala:314-329, 504-577), then streams Phase2as round-robin over
proxy leaders (Leader.scala:331-407). Leader election is a co-located
``election.basic.Participant`` whose callback drives ``leader_change``
(Leader.scala:192-203, 432-459). Nacks fast-forward the round
(Leader.scala:672-697); Recover re-runs phase 1 (Leader.scala:706-722).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.election import basic as election
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import (
    Config,
    DistributionScheme,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ChosenWatermark,
    ClientRequest,
    ClientRequestBatch,
    CommandBatch,
    CommandBatchOrNoop,
    LeaderInfoReplyBatcher,
    LeaderInfoReplyClient,
    LeaderInfoRequestBatcher,
    LeaderInfoRequestClient,
    Nack,
    NotLeaderBatcher,
    NotLeaderClient,
    Phase1a,
    Phase1b,
    Phase2a,
    Recover,
)
from frankenpaxos_tpu.quorums import Grid
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@dataclasses.dataclass(frozen=True)
class LeaderOptions:
    resend_phase1as_period: float = 5.0
    flush_phase2as_every_n: int = 1
    noop_flush_period: float = 0.0  # 0 disables
    election_options: election.ElectionOptions = election.ElectionOptions()
    measure_latencies: bool = True


_INACTIVE = "inactive"


@dataclasses.dataclass
class _Phase1:
    # One vote map per acceptor group: acceptor index -> Phase1b.
    phase1bs: List[Dict[int, Phase1b]]
    phase1b_acceptors: set
    pending_client_request_batches: List[ClientRequestBatch]
    resend_phase1as: object


@dataclasses.dataclass
class _Phase2:
    noop_flush: Optional[object]


class Leader(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: LeaderOptions = LeaderOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.requests_total = collectors.counter(
            "multipaxos_leader_requests_total", "requests", labels=("type",)
        )
        self.leader_changes_total = collectors.counter(
            "multipaxos_leader_leader_changes_total", "leader changes"
        )
        self.index = config.leader_addresses.index(address)
        self.grid = Grid(
            [
                [(row, col) for col in range(len(config.acceptor_addresses[row]))]
                for row in range(config.num_acceptor_groups)
            ],
            seed=seed,
        )
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = self.round_system.next_classic_round(0, -1)
        self.next_slot = 0
        self.chosen_watermark = 0
        self._current_proxy_leader = 0
        self._unflushed_phase2as = 0
        # Co-located election participant (Leader.scala:160-203).
        self.election = election.Participant(
            config.leader_election_addresses[self.index],
            transport,
            logger,
            config.leader_election_addresses,
            initial_leader_index=0,
            options=options.election_options,
            seed=seed,
        )
        self.election.register(
            lambda leader_index: self.leader_change(leader_index == self.index)
        )
        self.state = (
            self._start_phase1(self.round, self.chosen_watermark)
            if self.index == 0
            else _INACTIVE
        )

    # -- Helpers -------------------------------------------------------------

    def _all_acceptors(self):
        for group in self.config.acceptor_addresses:
            yield from group

    def _make_resend_phase1as_timer(self, phase1a: Phase1a):
        def fire() -> None:
            for acceptor in self._all_acceptors():
                self.chan(acceptor).send(phase1a)
            timer.start()

        timer = self.timer(
            "resendPhase1as", self.options.resend_phase1as_period, fire
        )
        timer.start()
        return timer

    def _make_noop_flush_timer(self):
        if self.config.flexible or self.options.noop_flush_period == 0.0:
            return None

        def fire() -> None:
            if not isinstance(self.state, _Phase2):
                self.logger.fatal("noop flush fired outside Phase2")
            self.chan(self._proxy_leader()).send(
                Phase2a(
                    slot=self.next_slot,
                    round=self.round,
                    value=CommandBatchOrNoop.noop(),
                )
            )
            self.next_slot += 1
            self._bump_proxy_leader()
            timer.start()

        timer = self.timer("noopFlush", self.options.noop_flush_period, fire)
        timer.start()
        return timer

    def _proxy_leader(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.proxy_leader_addresses[self._current_proxy_leader]
        return self.config.proxy_leader_addresses[self.index]

    def _bump_proxy_leader(self) -> None:
        self._current_proxy_leader += 1
        if self._current_proxy_leader >= self.config.num_proxy_leaders:
            self._current_proxy_leader = 0

    @staticmethod
    def _max_phase1b_slot(phase1b: Phase1b) -> int:
        return max((info.slot for info in phase1b.info), default=-1)

    @staticmethod
    def _safe_value(phase1bs, slot: int) -> CommandBatchOrNoop:
        """Max-vote-round value at this slot, else noop (Leader.scala:314-329)."""
        infos = [
            info
            for phase1b in phase1bs
            for info in phase1b.info
            if info.slot == slot
        ]
        if not infos:
            return CommandBatchOrNoop.noop()
        return max(infos, key=lambda i: i.vote_round).vote_value

    def _process_client_request_batch(self, batch: ClientRequestBatch) -> None:
        if not isinstance(self.state, _Phase2):
            self.logger.fatal(
                "tried to process a client request batch outside Phase 2"
            )
        proxy_leader = self._proxy_leader()
        phase2a = Phase2a(
            slot=self.next_slot,
            round=self.round,
            value=CommandBatchOrNoop(batch.batch),
        )
        if self.options.flush_phase2as_every_n == 1:
            self.chan(proxy_leader).send(phase2a)
            self._bump_proxy_leader()
        else:
            self.chan(proxy_leader).send_no_flush(phase2a)
            self._unflushed_phase2as += 1
            if self._unflushed_phase2as >= self.options.flush_phase2as_every_n:
                self.flush(proxy_leader)
                self._unflushed_phase2as = 0
                self._bump_proxy_leader()
        self.next_slot += 1

    def _start_phase1(self, round: int, chosen_watermark: int) -> _Phase1:
        phase1a = Phase1a(round=round, chosen_watermark=chosen_watermark)
        if not self.config.flexible:
            for group in self.config.acceptor_addresses:
                quorum = self.rng.sample(range(len(group)), self.config.f + 1)
                for i in quorum:
                    self.chan(group[i]).send(phase1a)
        else:
            for (row, col) in self.grid.random_read_quorum():
                self.chan(self.config.acceptor_addresses[row][col]).send(phase1a)
        return _Phase1(
            phase1bs=[{} for _ in range(self.config.num_acceptor_groups)],
            phase1b_acceptors=set(),
            pending_client_request_batches=[],
            resend_phase1as=self._make_resend_phase1as_timer(phase1a),
        )

    def leader_change(self, is_new_leader: bool) -> None:
        self.leader_changes_total.inc()
        if isinstance(self.state, _Phase1):
            self.state.resend_phase1as.stop()
        elif isinstance(self.state, _Phase2) and self.state.noop_flush is not None:
            self.state.noop_flush.stop()
        if not is_new_leader:
            self.state = _INACTIVE
        else:
            self.round = self.round_system.next_classic_round(self.index, self.round)
            self.state = self._start_phase1(self.round, self.chosen_watermark)

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        self.requests_total.labels(type(msg).__name__).inc()
        if isinstance(msg, Phase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, ClientRequest):
            self._handle_client_request(src, msg)
        elif isinstance(msg, ClientRequestBatch):
            self._handle_client_request_batch(src, msg)
        elif isinstance(msg, LeaderInfoRequestClient):
            if self.state != _INACTIVE:
                self.chan(src).send(LeaderInfoReplyClient(round=self.round))
        elif isinstance(msg, LeaderInfoRequestBatcher):
            if self.state != _INACTIVE:
                self.chan(src).send(LeaderInfoReplyBatcher(round=self.round))
        elif isinstance(msg, Nack):
            self._handle_nack(msg)
        elif isinstance(msg, ChosenWatermark):
            self.chosen_watermark = max(self.chosen_watermark, msg.slot)
        elif isinstance(msg, Recover):
            if self.state != _INACTIVE:
                self.leader_change(is_new_leader=True)
        else:
            self.logger.fatal(f"unknown leader message {msg!r}")

    def _handle_phase1b(self, phase1b: Phase1b) -> None:
        if not isinstance(self.state, _Phase1):
            return
        if phase1b.round != self.round:
            self.logger.check_lt(phase1b.round, self.round)
            return
        phase1 = self.state
        phase1.phase1bs[phase1b.group_index][phase1b.acceptor_index] = phase1b
        if not self.config.flexible and any(
            len(group) < self.config.f + 1 for group in phase1.phase1bs
        ):
            return
        if self.config.flexible:
            phase1.phase1b_acceptors.add(
                (phase1b.group_index, phase1b.acceptor_index)
            )
            if not self.grid.is_read_quorum(set(phase1.phase1b_acceptors)):
                return

        max_slot = max(
            (
                self._max_phase1b_slot(b)
                for group in phase1.phase1bs
                for b in group.values()
            ),
            default=-1,
        )
        # Log repair: re-propose safe values for every unchosen slot
        # (Leader.scala:541-575). In flexible mode every phase1b vote is
        # usable for any slot (a superset of a read quorum is still a read
        # quorum), so flatten once outside the loop.
        flexible_votes = (
            [b for g in phase1.phase1bs for b in g.values()]
            if self.config.flexible
            else None
        )
        for slot in range(self.chosen_watermark, max_slot + 1):
            if flexible_votes is not None:
                votes = flexible_votes
            else:
                votes = list(
                    phase1.phase1bs[slot % self.config.num_acceptor_groups].values()
                )
            self.chan(self._proxy_leader()).send(
                Phase2a(
                    slot=slot,
                    round=self.round,
                    value=self._safe_value(votes, slot),
                )
            )
        # Deliberate divergence from Leader.scala:566 (`nextSlot = maxSlot+1`):
        # when acceptors report no votes above the chosen watermark, maxSlot+1
        # would regress next_slot below chosen_watermark and a new leader
        # would propose fresh values in already-chosen slots.
        self.next_slot = max(self.chosen_watermark, max_slot + 1)
        phase1.resend_phase1as.stop()
        self.state = _Phase2(self._make_noop_flush_timer())
        for batch in phase1.pending_client_request_batches:
            self._process_client_request_batch(batch)

    def _handle_client_request(self, src: Address, msg: ClientRequest) -> None:
        if self.state == _INACTIVE:
            self.chan(src).send(NotLeaderClient())
        elif isinstance(self.state, _Phase1):
            self.state.pending_client_request_batches.append(
                ClientRequestBatch(CommandBatch((msg.command,)))
            )
        else:
            self._process_client_request_batch(
                ClientRequestBatch(CommandBatch((msg.command,)))
            )

    def _handle_client_request_batch(
        self, src: Address, msg: ClientRequestBatch
    ) -> None:
        if self.state == _INACTIVE:
            self.chan(src).send(NotLeaderBatcher(client_request_batch=msg))
        elif isinstance(self.state, _Phase1):
            self.state.pending_client_request_batches.append(msg)
        else:
            self._process_client_request_batch(msg)

    def _handle_nack(self, msg: Nack) -> None:
        if msg.round <= self.round:
            return
        if self.state == _INACTIVE:
            self.round = msg.round
        else:
            # Fast-forward to the nacked round, then let leader_change
            # apply one next_classic_round bump. Deliberate deviation: the
            # reference advances TWICE (Leader.scala:676-697 handleNack
            # computes nextClassicRound from nack.round AND leaderChange
            # bumps again from that), landing one classic round higher.
            # One bump already guarantees round > nack.round and
            # self-ownership; the second only burns round space faster.
            self.round = msg.round
            self.leader_change(is_new_leader=True)
