"""MultiPaxos Client (reference ``multipaxos/Client.scala``).

A client multiplexes virtual clients ("pseudonyms"), each with at most one
outstanding request. Writes go to the round's leader (or a batcher) with a
resend timer (Client.scala:1035-1051); linearizable reads first collect
MaxSlotReplies from f+1 acceptors of a random group (or a grid read
quorum), compute the read slot, then send a ReadRequest to a random
replica (Client.scala:851-933; the "Evelyn Paxos" quorum read); sequential
reads reuse the largest seen slot; eventual reads go straight to a
replica. NotLeaderClient triggers leader-info polling.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, Optional, Tuple

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import (
    Config,
    DistributionScheme,
)
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ClientReply,
    ClientRequest,
    Command,
    CommandId,
    EventualReadRequest,
    LeaderInfoReplyClient,
    LeaderInfoRequestClient,
    MaxSlotReply,
    MaxSlotRequest,
    NotLeaderClient,
    ReadReply,
    ReadRequest,
    SequentialReadRequest,
)
from frankenpaxos_tpu.quorums import Grid
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@dataclasses.dataclass(frozen=True)
class ClientOptions:
    resend_client_request_period: float = 10.0
    resend_max_slot_requests_period: float = 10.0
    resend_read_request_period: float = 10.0
    resend_sequential_read_request_period: float = 10.0
    resend_eventual_read_request_period: float = 10.0
    unsafe_read_at_first_slot: bool = False
    unsafe_read_at_i: bool = False
    flush_writes_every_n: int = 1
    flush_reads_every_n: int = 1
    measure_latencies: bool = True


@dataclasses.dataclass
class _PendingWrite:
    id: int
    command: bytes
    result: Promise
    resend: object


@dataclasses.dataclass
class _MaxSlot:
    id: int
    command: bytes
    result: Promise
    max_slot_replies: Dict[Tuple[int, int], MaxSlotReply]
    resend: object


@dataclasses.dataclass
class _PendingRead:
    id: int
    command: bytes
    result: Promise
    resend: object


@dataclasses.dataclass
class _PendingSequentialRead:
    id: int
    command: bytes
    result: Promise
    resend: object


@dataclasses.dataclass
class _PendingEventualRead:
    id: int
    command: bytes
    result: Promise
    resend: object


class Client(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: ClientOptions = ClientOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.requests_sent_total = collectors.counter(
            "multipaxos_client_client_requests_sent_total", "requests sent"
        )
        self.replies_received_total = collectors.counter(
            "multipaxos_client_replies_received_total", "replies received"
        )
        self.address_bytes = transport.address_to_bytes(address)
        self.grid = Grid(
            [
                [(row, col) for col in range(len(config.acceptor_addresses[row]))]
                for row in range(config.num_acceptor_groups)
            ],
            seed=seed,
        )
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = 0
        self.ids: Dict[int, int] = {}
        self.largest_seen_slots: Dict[int, int] = {}
        self.states: Dict[int, object] = {}

    # -- Send helpers --------------------------------------------------------

    def _leader(self) -> Address:
        return self.config.leader_addresses[self.round_system.leader(self.round)]

    def _batcher(self) -> Address:
        if self.config.distribution_scheme == DistributionScheme.HASH:
            return self.config.batcher_addresses[
                self.rng.randrange(self.config.num_batchers)
            ]
        return self.config.batcher_addresses[self.round_system.leader(self.round)]

    def _random_replica(self) -> Address:
        return self.config.replica_addresses[
            self.rng.randrange(self.config.num_replicas)
        ]

    def _random_read_batcher(self) -> Address:
        return self.config.read_batcher_addresses[
            self.rng.randrange(self.config.num_read_batchers)
        ]

    def _send_client_request(self, request: ClientRequest) -> None:
        if self.config.num_batchers == 0:
            self.chan(self._leader()).send(request)
        else:
            self.chan(self._batcher()).send(request)

    def _command(self, pseudonym: int, id: int, command: bytes) -> Command:
        return Command(
            command_id=CommandId(
                client_address=self.address_bytes,
                client_pseudonym=pseudonym,
                client_id=id,
            ),
            command=command,
        )

    def _make_resend_timer(self, name: str, period: float, fire_once):
        def fire() -> None:
            fire_once()
            timer.start()

        timer = self.timer(name, period, fire)
        timer.start()
        return timer

    # -- Public API (Client.scala:1035-1110) ---------------------------------

    def write(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.states:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} has a pending request"))
            return promise
        id = self.ids.get(pseudonym, 0)
        request = ClientRequest(self._command(pseudonym, id, command))
        self._send_client_request(request)
        self.states[pseudonym] = _PendingWrite(
            id=id,
            command=command,
            result=promise,
            resend=self._make_resend_timer(
                f"resendClientRequest[{pseudonym};{id}]",
                self.options.resend_client_request_period,
                lambda: self._send_client_request(request),
            ),
        )
        self.ids[pseudonym] = id + 1
        self.requests_sent_total.inc()
        return promise

    def read(self, pseudonym: int, command: bytes) -> Promise:
        """Linearizable quorum read."""
        promise = Promise()
        if pseudonym in self.states:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} has a pending request"))
            return promise
        id = self.ids.get(pseudonym, 0)
        if self.config.num_read_batchers == 0:
            if not self.config.flexible:
                group_index = self.rng.randrange(self.config.num_acceptor_groups)
                group = self.config.acceptor_addresses[group_index]
                quorum = [
                    group[i]
                    for i in self.rng.sample(range(len(group)), self.config.f + 1)
                ]
                resend_to = list(group)
            else:
                quorum = [
                    self.config.acceptor_addresses[row][col]
                    for (row, col) in self.grid.random_read_quorum()
                ]
                resend_to = [a for g in self.config.acceptor_addresses for a in g]
            request = MaxSlotRequest(
                command_id=CommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=id,
                )
            )
            for acceptor in quorum:
                self.chan(acceptor).send(request)

            def resend() -> None:
                for acceptor in resend_to:
                    self.chan(acceptor).send(request)

            self.states[pseudonym] = _MaxSlot(
                id=id,
                command=command,
                result=promise,
                max_slot_replies={},
                resend=self._make_resend_timer(
                    f"resendMaxSlotRequest[{pseudonym};{id}]",
                    self.options.resend_max_slot_requests_period,
                    resend,
                ),
            )
        else:
            request = ReadRequest(slot=-1, command=self._command(pseudonym, id, command))
            self.chan(self._random_read_batcher()).send(request)
            self.states[pseudonym] = _PendingRead(
                id=id,
                command=command,
                result=promise,
                resend=self._make_resend_timer(
                    f"resendReadRequest[{pseudonym};{id}]",
                    self.options.resend_read_request_period,
                    lambda: self.chan(self._random_read_batcher()).send(request),
                ),
            )
        self.ids[pseudonym] = id + 1
        return promise

    def sequential_read(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.states:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} has a pending request"))
            return promise
        id = self.ids.get(pseudonym, 0)
        request = SequentialReadRequest(
            slot=self.largest_seen_slots.get(pseudonym, -1),
            command=self._command(pseudonym, id, command),
        )
        self._send_sequential_read(request)
        self.states[pseudonym] = _PendingSequentialRead(
            id=id,
            command=command,
            result=promise,
            resend=self._make_resend_timer(
                f"resendSequentialReadRequest[{pseudonym};{id}]",
                self.options.resend_sequential_read_request_period,
                lambda: self._send_sequential_read(request),
            ),
        )
        self.ids[pseudonym] = id + 1
        return promise

    def _send_sequential_read(self, request: SequentialReadRequest) -> None:
        if self.config.num_read_batchers == 0:
            self.chan(self._random_replica()).send(request)
        else:
            self.chan(self._random_read_batcher()).send(request)

    def eventual_read(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.states:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} has a pending request"))
            return promise
        id = self.ids.get(pseudonym, 0)
        request = EventualReadRequest(self._command(pseudonym, id, command))
        self._send_eventual_read(request)
        self.states[pseudonym] = _PendingEventualRead(
            id=id,
            command=command,
            result=promise,
            resend=self._make_resend_timer(
                f"resendEventualReadRequest[{pseudonym};{id}]",
                self.options.resend_eventual_read_request_period,
                lambda: self._send_eventual_read(request),
            ),
        )
        self.ids[pseudonym] = id + 1
        return promise

    def _send_eventual_read(self, request: EventualReadRequest) -> None:
        if self.config.num_read_batchers == 0:
            self.chan(self._random_replica()).send(request)
        else:
            self.chan(self._random_read_batcher()).send(request)

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ClientReply):
            self._handle_client_reply(msg)
        elif isinstance(msg, MaxSlotReply):
            self._handle_max_slot_reply(msg)
        elif isinstance(msg, ReadReply):
            self._handle_read_reply(msg)
        elif isinstance(msg, NotLeaderClient):
            for leader in self.config.leader_addresses:
                self.chan(leader).send(LeaderInfoRequestClient())
        elif isinstance(msg, LeaderInfoReplyClient):
            if msg.round > self.round:
                self.round = msg.round
        else:
            self.logger.fatal(f"unknown client message {msg!r}")

    def _handle_client_reply(self, reply: ClientReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if not isinstance(state, _PendingWrite):
            return
        if reply.command_id.client_id != state.id:
            return
        state.resend.stop()
        self.largest_seen_slots[pseudonym] = max(
            self.largest_seen_slots.get(pseudonym, -1), reply.slot
        )
        del self.states[pseudonym]
        self.replies_received_total.inc()
        state.result.success(reply.result)

    def _handle_max_slot_reply(self, reply: MaxSlotReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if not isinstance(state, _MaxSlot):
            return
        if reply.command_id.client_id != state.id:
            return
        state.max_slot_replies[(reply.group_index, reply.acceptor_index)] = reply
        if (
            not self.config.flexible
            and len(state.max_slot_replies) < self.config.f + 1
        ):
            return
        if self.config.flexible and not self.grid.is_read_quorum(
            set(state.max_slot_replies.keys())
        ):
            return
        # Compute the read slot (Client.scala:912-920): with round-robin
        # groups the global slot bound is max voted slot in ONE group plus
        # numGroups - 1 (other groups may own later slots).
        max_slot = max(r.slot for r in state.max_slot_replies.values())
        if self.options.unsafe_read_at_first_slot:
            slot = 0
        elif self.config.flexible or self.options.unsafe_read_at_i:
            slot = max_slot
        else:
            slot = max_slot + self.config.num_acceptor_groups - 1
        request = ReadRequest(
            slot=slot, command=self._command(pseudonym, state.id, state.command)
        )
        self.chan(self._random_replica()).send(request)
        state.resend.stop()

        def resend() -> None:
            self.chan(self._random_replica()).send(request)

        self.states[pseudonym] = _PendingRead(
            id=state.id,
            command=state.command,
            result=state.result,
            resend=self._make_resend_timer(
                f"resendReadRequest[{pseudonym};{state.id}]",
                self.options.resend_read_request_period,
                resend,
            ),
        )

    def _handle_read_reply(self, reply: ReadReply) -> None:
        pseudonym = reply.command_id.client_pseudonym
        state = self.states.get(pseudonym)
        if isinstance(state, (_PendingRead, _PendingSequentialRead)):
            if reply.command_id.client_id != state.id:
                return
            state.resend.stop()
            self.largest_seen_slots[pseudonym] = max(
                self.largest_seen_slots.get(pseudonym, -1), reply.slot
            )
            del self.states[pseudonym]
            state.result.success(reply.result)
        elif isinstance(state, _PendingEventualRead):
            if reply.command_id.client_id != state.id:
                return
            state.resend.stop()
            del self.states[pseudonym]
            state.result.success(reply.result)
