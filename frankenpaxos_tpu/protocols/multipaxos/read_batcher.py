"""MultiPaxos ReadBatcher (reference ``multipaxos/ReadBatcher.scala``).

Batches linearizable reads: accumulates commands, sends one
BatchMaxSlotRequest to f+1 acceptors of a random group per batch, and on a
quorum of BatchMaxSlotReplies forwards the batch to a random replica at
the computed slot. Sequential/eventual reads batch straight to replicas.
Batching schemes: size (flush at N or on timeout), time (timeout only),
adaptive (a new batch round-trip starts as soon as the previous returns).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Union

import random

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import Config
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    BatchMaxSlotReply,
    BatchMaxSlotRequest,
    Command,
    EventualReadRequest,
    EventualReadRequestBatch,
    ReadRequest,
    ReadRequestBatch,
    SequentialReadRequest,
    SequentialReadRequestBatch,
)


@dataclasses.dataclass(frozen=True)
class SizeScheme:
    batch_size: int = 100
    timeout: float = 1.0


@dataclasses.dataclass(frozen=True)
class TimeScheme:
    timeout: float = 1.0


@dataclasses.dataclass(frozen=True)
class AdaptiveScheme:
    pass


ReadBatchingScheme = Union[SizeScheme, TimeScheme, AdaptiveScheme]


def scheme_from_string(s: str) -> ReadBatchingScheme:
    """Parse 'size,100,1.0' | 'time,1.0' | 'adaptive' (the analog of the
    scopt reader, ReadBatcher.scala:25-49)."""
    parts = [p.strip() for p in s.split(",")]
    if parts[0] == "size":
        return SizeScheme(int(parts[1]), float(parts[2]))
    if parts[0] == "time":
        return TimeScheme(float(parts[1]))
    if parts[0] == "adaptive":
        return AdaptiveScheme()
    raise ValueError(f"{s} must look like 'size,1,1.0', 'time,1.0' or 'adaptive'.")


@dataclasses.dataclass(frozen=True)
class ReadBatcherOptions:
    read_batching_scheme: ReadBatchingScheme = SizeScheme()
    unsafe_read_at_first_slot: bool = False
    unsafe_read_at_i: bool = False
    measure_latencies: bool = True


class ReadBatcher(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: ReadBatcherOptions = ReadBatcherOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.read_batches_sent_total = collectors.counter(
            "multipaxos_read_batcher_read_batches_sent_total", "read batches"
        )
        self.index = config.read_batcher_addresses.index(address)
        self.linearizable_id = 0
        self.linearizable_batch: List[Command] = []
        self.pending_linearizable_batches: Dict[int, List[Command]] = {}
        self.batch_max_slot_replies: Dict[int, Dict[int, BatchMaxSlotReply]] = {}
        self.sequential_slot = -1
        self.sequential_batch: List[Command] = []
        self.eventual_batch: List[Command] = []
        scheme = options.read_batching_scheme
        if isinstance(scheme, (SizeScheme, TimeScheme)):
            self.linearizable_timer = self._make_timer(
                "linearizableTimer", scheme.timeout, self._flush_linearizable
            )
            self.sequential_timer = self._make_timer(
                "sequentialTimer", scheme.timeout, self._flush_sequential
            )
            self.eventual_timer = self._make_timer(
                "eventualTimer", scheme.timeout, self._flush_eventual
            )
        else:  # Adaptive: kick off the max-slot pipeline immediately.
            self.linearizable_timer = None
            self.sequential_timer = None
            self.eventual_timer = None
            self._send_batch_max_slot_request(-1)

    def _make_timer(self, name: str, timeout: float, flush):
        def fire() -> None:
            flush()
            timer.start()

        timer = self.timer(name, timeout, fire)
        timer.start()
        return timer

    def _random_replica(self) -> Address:
        return self.config.replica_addresses[
            self.rng.randrange(self.config.num_replicas)
        ]

    def _send_batch_max_slot_request(self, read_batcher_id: int) -> None:
        if not self.config.flexible:
            group = self.config.acceptor_addresses[
                self.rng.randrange(self.config.num_acceptor_groups)
            ]
            quorum = [
                group[i]
                for i in self.rng.sample(range(len(group)), self.config.f + 1)
            ]
        else:
            # Flexible mode: a grid read quorum is a FULL row; f+1 of a wider
            # row would not intersect write quorums (columns).
            quorum = list(
                self.config.acceptor_addresses[
                    self.rng.randrange(self.config.num_acceptor_groups)
                ]
            )
        request = BatchMaxSlotRequest(
            read_batcher_index=self.index, read_batcher_id=read_batcher_id
        )
        for acceptor in quorum:
            self.chan(acceptor).send(request)
        self.batch_max_slot_replies[read_batcher_id] = {}

    def _flush_linearizable(self) -> None:
        if not self.linearizable_batch:
            return
        self._send_batch_max_slot_request(self.linearizable_id)
        self.pending_linearizable_batches[self.linearizable_id] = (
            self.linearizable_batch
        )
        self.linearizable_id += 1
        self.linearizable_batch = []

    def _flush_sequential(self) -> None:
        if not self.sequential_batch:
            return
        self.chan(self._random_replica()).send(
            SequentialReadRequestBatch(
                slot=self.sequential_slot, commands=tuple(self.sequential_batch)
            )
        )
        self.sequential_slot = -1
        self.sequential_batch = []

    def _flush_eventual(self) -> None:
        if not self.eventual_batch:
            return
        self.chan(self._random_replica()).send(
            EventualReadRequestBatch(commands=tuple(self.eventual_batch))
        )
        self.eventual_batch = []

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ReadRequest):
            self._handle_read_request(msg)
        elif isinstance(msg, SequentialReadRequest):
            self._handle_sequential_read_request(msg)
        elif isinstance(msg, EventualReadRequest):
            self._handle_eventual_read_request(msg)
        elif isinstance(msg, BatchMaxSlotReply):
            self._handle_batch_max_slot_reply(msg)
        else:
            self.logger.fatal(f"unknown read batcher message {msg!r}")

    def _handle_read_request(self, msg: ReadRequest) -> None:
        self.linearizable_batch.append(msg.command)
        scheme = self.options.read_batching_scheme
        if isinstance(scheme, SizeScheme):
            if len(self.linearizable_batch) < scheme.batch_size:
                return
            self._flush_linearizable()
            self.linearizable_timer.reset()

    def _handle_sequential_read_request(self, msg: SequentialReadRequest) -> None:
        scheme = self.options.read_batching_scheme
        if isinstance(scheme, AdaptiveScheme):
            self.logger.fatal("adaptive batching incompatible with sequential reads")
        self.sequential_slot = max(self.sequential_slot, msg.slot)
        self.sequential_batch.append(msg.command)
        if isinstance(scheme, SizeScheme):
            if len(self.sequential_batch) < scheme.batch_size:
                return
            self._flush_sequential()
            self.sequential_timer.reset()

    def _handle_eventual_read_request(self, msg: EventualReadRequest) -> None:
        scheme = self.options.read_batching_scheme
        if isinstance(scheme, AdaptiveScheme):
            self.logger.fatal("adaptive batching incompatible with eventual reads")
        self.eventual_batch.append(msg.command)
        if isinstance(scheme, SizeScheme):
            if len(self.eventual_batch) < scheme.batch_size:
                return
            self._flush_eventual()
            self.eventual_timer.reset()

    def _handle_batch_max_slot_reply(self, msg: BatchMaxSlotReply) -> None:
        replies = self.batch_max_slot_replies.get(msg.read_batcher_id)
        if replies is None:
            return  # duplicate
        replies[msg.acceptor_index] = msg
        quorum_size = (
            len(self.config.acceptor_addresses[0])  # full grid row
            if self.config.flexible
            else self.config.f + 1
        )
        if len(replies) < quorum_size:
            return
        max_slot = max(r.slot for r in replies.values())
        if self.options.unsafe_read_at_first_slot:
            slot = 0
        elif self.config.flexible or self.options.unsafe_read_at_i:
            # Grids don't round-robin slots over groups; no slot inflation.
            slot = max_slot
        else:
            slot = max_slot + self.config.num_acceptor_groups - 1
        del self.batch_max_slot_replies[msg.read_batcher_id]

        batch = self.pending_linearizable_batches.pop(msg.read_batcher_id, None)
        if batch is not None:
            self.chan(self._random_replica()).send(
                ReadRequestBatch(slot=slot, commands=tuple(batch))
            )
            self.read_batches_sent_total.inc()

        if isinstance(self.options.read_batching_scheme, AdaptiveScheme):
            self._send_batch_max_slot_request(self.linearizable_id)
            if self.linearizable_batch:
                self.pending_linearizable_batches[self.linearizable_id] = (
                    self.linearizable_batch
                )
            self.linearizable_id += 1
            self.linearizable_batch = []
