"""MultiPaxos Batcher (reference ``multipaxos/Batcher.scala:148-200``):
accumulates client commands into batches of ``batch_size`` and forwards
them to the current round's leader; on NotLeaderBatcher it polls leaders
for the round and resends pending batches to the new leader."""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport
from frankenpaxos_tpu.monitoring import Collectors, FakeCollectors
from frankenpaxos_tpu.protocols.multipaxos.config import Config
from frankenpaxos_tpu.protocols.multipaxos.messages import (
    ClientRequest,
    ClientRequestBatch,
    Command,
    CommandBatch,
    LeaderInfoReplyBatcher,
    LeaderInfoRequestBatcher,
    NotLeaderBatcher,
)
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin


@dataclasses.dataclass(frozen=True)
class BatcherOptions:
    batch_size: int = 100
    measure_latencies: bool = True


class Batcher(Actor):
    def __init__(
        self,
        address: Address,
        transport: Transport,
        logger: Logger,
        config: Config,
        options: BatcherOptions = BatcherOptions(),
        collectors: Optional[Collectors] = None,
        seed: int = 0,
    ):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        collectors = collectors or FakeCollectors()
        self.batches_sent = collectors.counter(
            "multipaxos_batcher_batches_sent", "batches sent"
        )
        self.round_system = ClassicRoundRobin(config.num_leaders)
        self.round = 0
        self.growing_batch: List[Command] = []
        self.pending_resend_batches: List[ClientRequestBatch] = []

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, ClientRequest):
            self._handle_client_request(msg)
        elif isinstance(msg, NotLeaderBatcher):
            self._handle_not_leader(msg)
        elif isinstance(msg, LeaderInfoReplyBatcher):
            self._handle_leader_info(msg)
        else:
            self.logger.fatal(f"unknown batcher message {msg!r}")

    def _handle_client_request(self, msg: ClientRequest) -> None:
        self.growing_batch.append(msg.command)
        if len(self.growing_batch) >= self.options.batch_size:
            leader = self.config.leader_addresses[
                self.round_system.leader(self.round)
            ]
            self.chan(leader).send(
                ClientRequestBatch(CommandBatch(tuple(self.growing_batch)))
            )
            self.growing_batch.clear()
            self.batches_sent.inc()

    def _handle_not_leader(self, msg: NotLeaderBatcher) -> None:
        self.pending_resend_batches.append(msg.client_request_batch)
        for leader in self.config.leader_addresses:
            self.chan(leader).send(LeaderInfoRequestBatcher())

    def _handle_leader_info(self, msg: LeaderInfoReplyBatcher) -> None:
        if msg.round <= self.round:
            return
        old_round, self.round = self.round, msg.round
        if self.round_system.leader(old_round) != self.round_system.leader(msg.round):
            leader = self.config.leader_addresses[
                self.round_system.leader(msg.round)
            ]
            for batch in self.pending_resend_batches:
                self.chan(leader).send(batch)
        self.pending_resend_batches.clear()
