"""CASPaxos — replicated register without a log (reference ``caspaxos/``:
Client, Leader, Acceptor over an int-set register whose change function is
set union).

Leaders cycle Idle → Phase1 → Phase2 → Idle per request batch
(``caspaxos/Leader.scala`` state ADT); acceptors keep (round, voteRound,
voteValue) (``caspaxos/Acceptor.scala``). On a nack the leader backs off
for a randomized period before retrying in a higher round
(WaitingToRecover). Deliberate divergence: we select the phase-1 value
from the HIGHEST vote round (classic CASPaxos safety); the reference's
``minBy(_.voteRound)`` (caspaxos/Leader.scala:318) appears to be a bug and
is only shielded there by the commutative union change function.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional

from frankenpaxos_tpu.core import Actor, Address, Logger, Transport, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.roundsystem import ClassicRoundRobin
from frankenpaxos_tpu.util import random_duration


@wire.message
@dataclasses.dataclass(frozen=True)
class CasClientRequest:
    client_address: bytes
    client_id: int
    int_set: frozenset


@wire.message
@dataclasses.dataclass(frozen=True)
class CasClientReply:
    client_id: int
    value: frozenset


@wire.message
@dataclasses.dataclass(frozen=True)
class CasPhase1a:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class CasPhase1b:
    round: int
    acceptor_index: int
    vote_round: int
    vote_value: Optional[frozenset]


@wire.message
@dataclasses.dataclass(frozen=True)
class CasPhase2a:
    round: int
    value: frozenset


@wire.message
@dataclasses.dataclass(frozen=True)
class CasPhase2b:
    round: int
    acceptor_index: int


@wire.message
@dataclasses.dataclass(frozen=True)
class CasNack:
    higher_round: int


@dataclasses.dataclass(frozen=True)
class CasPaxosConfig:
    f: int
    leader_addresses: tuple
    acceptor_addresses: tuple

    @property
    def quorum_size(self) -> int:
        return self.f + 1

    def check_valid(self) -> None:
        if self.f < 1:
            raise ValueError("f must be >= 1")
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != 2 * self.f + 1:
            raise ValueError("need exactly 2f+1 acceptors")


@dataclasses.dataclass(frozen=True)
class LeaderOptions:
    resend_phase1as_period: float = 5.0
    resend_phase2as_period: float = 5.0
    min_nack_sleep_period: float = 0.5
    max_nack_sleep_period: float = 1.0


@dataclasses.dataclass
class _Idle:
    round: int


@dataclasses.dataclass
class _Phase1:
    client_requests: List[CasClientRequest]
    round: int
    phase1bs: Dict[int, CasPhase1b]
    resend: object


@dataclasses.dataclass
class _Phase2:
    client_requests: List[CasClientRequest]
    round: int
    value: frozenset
    phase2bs: Dict[int, CasPhase2b]
    resend: object


@dataclasses.dataclass
class _WaitingToRecover:
    client_requests: List[CasClientRequest]
    round: int
    recover_timer: object


class CasLeader(Actor):
    def __init__(self, address, transport, logger, config: CasPaxosConfig,
                 options: LeaderOptions = LeaderOptions(), seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round_system = ClassicRoundRobin(len(config.leader_addresses))
        self.state = _Idle(self.round_system.next_classic_round(self.index, -1))

    def _broadcast(self, msg) -> None:
        for a in self.config.acceptor_addresses:
            self.chan(a).send(msg)

    def _make_resend(self, name: str, period: float, msg):
        def fire() -> None:
            self._broadcast(msg)
            timer.start()

        timer = self.timer(name, period, fire)
        timer.start()
        return timer

    def _transition_to_phase1(self, round: int, client_requests) -> None:
        phase1a = CasPhase1a(round=round)
        self._broadcast(phase1a)
        self.state = _Phase1(
            client_requests=list(client_requests),
            round=round,
            phase1bs={},
            resend=self._make_resend(
                "resendPhase1as", self.options.resend_phase1as_period, phase1a
            ),
        )

    def _stop_timers(self) -> None:
        s = self.state
        if isinstance(s, _Phase1):
            s.resend.stop()
        elif isinstance(s, _Phase2):
            s.resend.stop()
        elif isinstance(s, _WaitingToRecover):
            s.recover_timer.stop()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, CasClientRequest):
            self._handle_client_request(msg)
        elif isinstance(msg, CasPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, CasPhase2b):
            self._handle_phase2b(msg)
        elif isinstance(msg, CasNack):
            self._handle_nack(msg)
        else:
            self.logger.fatal(f"unknown caspaxos leader message {msg!r}")

    def _handle_client_request(self, msg: CasClientRequest) -> None:
        if isinstance(self.state, _Idle):
            self._transition_to_phase1(self.state.round, [msg])
        else:
            self.state.client_requests.append(msg)

    def _handle_phase1b(self, msg: CasPhase1b) -> None:
        if not isinstance(self.state, _Phase1):
            return
        phase1 = self.state
        if msg.round != phase1.round:
            self.logger.check_lt(msg.round, phase1.round)
            return
        phase1.phase1bs[msg.acceptor_index] = msg
        if len(phase1.phase1bs) < self.config.quorum_size:
            return
        top = max(phase1.phase1bs.values(), key=lambda b: b.vote_round)
        previous = (
            frozenset() if top.vote_round == -1 else top.vote_value
        )
        new_value = frozenset(previous | phase1.client_requests[0].int_set)
        phase2a = CasPhase2a(round=phase1.round, value=new_value)
        self._broadcast(phase2a)
        phase1.resend.stop()
        self.state = _Phase2(
            client_requests=phase1.client_requests,
            round=phase1.round,
            value=new_value,
            phase2bs={},
            resend=self._make_resend(
                "resendPhase2as", self.options.resend_phase2as_period, phase2a
            ),
        )

    def _handle_phase2b(self, msg: CasPhase2b) -> None:
        if not isinstance(self.state, _Phase2):
            return
        phase2 = self.state
        if msg.round != phase2.round:
            self.logger.check_lt(msg.round, phase2.round)
            return
        phase2.phase2bs[msg.acceptor_index] = msg
        if len(phase2.phase2bs) < self.config.quorum_size:
            return
        request = phase2.client_requests[0]
        client = self.transport.address_from_bytes(request.client_address)
        self.chan(client).send(
            CasClientReply(client_id=request.client_id, value=phase2.value)
        )
        phase2.resend.stop()
        round = self.round_system.next_classic_round(self.index, phase2.round)
        if len(phase2.client_requests) == 1:
            self.state = _Idle(round=round)
        else:
            self._transition_to_phase1(round, phase2.client_requests[1:])

    def _handle_nack(self, msg: CasNack) -> None:
        round = self.state.round
        if msg.higher_round <= round:
            return
        new_round = self.round_system.next_classic_round(
            self.index, msg.higher_round
        )
        self._stop_timers()
        if isinstance(self.state, _Idle):
            self.state = _Idle(round=new_round)
            return
        requests = list(self.state.client_requests)

        def recover() -> None:
            self._transition_to_phase1(new_round, requests)

        timer = self.timer(
            "recover",
            random_duration(
                self.rng,
                self.options.min_nack_sleep_period,
                self.options.max_nack_sleep_period,
            ),
            recover,
        )
        timer.start()
        self.state = _WaitingToRecover(
            client_requests=requests, round=new_round, recover_timer=timer
        )


class CasAcceptor(Actor):
    def __init__(self, address, transport, logger, config: CasPaxosConfig):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.acceptor_addresses)
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.round = -1
        self.vote_round = -1
        self.vote_value: Optional[frozenset] = None

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, CasPhase1a):
            if msg.round <= self.round:
                self.chan(src).send(CasNack(higher_round=self.round))
                return
            self.round = msg.round
            self.chan(src).send(
                CasPhase1b(
                    round=msg.round,
                    acceptor_index=self.index,
                    vote_round=self.vote_round,
                    vote_value=self.vote_value,
                )
            )
        elif isinstance(msg, CasPhase2a):
            if msg.round < self.round:
                self.chan(src).send(CasNack(higher_round=self.round))
                return
            self.round = msg.round
            self.vote_round = msg.round
            self.vote_value = msg.value
            self.chan(src).send(
                CasPhase2b(round=msg.round, acceptor_index=self.index)
            )
        else:
            self.logger.fatal(f"unknown caspaxos acceptor message {msg!r}")


@dataclasses.dataclass
class _PendingCas:
    id: int
    result: Promise
    resend: object


class CasClient(Actor):
    def __init__(self, address, transport, logger, config: CasPaxosConfig,
                 resend_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.resend_period = resend_period
        self.address_bytes = transport.address_to_bytes(address)
        self.next_id = 0
        self.pending: Optional[_PendingCas] = None

    def propose(self, int_set) -> Promise:
        """Union int_set into the register; resolves with the new value."""
        promise = Promise()
        if self.pending is not None:
            promise.failure(RuntimeError("a proposal is already pending"))
            return promise
        id = self.next_id
        self.next_id += 1
        request = CasClientRequest(
            client_address=self.address_bytes,
            client_id=id,
            int_set=frozenset(int_set),
        )
        leader = self.config.leader_addresses[
            self.rng.randrange(len(self.config.leader_addresses))
        ]
        self.chan(leader).send(request)

        def resend() -> None:
            # Retry with any leader.
            target = self.config.leader_addresses[
                self.rng.randrange(len(self.config.leader_addresses))
            ]
            self.chan(target).send(request)
            timer.start()

        timer = self.timer(f"resendCas{id}", self.resend_period, resend)
        timer.start()
        self.pending = _PendingCas(id=id, result=promise, resend=timer)
        return promise

    def receive(self, src: Address, msg) -> None:
        if not isinstance(msg, CasClientReply):
            self.logger.fatal(f"unknown caspaxos client message {msg!r}")
        if self.pending is None or msg.client_id != self.pending.id:
            return
        pending = self.pending
        pending.resend.stop()
        self.pending = None
        pending.result.success(msg.value)
