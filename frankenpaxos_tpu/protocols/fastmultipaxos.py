"""Fast MultiPaxos (reference ``fastmultipaxos/``: Client, Leader,
Acceptor; protocol cheatsheet in ``FastMultiPaxos.proto``).

In a FAST round, clients send commands straight to the acceptors, which
vote for them in the next free slot if they previously received the
leader's distinguished "any" value for that slot — saving a message
delay versus classic Paxos. The leader collects phase 2b votes: a value
with ``fast_quorum_size`` (= f + majority-of-f+1) identical votes is
chosen; if no value can still reach a fast quorum the slot is STUCK and
the leader bumps to a higher round (``Leader.scala:692-737``). Classic
rounds work like ordinary MultiPaxos with the leader proposing. Phase 1
repair picks, per slot, the highest-vote-round values and applies the
O4 "popular item" rule from the Fast Paxos paper
(``Leader.scala:506-572``). The leader executes chosen commands itself
(there is no separate replica role) and replies with its current round
so clients learn whether to go fast (``Leader.scala:923-976``).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Set, Tuple

from frankenpaxos_tpu.core import Actor, Address, wire
from frankenpaxos_tpu.core.promise import Promise
from frankenpaxos_tpu.election import basic as election
from frankenpaxos_tpu.heartbeat import HeartbeatOptions, Participant
from frankenpaxos_tpu.protocols.multipaxos.messages import Command, CommandId
from frankenpaxos_tpu.roundsystem import RoundSystem, RoundType
from frankenpaxos_tpu.statemachine import StateMachine
from frankenpaxos_tpu.thrifty import ThriftySystem, NotThrifty
from frankenpaxos_tpu.util import histogram, popular_items

# Value kinds carried by phase 2a messages (FastMultiPaxos.proto's
# oneof {Command, Noop, AnyVal, AnyValSuffix}).
COMMAND = "command"
NOOP = "noop"
ANY = "any"
ANY_SUFFIX = "any_suffix"


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpProposeRequest:
    round: int  # the round the CLIENT believes is current
    command: Command


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpProposeReply:
    round: int
    client_pseudonym: int
    client_id: int
    result: bytes


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpLeaderInfo:
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase1a:
    round: int
    chosen_watermark: int
    chosen_slots: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase1b:
    acceptor_id: int
    round: int
    votes: tuple  # of (slot, vote_round, kind, command|None)


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase1bNack:
    acceptor_id: int
    round: int


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase2a:
    slot: int  # for ANY_SUFFIX: the first slot of the infinite suffix
    round: int
    kind: str
    command: Optional[Command] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase2aBuffer:
    phase2as: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase2b:
    acceptor_id: int
    slot: int
    round: int
    kind: str  # COMMAND or NOOP
    command: Optional[Command] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpPhase2bBuffer:
    phase2bs: tuple


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpValueChosen:
    slot: int
    kind: str
    command: Optional[Command] = None


@wire.message
@dataclasses.dataclass(frozen=True)
class FmpValueChosenBuffer:
    values: tuple


@dataclasses.dataclass(frozen=True)
class FastMultiPaxosConfig:
    f: int
    leader_addresses: tuple
    leader_election_addresses: tuple
    leader_heartbeat_addresses: tuple
    acceptor_addresses: tuple
    acceptor_heartbeat_addresses: tuple
    round_system: RoundSystem

    @property
    def n(self) -> int:
        return 2 * self.f + 1

    @property
    def classic_quorum_size(self) -> int:
        return self.f + 1

    @property
    def quorum_majority_size(self) -> int:
        # A majority of a classic quorum (Config.scala:19).
        return (self.f + 1) // 2 + 1

    @property
    def fast_quorum_size(self) -> int:
        return self.f + self.quorum_majority_size

    def check_valid(self) -> None:
        if len(self.leader_addresses) < self.f + 1:
            raise ValueError("need >= f+1 leaders")
        if len(self.acceptor_addresses) != self.n:
            raise ValueError("need exactly 2f+1 acceptors")
        if len(self.leader_election_addresses) != len(self.leader_addresses):
            raise ValueError("one election address per leader")
        if len(self.leader_heartbeat_addresses) != len(self.leader_addresses):
            raise ValueError("one heartbeat address per leader")
        if len(self.acceptor_heartbeat_addresses) != self.n:
            raise ValueError("one heartbeat address per acceptor")


# -- Acceptor -----------------------------------------------------------------


@dataclasses.dataclass
class _AcceptorEntry:
    vote_round: int
    kind: Optional[str]  # COMMAND, NOOP, or None (= voted for nothing)
    command: Optional[Command]
    any_round: Optional[int]


class FmpAcceptor(Actor):
    """``fastmultipaxos/Acceptor.scala``. One round per acceptor (not per
    slot); a log of votes; ``tail_any`` models the reference's
    ``putTail`` — an infinite suffix of "any" grants starting at a slot
    (Acceptor.scala:316-331)."""

    def __init__(self, address, transport, logger,
                 config: FastMultiPaxosConfig, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.index = config.acceptor_addresses.index(address)
        self.rng = random.Random(seed)
        self.round = -1
        self.log: Dict[int, _AcceptorEntry] = {}
        self.tail_any: Optional[Tuple[int, int]] = None  # (start, round)
        self.next_slot = 0
        # Heartbeat participant so leaders can track liveness
        # (Acceptor.scala:120-131).
        self.heartbeat = Participant(
            config.acceptor_heartbeat_addresses[self.index],
            transport, logger, [],
        )

    def _get(self, slot: int) -> Optional[_AcceptorEntry]:
        entry = self.log.get(slot)
        if entry is not None:
            return entry
        if self.tail_any is not None and slot >= self.tail_any[0]:
            return _AcceptorEntry(-1, None, None, self.tail_any[1])
        return None

    def _leader_chan(self):
        return self.chan(
            self.config.leader_addresses[
                self.config.round_system.leader(max(self.round, 0))
            ]
        )

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FmpProposeRequest):
            self._handle_propose(msg)
        elif isinstance(msg, FmpPhase1a):
            self._handle_phase1a(src, msg)
        elif isinstance(msg, FmpPhase2a):
            phase2b = self._process_phase2a(msg)
            if phase2b is not None:
                self._leader_chan().send(phase2b)
        elif isinstance(msg, FmpPhase2aBuffer):
            phase2bs = tuple(
                b for b in map(self._process_phase2a, msg.phase2as)
                if b is not None
            )
            if phase2bs:
                self._leader_chan().send(FmpPhase2bBuffer(phase2bs))
        else:
            self.logger.fatal(f"unknown fmp acceptor message {msg!r}")

    def _handle_propose(self, msg: FmpProposeRequest) -> None:
        """A client proposes directly (fast round): vote in next_slot iff
        we hold an "any" grant for our current round there and haven't
        voted in it yet (Acceptor.scala:225-248)."""
        entry = self._get(self.next_slot)
        if (
            entry is not None
            and entry.any_round == self.round
            and entry.vote_round < self.round
        ):
            self.log[self.next_slot] = _AcceptorEntry(
                self.round, COMMAND, msg.command, None
            )
            phase2b = FmpPhase2b(
                acceptor_id=self.index,
                slot=self.next_slot,
                round=self.round,
                kind=COMMAND,
                command=msg.command,
            )
            self.next_slot += 1
            self._leader_chan().send(FmpPhase2bBuffer((phase2b,)))
        # Without an "any" grant the request is ignored; the client's
        # repropose timer reroutes it via the leaders.

    def _handle_phase1a(self, src: Address, msg: FmpPhase1a) -> None:
        if msg.round <= self.round:
            self.chan(src).send(
                FmpPhase1bNack(acceptor_id=self.index, round=self.round)
            )
            return
        self.round = msg.round
        votes = []
        chosen = set(msg.chosen_slots)
        for slot in sorted(self.log):
            if slot < msg.chosen_watermark or slot in chosen:
                continue
            entry = self.log[slot]
            if entry.kind is None:
                continue  # an "any" grant without a vote
            votes.append((slot, entry.vote_round, entry.kind, entry.command))
        self.chan(src).send(
            FmpPhase1b(
                acceptor_id=self.index, round=msg.round, votes=tuple(votes)
            )
        )

    def _process_phase2a(self, msg: FmpPhase2a) -> Optional[FmpPhase2b]:
        entry = self._get(msg.slot) or _AcceptorEntry(-1, None, None, None)
        if msg.round < self.round:
            return None
        if msg.round == entry.vote_round:
            # Already voted this round: relay the vote again for liveness
            # (Acceptor.scala:267-283).
            return FmpPhase2b(
                acceptor_id=self.index, slot=msg.slot,
                round=entry.vote_round, kind=entry.kind,
                command=entry.command,
            )
        self.round = msg.round
        if msg.kind in (COMMAND, NOOP):
            self.log[msg.slot] = _AcceptorEntry(
                msg.round, msg.kind, msg.command, None
            )
            if msg.slot >= self.next_slot:
                self.next_slot = msg.slot + 1
            return FmpPhase2b(
                acceptor_id=self.index, slot=msg.slot, round=msg.round,
                kind=msg.kind, command=msg.command,
            )
        if msg.kind == ANY:
            self.log[msg.slot] = _AcceptorEntry(
                entry.vote_round, entry.kind, entry.command, msg.round
            )
            return None
        if msg.kind == ANY_SUFFIX:
            # Grant "any" to every voted slot >= msg.slot and to the
            # infinite unvoted suffix (Acceptor.scala:316-331). Fast
            # voting resumes at the suffix start: slots below msg.slot
            # are settled or under repair by the leader, and leaving
            # next_slot pointing at an ungranted gap slot would silently
            # drop every fast-path proposal.
            if msg.slot > self.next_slot:
                self.next_slot = msg.slot
            for slot in list(self.log):
                if slot >= msg.slot:
                    e = self.log[slot]
                    self.log[slot] = _AcceptorEntry(
                        e.vote_round, e.kind, e.command, msg.round
                    )
            if not self.log:
                self.tail_any = (msg.slot, msg.round)
            else:
                start = max(msg.slot, max(self.log) + 1)
                self.tail_any = (start, msg.round)
                # Unvoted gap slots in [msg.slot, start) get explicit
                # grant entries so the suffix truly covers [slot, inf).
                for slot in range(msg.slot, start):
                    if slot not in self.log:
                        self.log[slot] = _AcceptorEntry(
                            -1, None, None, msg.round
                        )
            return None
        self.logger.fatal(f"unknown phase2a kind {msg.kind}")


# -- Leader -------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class FmpLeaderOptions:
    thrifty_system: ThriftySystem = NotThrifty()
    resend_phase1as_period: float = 5.0
    resend_phase2as_period: float = 5.0
    phase2a_max_buffer_size: int = 1
    phase2a_buffer_flush_period: float = 0.1
    value_chosen_max_buffer_size: int = 1
    value_chosen_buffer_flush_period: float = 5.0
    election_options: election.ElectionOptions = election.ElectionOptions()
    heartbeat_options: HeartbeatOptions = HeartbeatOptions()


_INACTIVE = "inactive"


@dataclasses.dataclass
class _Phase1:
    phase1bs: Dict[int, FmpPhase1b]
    pending_proposals: List[Tuple[Address, FmpProposeRequest]]


@dataclasses.dataclass
class _Phase2:
    # slot -> (kind, command) proposed in this round but not yet chosen.
    pending_entries: Dict[int, Tuple[str, Optional[Command]]]
    # slot -> acceptor_id -> phase2b.
    phase2bs: Dict[int, Dict[int, FmpPhase2b]]
    phase2a_buffer: List[FmpPhase2a]
    value_chosen_buffer: List[FmpValueChosen]


class FmpLeader(Actor):
    """``fastmultipaxos/Leader.scala``. Executes the log itself and
    answers clients with its round (there is no replica role)."""

    def __init__(self, address, transport, logger,
                 config: FastMultiPaxosConfig, state_machine: StateMachine,
                 options: FmpLeaderOptions = FmpLeaderOptions(),
                 seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        logger.check(address in config.leader_addresses)
        self.config = config
        self.options = options
        self.state_machine = state_machine
        self.rng = random.Random(seed)
        self.index = config.leader_addresses.index(address)
        self.round = 0 if config.round_system.leader(0) == self.index else -1
        self.log: Dict[int, Tuple[str, Optional[Command]]] = {}
        self.client_table: Dict[Tuple[bytes, int], Tuple[int, bytes]] = {}
        self.chosen_watermark = 0
        self.next_slot = 0

        # Election among leaders (Leader.scala:313-337).
        self.election = election.Participant(
            config.leader_election_addresses[self.index],
            transport, logger, config.leader_election_addresses,
            initial_leader_index=config.round_system.leader(0),
            options=options.election_options, seed=seed,
        )
        self.election.register(
            lambda leader_index: self.leader_change(
                leader_index == self.index, self.round
            )
        )
        # Heartbeats monitoring acceptor liveness: a fast round is only
        # attempted if a fast quorum looks alive (Leader.scala:842-858).
        self.heartbeat = Participant(
            config.leader_heartbeat_addresses[self.index],
            transport, logger, config.acceptor_heartbeat_addresses,
            options=options.heartbeat_options,
        )

        def resend_phase1as() -> None:
            if isinstance(self.state, _Phase1):
                self._send_phase1as(thrifty=False)
            self.resend_phase1as_timer.start()

        def resend_phase2as() -> None:
            self._resend_phase2as()
            self.resend_phase2as_timer.start()

        def flush_phase2as() -> None:
            self.flush_phase2a_buffer()
            self.phase2a_flush_timer.start()

        def flush_value_chosen() -> None:
            self.flush_value_chosen_buffer()
            self.value_chosen_flush_timer.start()

        self.resend_phase1as_timer = self.timer(
            "resendPhase1as", options.resend_phase1as_period, resend_phase1as
        )
        self.resend_phase2as_timer = self.timer(
            "resendPhase2as", options.resend_phase2as_period, resend_phase2as
        )
        self.phase2a_flush_timer = self.timer(
            "phase2aBufferFlush", options.phase2a_buffer_flush_period,
            flush_phase2as,
        )
        self.value_chosen_flush_timer = self.timer(
            "valueChosenBufferFlush", options.value_chosen_buffer_flush_period,
            flush_value_chosen,
        )

        if self.round == 0:
            self._send_phase1as(thrifty=True)
            self.resend_phase1as_timer.start()
            self.state: object = _Phase1({}, [])
        else:
            self.state = _INACTIVE

    # -- Helpers -------------------------------------------------------------

    def _quorum_size(self, round: int) -> int:
        if self.config.round_system.round_type(round) == RoundType.FAST:
            return self.config.fast_quorum_size
        return self.config.classic_quorum_size

    def _thrifty_acceptors(self, min_size: int):
        chosen = self.options.thrifty_system.choose(
            {a: 0.0 for a in self.config.acceptor_addresses},
            min_size, self.rng,
        )
        return [self.chan(a) for a in chosen]

    def _send_phase1as(self, thrifty: bool) -> None:
        targets = (
            self._thrifty_acceptors(self.config.classic_quorum_size)
            if thrifty
            else [self.chan(a) for a in self.config.acceptor_addresses]
        )
        phase1a = FmpPhase1a(
            round=self.round,
            chosen_watermark=self.chosen_watermark,
            chosen_slots=tuple(
                s for s in self.log if s >= self.chosen_watermark
            ),
        )
        for chan in targets:
            chan.send(phase1a)

    def _choose_proposal(
        self, votes: Dict[int, Dict[int, Tuple[int, str, Optional[Command]]]],
        slot: int,
    ) -> Tuple[Tuple[str, Optional[Command]], Set[Command]]:
        """The Fast Paxos value-selection rule (Leader.scala:506-572):
        among the highest-vote-round values V, a singleton or an O4
        (majority-popular) value MUST be proposed; otherwise anything in
        V may be, and the rest are returned for later proposal."""
        in_slot = [
            votes[a].get(slot, (-1, None, None)) for a in votes
        ]
        k = max(vr for vr, _, _ in in_slot)
        if k == -1:
            return (NOOP, None), set()
        V = [(kind, cmd) for vr, kind, cmd in in_slot if vr == k]
        if len(set(V)) == 1:
            return V[0], set()
        o4 = popular_items(V, self.config.quorum_majority_size)
        if o4:
            self.logger.check_eq(len(o4), 1)
            return next(iter(o4)), set()
        rest = {cmd for kind, cmd in V if kind == COMMAND}
        first = V[0]
        if first[0] == COMMAND:
            rest.discard(first[1])
        return first, rest

    def _phase2b_result(
        self, phase2: _Phase2, slot: int
    ) -> Tuple[str, Optional[Tuple[str, Optional[Command]]]]:
        """("nothing"|"ready"|"stuck", entry) — fast rounds need
        fast_quorum_size IDENTICAL votes and may get irrecoverably stuck
        (Leader.scala:692-737)."""
        in_slot = phase2.phase2bs[slot]
        if self.config.round_system.round_type(self.round) == RoundType.CLASSIC:
            if len(in_slot) >= self.config.classic_quorum_size:
                return "ready", phase2.pending_entries[slot]
            return "nothing", None
        if len(in_slot) < self.config.classic_quorum_size:
            return "nothing", None
        counts = histogram(
            (b.kind, b.command) for b in in_slot.values()
        )
        votes_left = self.config.n - len(in_slot)
        if not any(
            c + votes_left >= self.config.fast_quorum_size
            for c in counts.values()
        ):
            return "stuck", None
        for value, count in counts.items():
            if count >= self.config.fast_quorum_size:
                return "ready", value
        return "nothing", None

    def flush_phase2a_buffer(self) -> None:
        if not isinstance(self.state, _Phase2):
            return
        if self.state.phase2a_buffer:
            buffer = FmpPhase2aBuffer(tuple(self.state.phase2a_buffer))
            for chan in self._thrifty_acceptors(self._quorum_size(self.round)):
                chan.send(buffer)
            self.state.phase2a_buffer.clear()

    def flush_value_chosen_buffer(self) -> None:
        if not isinstance(self.state, _Phase2):
            return
        if self.state.value_chosen_buffer:
            buffer = FmpValueChosenBuffer(tuple(self.state.value_chosen_buffer))
            for a in self.config.leader_addresses:
                if a != self.address:
                    self.chan(a).send(buffer)
            self.state.value_chosen_buffer.clear()

    def _resend_phase2as(self) -> None:
        """No slot may stay unchosen forever (Leader.scala:787-837): besides
        re-proposing pending entries, drive every partially-voted slot below
        next_slot to a decision — propose the most-voted value there, or a
        noop if nothing was voted (a fast-path slot where some acceptors
        missed the client's direct send can otherwise never reach its
        all-acceptor fast quorum)."""
        if not isinstance(self.state, _Phase2):
            return
        sent: Set[int] = set()
        for slot, (kind, command) in self.state.pending_entries.items():
            sent.add(slot)
            phase2a = FmpPhase2a(
                slot=slot, round=self.round, kind=kind, command=command
            )
            for a in self.config.acceptor_addresses:
                self.chan(a).send(phase2a)
        end_slot = max(
            list(self.state.phase2bs) + list(self.log) + [-1]
        )
        for slot in range(self.chosen_watermark, end_slot + 1):
            if slot in sent or slot in self.log:
                continue
            votes = self.state.phase2bs.get(slot, {})
            if votes:
                counts = histogram((b.kind, b.command) for b in votes.values())
                (kind, command), _ = max(counts.items(), key=lambda kv: kv[1])
            else:
                kind, command = NOOP, None
            phase2a = FmpPhase2a(
                slot=slot, round=self.round, kind=kind, command=command
            )
            for a in self.config.acceptor_addresses:
                self.chan(a).send(phase2a)

    def _buffer_phase2a(self, phase2a: FmpPhase2a) -> None:
        state = self.state
        state.phase2a_buffer.append(phase2a)
        if len(state.phase2a_buffer) >= self.options.phase2a_max_buffer_size:
            self.flush_phase2a_buffer()

    def leader_change(self, is_new_leader: bool, higher_than: int) -> None:
        """(Leader.scala:842-923) — go fast if a fast quorum of acceptors
        looks alive, else classic."""
        self.logger.check_ge(higher_than, self.round)
        rs = self.config.round_system
        alive = len(self.heartbeat.unsafe_alive())
        if alive >= self.config.fast_quorum_size:
            next_round = rs.next_fast_round(self.index, higher_than)
            if next_round is None:
                next_round = rs.next_classic_round(self.index, higher_than)
        else:
            next_round = rs.next_classic_round(self.index, higher_than)

        if is_new_leader:
            self.round = next_round
            self._send_phase1as(thrifty=True)
            self.resend_phase1as_timer.reset()
            self.resend_phase2as_timer.stop()
            self.phase2a_flush_timer.stop()
            self.value_chosen_flush_timer.stop()
            self.state = _Phase1({}, [])
        else:
            self.resend_phase1as_timer.stop()
            self.resend_phase2as_timer.stop()
            self.phase2a_flush_timer.stop()
            self.value_chosen_flush_timer.stop()
            self.state = _INACTIVE

    def _execute_log(self) -> None:
        while self.chosen_watermark in self.log:
            kind, command = self.log[self.chosen_watermark]
            if kind == COMMAND:
                cid = command.command_id
                key = (cid.client_address, cid.client_pseudonym)
                cached = self.client_table.get(key)
                if cached is None or cid.client_id > cached[0]:
                    output = self.state_machine.run(command.command)
                    self.client_table[key] = (cid.client_id, output)
                    if self.state != _INACTIVE:
                        client = self.transport.address_from_bytes(
                            cid.client_address
                        )
                        self.chan(client).send(
                            FmpProposeReply(
                                round=self.round,
                                client_pseudonym=cid.client_pseudonym,
                                client_id=cid.client_id,
                                result=output,
                            )
                        )
            self.chosen_watermark += 1

    # -- Handlers ------------------------------------------------------------

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FmpProposeRequest):
            self._handle_propose(src, msg)
        elif isinstance(msg, FmpPhase1b):
            self._handle_phase1b(msg)
        elif isinstance(msg, FmpPhase1bNack):
            self._handle_phase1b_nack(msg)
        elif isinstance(msg, FmpPhase2b):
            self._process_phase2b(msg)
        elif isinstance(msg, FmpPhase2bBuffer):
            for phase2b in msg.phase2bs:
                self._process_phase2b(phase2b)
        elif isinstance(msg, FmpValueChosen):
            self._handle_value_chosen(msg)
        elif isinstance(msg, FmpValueChosenBuffer):
            for value in msg.values:
                self._handle_value_chosen(value)
        else:
            self.logger.fatal(f"unknown fmp leader message {msg!r}")

    def _handle_propose(self, src: Address, msg: FmpProposeRequest) -> None:
        cid = msg.command.command_id
        cached = self.client_table.get(
            (cid.client_address, cid.client_pseudonym)
        )
        if cached is not None:
            if cid.client_id == cached[0] and self.state != _INACTIVE:
                self.chan(src).send(
                    FmpProposeReply(
                        round=self.round,
                        client_pseudonym=cid.client_pseudonym,
                        client_id=cached[0],
                        result=cached[1],
                    )
                )
                return
            if cid.client_id < cached[0]:
                return

        if self.state == _INACTIVE:
            return
        if isinstance(self.state, _Phase1):
            if msg.round != self.round:
                self.chan(src).send(FmpLeaderInfo(round=self.round))
            else:
                self.state.pending_proposals.append((src, msg))
            return

        # Phase 2.
        if msg.round != self.round:
            self.chan(src).send(FmpLeaderInfo(round=self.round))
            return
        if self.config.round_system.round_type(self.round) == RoundType.FAST:
            # The client knows it's a fast round yet came to us: the fast
            # path failed for it, so move to a fresh round
            # (Leader.scala:1110-1121).
            self.leader_change(True, self.round)
            return
        self.state.pending_entries[self.next_slot] = (COMMAND, msg.command)
        self.state.phase2bs[self.next_slot] = {}
        self._buffer_phase2a(
            FmpPhase2a(
                slot=self.next_slot, round=self.round,
                kind=COMMAND, command=msg.command,
            )
        )
        self.next_slot += 1

    def _handle_phase1b(self, msg: FmpPhase1b) -> None:
        if not isinstance(self.state, _Phase1) or msg.round != self.round:
            return
        state = self.state
        state.phase1bs[msg.acceptor_id] = msg
        if len(state.phase1bs) < self.config.classic_quorum_size:
            return
        self.resend_phase1as_timer.stop()

        votes: Dict[int, Dict[int, Tuple[int, str, Optional[Command]]]] = {
            a: {s: (vr, kind, cmd) for s, vr, kind, cmd in b.votes}
            for a, b in state.phase1bs.items()
        }
        end_slot = max(
            [s for by_slot in votes.values() for s in by_slot]
            + [s for s in self.log]
            + [-1]
        )

        phase2 = _Phase2({}, {}, [], [])
        proposed: Set[Command] = set()
        yet_to_propose: Set[Command] = set()
        for slot in range(self.chosen_watermark, end_slot + 1):
            if slot in self.log:
                continue
            (kind, command), rest = self._choose_proposal(votes, slot)
            yet_to_propose |= rest
            if kind == COMMAND:
                proposed.add(command)
            phase2.pending_entries[slot] = (kind, command)
            phase2.phase2bs[slot] = {}
            phase2.phase2a_buffer.append(
                FmpPhase2a(slot=slot, round=self.round, kind=kind,
                           command=command)
            )

        self.state = phase2
        self.resend_phase2as_timer.start()
        self.phase2a_flush_timer.start()
        self.value_chosen_flush_timer.start()

        self.next_slot = end_slot + 1
        for _, proposal in state.pending_proposals:
            phase2.pending_entries[self.next_slot] = (
                COMMAND, proposal.command
            )
            phase2.phase2bs[self.next_slot] = {}
            phase2.phase2a_buffer.append(
                FmpPhase2a(slot=self.next_slot, round=self.round,
                           kind=COMMAND, command=proposal.command)
            )
            self.next_slot += 1
        for command in yet_to_propose - proposed:
            phase2.pending_entries[self.next_slot] = (COMMAND, command)
            phase2.phase2bs[self.next_slot] = {}
            phase2.phase2a_buffer.append(
                FmpPhase2a(slot=self.next_slot, round=self.round,
                           kind=COMMAND, command=command)
            )
            self.next_slot += 1

        if self.config.round_system.round_type(self.round) == RoundType.FAST:
            # Open the infinite fast-path suffix (Leader.scala:1262-1267).
            phase2.phase2a_buffer.append(
                FmpPhase2a(slot=self.next_slot, round=self.round,
                           kind=ANY_SUFFIX)
            )
        self.flush_phase2a_buffer()

    def _handle_phase1b_nack(self, msg: FmpPhase1bNack) -> None:
        if isinstance(self.state, _Phase1) and msg.round > self.round:
            self.leader_change(True, msg.round)

    def _process_phase2b(self, msg: FmpPhase2b) -> None:
        if not isinstance(self.state, _Phase2):
            return
        if msg.round != self.round or msg.slot in self.log:
            return
        phase2 = self.state
        phase2.phase2bs.setdefault(msg.slot, {})[msg.acceptor_id] = msg
        if (
            self.config.round_system.round_type(self.round)
            == RoundType.CLASSIC
            and msg.slot not in phase2.pending_entries
        ):
            return
        status, entry = self._phase2b_result(phase2, msg.slot)
        if status == "nothing":
            return
        if status == "stuck":
            self.leader_change(True, self.round)
            return
        kind, command = entry
        self.log[msg.slot] = entry
        phase2.pending_entries.pop(msg.slot, None)
        phase2.phase2bs.pop(msg.slot, None)
        self._execute_log()
        value_chosen = FmpValueChosen(slot=msg.slot, kind=kind,
                                      command=command)
        if self.options.value_chosen_max_buffer_size == 1:
            for a in self.config.leader_addresses:
                if a != self.address:
                    self.chan(a).send(value_chosen)
        else:
            phase2.value_chosen_buffer.append(value_chosen)
            if (
                len(phase2.value_chosen_buffer)
                >= self.options.value_chosen_max_buffer_size
            ):
                self.flush_value_chosen_buffer()

    def _handle_value_chosen(self, msg: FmpValueChosen) -> None:
        existing = self.log.get(msg.slot)
        entry = (msg.kind, msg.command)
        if existing is not None:
            self.logger.check_eq(existing, entry)
        else:
            self.log[msg.slot] = entry
        self._execute_log()


# -- Client -------------------------------------------------------------------


@dataclasses.dataclass
class _FmpPending:
    id: int
    command: bytes
    result: Promise
    repropose: object


class FmpClient(Actor):
    """``fastmultipaxos/Client.scala``: tracks its best guess of the
    round; fast rounds go straight to ALL acceptors, classic rounds to
    the round's leader; a repropose timer falls back to every leader."""

    def __init__(self, address, transport, logger,
                 config: FastMultiPaxosConfig,
                 repropose_period: float = 10.0, seed: int = 0):
        super().__init__(address, transport, logger)
        config.check_valid()
        self.config = config
        self.rng = random.Random(seed)
        self.repropose_period = repropose_period
        self.address_bytes = transport.address_to_bytes(address)
        self.round = 0
        self.ids: Dict[int, int] = {}
        self.pending: Dict[int, _FmpPending] = {}

    def _request(self, pseudonym: int, pending: _FmpPending):
        return FmpProposeRequest(
            round=self.round,
            command=Command(
                command_id=CommandId(
                    client_address=self.address_bytes,
                    client_pseudonym=pseudonym,
                    client_id=pending.id,
                ),
                command=pending.command,
            ),
        )

    def _send(self, pseudonym: int, pending: _FmpPending) -> None:
        request = self._request(pseudonym, pending)
        if (
            self.config.round_system.round_type(self.round) == RoundType.FAST
        ):
            for a in self.config.acceptor_addresses:
                self.chan(a).send(request)
        else:
            leader = self.config.leader_addresses[
                self.config.round_system.leader(self.round)
            ]
            self.chan(leader).send(request)

    def propose(self, pseudonym: int, command: bytes) -> Promise:
        promise = Promise()
        if pseudonym in self.pending:
            promise.failure(RuntimeError(f"pseudonym {pseudonym} busy"))
            return promise
        id = self.ids.get(pseudonym, 0)
        self.ids[pseudonym] = id + 1

        def repropose() -> None:
            # Fall back through every leader (Client.scala:233-254).
            pending = self.pending.get(pseudonym)
            if pending is not None:
                request = self._request(pseudonym, pending)
                for a in self.config.leader_addresses:
                    self.chan(a).send(request)
            timer.start()

        timer = self.timer(
            f"repropose{pseudonym}", self.repropose_period, repropose
        )
        pending = _FmpPending(
            id=id, command=command, result=promise, repropose=timer
        )
        self.pending[pseudonym] = pending
        self._send(pseudonym, pending)
        timer.start()
        return promise

    def _process_new_round(self, new_round: int) -> None:
        if new_round <= self.round:
            return
        self.round = new_round
        for pseudonym, pending in self.pending.items():
            self._send(pseudonym, pending)
            pending.repropose.reset()

    def receive(self, src: Address, msg) -> None:
        if isinstance(msg, FmpLeaderInfo):
            self._process_new_round(msg.round)
        elif isinstance(msg, FmpProposeReply):
            pending = self.pending.get(msg.client_pseudonym)
            if pending is not None and msg.client_id == pending.id:
                pending.repropose.stop()
                del self.pending[msg.client_pseudonym]
                pending.result.success(msg.result)
            self._process_new_round(msg.round)
        else:
            self.logger.fatal(f"unknown fmp client message {msg!r}")
