"""Pluggable deterministic state machines with conflict detection and
snapshots.

Capability parity with the reference ``statemachine`` package:
``StateMachine`` trait (``statemachine/StateMachine.scala:11-46``: ``run``,
``conflicts``, ``to_bytes``/``from_bytes`` snapshots, ``conflict_index``,
``top_k_conflict_index``), the registry-by-name used by CLI flags
(:48-59), and the implementations ``Noop``, ``Register``, ``AppendLog``,
``ReadableAppendLog``, and ``KeyValueStore`` (get/set over a string map;
two commands conflict iff their key sets intersect and at least one
writes, ``KeyValueStore.scala:77-96``; inverted-index ConflictIndex
:112-217 and TopK variant :219-383). ``TypedStateMachine`` adapts
struct-typed SMs to the bytes interface (``TypedStateMachine.scala``).

Commands and outputs are bytes at the framework boundary (what protocols
replicate); typed SMs use the wire codec for their inputs/outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Generic, List, Optional, Set, Tuple, TypeVar

from frankenpaxos_tpu.core import wire
from frankenpaxos_tpu.util import TopK, TopOne, VertexIdLike

Key = TypeVar("Key")


class ConflictIndex(Generic[Key]):
    """Tracks put commands by key and answers "which commands conflict with
    this one" (``statemachine/ConflictIndex.scala``)."""

    def put(self, key: Key, command: bytes) -> None:
        raise NotImplementedError

    def remove(self, key: Key) -> None:
        raise NotImplementedError

    def get_conflicts(self, command: bytes) -> Set[Key]:
        raise NotImplementedError

    def put_snapshot(self, key: Key) -> None:
        """Record a snapshot command, which conflicts with everything."""
        raise NotImplementedError


class NaiveConflictIndex(ConflictIndex[Key]):
    """O(n) conflict index valid for any state machine
    (StateMachine.scala's default conflictIndex)."""

    def __init__(self, conflicts):
        self._conflicts = conflicts
        self.commands: Dict[Key, bytes] = {}
        self.snapshots: Set[Key] = set()

    def put(self, key: Key, command: bytes) -> None:
        self.commands[key] = command

    def remove(self, key: Key) -> None:
        self.commands.pop(key, None)
        self.snapshots.discard(key)

    def put_snapshot(self, key: Key) -> None:
        self.snapshots.add(key)

    def get_conflicts(self, command: bytes) -> Set[Key]:
        out = {
            k for k, cmd in self.commands.items() if self._conflicts(cmd, command)
        }
        return out | set(self.snapshots)


class StateMachine:
    """A deterministic state machine (StateMachine.scala:11-46)."""

    def run(self, input: bytes) -> bytes:
        raise NotImplementedError

    def conflicts(self, first: bytes, second: bytes) -> bool:
        raise NotImplementedError

    def to_bytes(self) -> bytes:
        """Snapshot the state (does not mutate)."""
        raise NotImplementedError

    def from_bytes(self, snapshot: bytes) -> None:
        """Replace state with a snapshot produced by to_bytes."""
        raise NotImplementedError

    def conflict_index(self) -> ConflictIndex:
        return NaiveConflictIndex(self.conflicts)

    def top_k_conflict_index(
        self, k: int, num_leaders: int, like: VertexIdLike
    ) -> ConflictIndex:
        return TopKConflictIndexAdapter(self, k, num_leaders, like)


class TopKConflictIndexAdapter(ConflictIndex):
    """Generic top-k conflict index: instead of exact conflict sets, keeps
    the top-k conflicting vertex ids per leader (the compression EPaxos-family
    protocols use for dependency sets; KeyValueStore.scala:219-383)."""

    def __init__(self, sm: StateMachine, k: int, num_leaders: int, like: VertexIdLike):
        self.sm = sm
        self.like = like
        self.k = k
        self.num_leaders = num_leaders
        self.commands: Dict[Any, bytes] = {}
        self.snapshot_top = TopK(k, num_leaders, like) if k > 1 else None
        self.snapshot_top_one = TopOne(num_leaders, like) if k == 1 else None

    def put(self, key, command: bytes) -> None:
        self.commands[key] = command

    def remove(self, key) -> None:
        self.commands.pop(key, None)

    def put_snapshot(self, key) -> None:
        if self.k == 1:
            self.snapshot_top_one.put(key)
        else:
            self.snapshot_top.put(key)

    def get_top_k_conflicts(self, command: bytes) -> List[Set[int]]:
        """Per-leader top-k conflicting ids (including snapshots)."""
        top = TopK(self.k, self.num_leaders, self.like)
        for key, cmd in self.commands.items():
            if self.sm.conflicts(cmd, command):
                top.put(key)
        if self.k == 1 and self.snapshot_top_one is not None:
            for i, frontier in enumerate(self.snapshot_top_one.get()):
                if frontier > 0:
                    top.put(self.like.make(i, frontier - 1))
        elif self.snapshot_top is not None:
            merged = TopK(self.k, self.num_leaders, self.like)
            merged.merge_equals(self.snapshot_top)
            merged.merge_equals(top)
            top = merged
        return top.get()

    def get_conflicts(self, command: bytes) -> Set:
        return {
            self.like.make(i, id_)
            for i, ids in enumerate(self.get_top_k_conflicts(command))
            for id_ in ids
        }


# -- Implementations ---------------------------------------------------------


class Noop(StateMachine):
    """Ignores inputs, outputs empty bytes (Noop.scala)."""

    def run(self, input: bytes) -> bytes:
        return b""

    def conflicts(self, first: bytes, second: bytes) -> bool:
        return False

    def to_bytes(self) -> bytes:
        return b""

    def from_bytes(self, snapshot: bytes) -> None:
        pass

    def __repr__(self) -> str:
        return "Noop"


class Register(StateMachine):
    """A single register; every write conflicts (Register.scala)."""

    def __init__(self) -> None:
        self.x = b""

    def run(self, input: bytes) -> bytes:
        self.x = input
        return self.x

    def conflicts(self, first: bytes, second: bytes) -> bool:
        return True

    def to_bytes(self) -> bytes:
        return self.x

    def from_bytes(self, snapshot: bytes) -> None:
        self.x = snapshot

    def __repr__(self) -> str:
        return f"Register({self.x!r})"


class AppendLog(StateMachine):
    """Append-only log; returns the index of the appended entry
    (AppendLog.scala)."""

    def __init__(self) -> None:
        self.log: List[bytes] = []

    def run(self, input: bytes) -> bytes:
        self.log.append(input)
        return wire.encode(len(self.log) - 1)

    def conflicts(self, first: bytes, second: bytes) -> bool:
        return True

    def to_bytes(self) -> bytes:
        return wire.encode(self.log)

    def from_bytes(self, snapshot: bytes) -> None:
        self.log = wire.decode(snapshot)

    def __repr__(self) -> str:
        return f"AppendLog({self.log!r})"


class ReadableAppendLog(StateMachine):
    """Append log with a built-in read: a non-empty input is appended (the
    reply is its index); an EMPTY input is a pure read returning the latest
    entry (ReadableAppendLog.scala:20-31 — "a little janky, but it keeps
    testing simple")."""

    def __init__(self) -> None:
        self.log: List[bytes] = []

    def run(self, input: bytes) -> bytes:
        if len(input) > 0:
            self.log.append(input)
            return wire.encode(len(self.log) - 1)
        return self.log[-1] if self.log else b""

    def conflicts(self, first: bytes, second: bytes) -> bool:
        return True

    def to_bytes(self) -> bytes:
        return wire.encode(self.log)

    def from_bytes(self, snapshot: bytes) -> None:
        self.log = wire.decode(snapshot)

    def get(self) -> List[bytes]:
        return list(self.log)

    def __repr__(self) -> str:
        return f"ReadableAppendLog({self.log!r})"


# -- KeyValueStore -----------------------------------------------------------


@wire.message
@dataclasses.dataclass(frozen=True)
class KVGetRequest:
    keys: tuple  # of str


@wire.message
@dataclasses.dataclass(frozen=True)
class KVSetRequest:
    key_values: tuple  # of (key, value) str pairs


@wire.message
@dataclasses.dataclass(frozen=True)
class KVGetReply:
    key_values: tuple  # of (key, Optional[value]) pairs


@wire.message
@dataclasses.dataclass(frozen=True)
class KVSetReply:
    pass


def kv_get(*keys: str) -> bytes:
    return wire.encode(KVGetRequest(tuple(keys)))


def kv_set(*key_values: Tuple[str, str]) -> bytes:
    return wire.encode(KVSetRequest(tuple(key_values)))


class KeyValueStore(StateMachine):
    """String-keyed KV store over get/set batches. Two commands conflict iff
    their key sets intersect and at least one is a set
    (KeyValueStore.scala:77-96)."""

    def __init__(self) -> None:
        self.kvs: Dict[str, str] = {}

    def __repr__(self) -> str:
        return f"KeyValueStore({self.kvs!r})"

    def get(self) -> Dict[str, str]:
        return dict(self.kvs)

    def typed_run(self, input: Any) -> Any:
        if isinstance(input, KVGetRequest):
            return KVGetReply(
                tuple((k, self.kvs.get(k)) for k in input.keys)
            )
        if isinstance(input, KVSetRequest):
            for k, v in input.key_values:
                self.kvs[k] = v
            return KVSetReply()
        raise TypeError(f"bad KeyValueStore input {input!r}")

    def run(self, input: bytes) -> bytes:
        return wire.encode(self.typed_run(wire.decode(input)))

    @staticmethod
    def _keys(input: Any) -> Set[str]:
        if isinstance(input, KVGetRequest):
            return set(input.keys)
        if isinstance(input, KVSetRequest):
            return {k for k, _ in input.key_values}
        raise TypeError(f"bad KeyValueStore input {input!r}")

    def conflicts(self, first: bytes, second: bytes) -> bool:
        a, b = wire.decode(first), wire.decode(second)
        if isinstance(a, KVGetRequest) and isinstance(b, KVGetRequest):
            return False
        return bool(self._keys(a) & self._keys(b))

    def to_bytes(self) -> bytes:
        return wire.encode(self.kvs)

    def from_bytes(self, snapshot: bytes) -> None:
        self.kvs = wire.decode(snapshot)

    def conflict_index(self) -> "KeyValueStoreConflictIndex":
        return KeyValueStoreConflictIndex()


class KeyValueStoreConflictIndex(ConflictIndex):
    """Inverted-index conflict index: per-key sets of getter and setter
    command keys (KeyValueStore.scala:112-217)."""

    def __init__(self) -> None:
        self.commands: Dict[Any, bytes] = {}
        self.gets: Dict[str, Set] = {}
        self.sets: Dict[str, Set] = {}
        self.snapshots: Set = set()

    def put(self, key, command: bytes) -> None:
        self.remove(key)
        self.commands[key] = command
        decoded = wire.decode(command)
        index = self.gets if isinstance(decoded, KVGetRequest) else self.sets
        for k in KeyValueStore._keys(decoded):
            index.setdefault(k, set()).add(key)

    def remove(self, key) -> None:
        command = self.commands.pop(key, None)
        self.snapshots.discard(key)
        if command is None:
            return
        decoded = wire.decode(command)
        index = self.gets if isinstance(decoded, KVGetRequest) else self.sets
        for k in KeyValueStore._keys(decoded):
            keys = index.get(k)
            if keys is not None:
                keys.discard(key)
                if not keys:
                    del index[k]

    def put_snapshot(self, key) -> None:
        self.snapshots.add(key)

    def get_conflicts(self, command: bytes) -> Set:
        decoded = wire.decode(command)
        out: Set = set(self.snapshots)
        if isinstance(decoded, KVGetRequest):
            for k in decoded.keys:
                out |= self.sets.get(k, set())
        else:
            for k in KeyValueStore._keys(decoded):
                out |= self.gets.get(k, set())
                out |= self.sets.get(k, set())
        return out


# -- Registry (StateMachine.scala:48-59) -------------------------------------

REGISTRY = {
    "AppendLog": AppendLog,
    "KeyValueStore": KeyValueStore,
    "Noop": Noop,
    "Register": Register,
    "ReadableAppendLog": ReadableAppendLog,
}


def from_name(name: str) -> StateMachine:
    try:
        return REGISTRY[name]()
    except KeyError:
        raise ValueError(
            f"{name} is not one of {', '.join(sorted(REGISTRY))}."
        ) from None
