"""frankenpaxos_tpu: a TPU-native framework for implementing, simulating,
property-testing, and benchmarking state-machine-replication protocols.

Capability parity target: mwhittaker/frankenpaxos (see SURVEY.md). Protocols
are written once against a small actor/transport abstraction and run on
interchangeable backends:

  * ``core.SimTransport``   — deterministic in-process simulation used for
    randomized invariant testing with counterexample shrinking (the
    reference's ``FakeTransport``/``JsTransport`` roles, merged).
  * ``core.TcpTransport``   — asyncio TCP deployment backend (the reference's
    ``NettyTcpTransport`` role).
  * ``tpu.TpuSimTransport`` — the new, TPU-native backend: per-actor protocol
    state flattened into batched JAX arrays, handlers ``jax.vmap``'d over a
    replica axis, quorum/ballot aggregation compiled to XLA segmented
    reductions, whole-cluster ticks under ``jax.lax.scan`` and sharded over a
    ``jax.sharding.Mesh``.
"""

__version__ = "0.1.0"
