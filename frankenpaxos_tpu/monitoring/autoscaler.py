"""SLO-driven elastic-capacity policy engine: the graceful degradation
LADDER over the serve loop's control plane.

PR 10/15 gave the control plane exactly one response to a p99 or shed
alarm: clamp admission (``monitoring/slo.py`` decays the offered-rate
scale) — the fleet degrades by refusing work. The Compartmentalization
report (arXiv 2012.15762) is a thesis about scaling each bottleneck
ROLE independently instead; ``tpu/elastic.py`` gives the backends
pre-allocated padded role planes behind traced membership counts, so
growing a role is a zero-recompile state edit. This module is the
policy that decides WHEN and WHICH:

    alarm fires
      -> scale UP the bottleneck role          (capacity first)
      -> admission clamp ONLY once every role
         is already at its padded capacity     (refusal last)
    alarm clears
      -> release the clamp FIRST               (restore admission)
      -> shrink roles only after a sustained
         in-SLO trough                         (drain-then-deactivate)

The bottleneck pick is FEEDFORWARD, not trial-and-error: each elastic
role maps onto an ``ops/costmodel.py`` role (``ROLE_COSTS``), and
``costmodel.capacity(role_counts)`` names the role whose aggregate
commands/sec ceiling is lowest — that is the one worth growing (HT-
Paxos, arXiv 1407.1237: the batching/dissemination roles saturate
first, so adding acceptors to a batcher-bound deployment buys
nothing). The same ceilings rank shrink candidates in reverse: the
trough releases the MOST over-provisioned role first. The stride is
CONFIDENCE-WEIGHTED: ``costmodel.envelope_confidence`` condenses the
committed capture record's measured/predicted envelope spread into
[0, 1], and the scale-up step is ``max_step`` scaled by it (floored
at ``step``) — a model with a tight envelope earns multi-instance
strides, a drifting one is trusted for single probes only.

Everything here is pure host arithmetic over the per-drain SLO status
dicts — the engine never touches the device. The serve loop applies
its decisions through two traced-state verbs (``ServeLoop.resize`` ->
``elastic.set_target`` and ``workload.set_rate``), so the compiled
program never changes. Like the SLO engine, the autoscaler's FULL
decision state round-trips through ``to_state``/``restore_state`` — a
SIGKILLed serve run resumes with the ladder position (targets,
cooldowns, clamp latch, trough streak) restored bit-exactly and its
subsequent decisions replay the uninterrupted twin's.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from frankenpaxos_tpu.ops import costmodel


# Elastic role axis (tpu/elastic.py names) -> cost-model role
# (ops/costmodel.py ROLE_COSTS names). "groups" are flagship proposer
# groups (a leader lane each); fleet "instances" are whole replicas of
# the leader-bound flagship program.
DEFAULT_ROLE_MAP: Tuple[Tuple[str, str], ...] = (
    ("proxies", "proxy_leader"),
    ("batchers", "batcher"),
    ("unbatchers", "unbatcher"),
    ("replicas", "replica"),
    ("groups", "leader"),
    ("instances", "leader"),
)


@dataclasses.dataclass(frozen=True)
class AutoscalerPolicy:
    """The ladder's knobs (JSON-roundtrippable so serve configs and
    checkpoint manifests serialize it)."""

    # Drains that must pass between consecutive resize ACTIONS (scale
    # up or down) — resizing every drain would outrun the drains that
    # measure the previous resize's effect.
    cooldown_drains: int = 1
    # Consecutive deeply-in-SLO drains (p99 <= trough_frac * target,
    # no shed breach, clamp released) before the first scale-down: the
    # diurnal-trough detector. Large enough that a burst's tail lull
    # does not shed capacity the next burst needs.
    trough_after: int = 6
    trough_frac: float = 0.5
    # Role-count step per action (padded capacities are small — the
    # ladder climbs one instance at a time so each drain measures one
    # increment's effect).
    step: int = 1
    # Confidence-weighted scale-UP stride ceiling: when the cost
    # model's capture record proves its predictions tight
    # (costmodel.envelope_confidence ~1.0), the ladder trusts the
    # feedforward bottleneck pick enough to climb
    # ``round(max_step * confidence)`` instances per action instead of
    # probing one at a time; a wide envelope spread (or no capture
    # evidence) decays the stride back to ``step``. Scale-DOWN always
    # gives capacity back one ``step`` at a time — shedding on a
    # model's word is how the next burst finds the fleet short.
    max_step: int = 1
    # Elastic role -> cost-model role for the capacity feedforward
    # (tuple-of-pairs so the policy stays hashable).
    role_map: Tuple[Tuple[str, str], ...] = DEFAULT_ROLE_MAP

    def __post_init__(self):
        assert self.cooldown_drains >= 0
        assert self.trough_after >= 1
        assert 0.0 < self.trough_frac <= 1.0
        assert self.step >= 1
        assert self.max_step >= self.step
        seen = set()
        for role, cm in self.role_map:
            assert role not in seen, f"duplicate role_map entry {role!r}"
            seen.add(role)
            assert cm in costmodel.ROLE_COSTS, (
                f"role_map target {cm!r} unknown to costmodel.ROLE_COSTS"
            )

    def to_dict(self) -> dict:
        return {
            "cooldown_drains": self.cooldown_drains,
            "trough_after": self.trough_after,
            "trough_frac": self.trough_frac,
            "step": self.step,
            "max_step": self.max_step,
            "role_map": [list(p) for p in self.role_map],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "AutoscalerPolicy":
        d = dict(d)
        if "role_map" in d:
            d["role_map"] = tuple(tuple(p) for p in d["role_map"])
        return cls(**d)


class Autoscaler:
    """Feed one :meth:`decide` per drain (the SLO engine's status dict
    in); read the resize actions + the effective admission scale out.

    The autoscaler is the serve loop's single writer of elastic
    targets, so it tracks them HOST-side (``self.targets``) — reading
    them back off the device would sync the hot path against the
    in-flight chunk, exactly what the double-buffered drain exists to
    avoid. ``roles`` fixes each role's (capacity, floor) from the
    ElasticPlan; ``initial`` seeds the targets (defaults to capacity,
    matching ``elastic.make_state``)."""

    def __init__(
        self,
        policy: AutoscalerPolicy,
        roles: Dict[str, Tuple[int, int]],  # role -> (capacity, floor)
        initial: Optional[Dict[str, int]] = None,
        envelope: Optional[dict] = None,
    ):
        assert roles, "an autoscaler needs at least one elastic role"
        self.policy = policy
        # Confidence weighting for the scale-up stride: the envelope
        # spread of the committed costmodel capture record (pass a
        # parsed costmodel_envelope.json payload, or None to read the
        # committed one). Construction-time config, like the policy —
        # a checkpoint-resumed twin re-derives it from the same file.
        self.feedforward_confidence = costmodel.envelope_confidence(
            envelope
        )
        rm = dict(policy.role_map)
        for r, (cap, floor) in roles.items():
            assert r in rm, f"no role_map entry for elastic role {r!r}"
            assert 1 <= floor <= cap, (r, cap, floor)
        self.roles = {r: (int(c), int(f)) for r, (c, f) in roles.items()}
        self.targets: Dict[str, int] = {
            r: int((initial or {}).get(r, cap))
            for r, (cap, _) in self.roles.items()
        }
        for r, n in self.targets.items():
            cap, floor = self.roles[r]
            assert floor <= n <= cap, (r, n)
        self.clamp_engaged = False
        self.drains = 0
        self.scale_up_events = 0
        self.scale_down_events = 0
        self.clamp_engagements = 0
        self.clamp_releases = 0
        self.events: List[dict] = []  # the ordered ladder record
        self._last_action_drain = -(10**9)
        self._trough_streak = 0
        self._events_restored = 0

    # -- the capacity feedforward -------------------------------------------

    def _ceilings(self) -> Dict[str, float]:
        """Per-ELASTIC-role aggregate commands/sec ceilings at the
        current targets (count x the mapped cost-model role's
        per-instance roofline rate)."""
        rm = dict(self.policy.role_map)
        return {
            r: n * costmodel.role_rate(rm[r])
            for r, n in self.targets.items()
        }

    def feedforward(self) -> dict:
        """The ``costmodel.capacity`` report at the current targets —
        the observability blob attached to every capacity event (the
        bottleneck pick is derived from the same ceilings)."""
        rm = dict(self.policy.role_map)
        counts: Dict[str, int] = {}
        for r, n in self.targets.items():
            # Two elastic roles never share a cost-model role within
            # one backend, but be safe: capacity() keys by cost-model
            # role, so a collision sums the counts.
            counts[rm[r]] = counts.get(rm[r], 0) + n
        out = costmodel.capacity(counts)
        out["envelope_confidence"] = dict(self.feedforward_confidence)
        out["up_step"] = self._up_step()
        return out

    def _up_step(self) -> int:
        """The confidence-weighted scale-up stride: ``max_step``
        scaled by how tightly the model's capture record tracks
        measurement, never below the base ``step``."""
        conf = self.feedforward_confidence["confidence"]
        return max(
            self.policy.step, int(round(self.policy.max_step * conf))
        )

    def _pick_grow(self) -> Optional[str]:
        """The bottleneck role that still has padded headroom (lowest
        ceiling wins — growing anything else moves no bottleneck)."""
        ceil = self._ceilings()
        grow = [
            r for r, n in self.targets.items() if n < self.roles[r][0]
        ]
        if not grow:
            return None
        return min(grow, key=lambda r: (ceil[r], r))

    def _pick_shrink(self) -> Optional[str]:
        """The most over-provisioned role above its floor (highest
        ceiling releases first)."""
        ceil = self._ceilings()
        shrink = [
            r for r, n in self.targets.items() if n > self.roles[r][1]
        ]
        if not shrink:
            return None
        return max(shrink, key=lambda r: (ceil[r], r))

    # -- the per-drain ladder step ------------------------------------------

    def _event(self, kind: str, **meta) -> dict:
        ev = {"event": self._events_restored + len(self.events),
              "drain": self.drains, "kind": kind, **meta}
        self.events.append(ev)
        return ev

    def decide(self, status: dict) -> dict:
        """One SLO status dict in (``SloEngine.observe``'s return);
        the ladder's decision out:

        * ``actions`` — resize verbs to apply, as
          ``{"role", "from", "to"}`` dicts (empty most drains);
        * ``clamp_engaged`` — whether the admission clamp may bind
          this drain (False while padded capacity remains);
        * ``effective_scale`` — what the loop multiplies into the base
          rate: the SLO engine's decayed scale when the clamp is
          engaged, 1.0 otherwise (the scale keeps decaying inside the
          SLO engine either way, so an engage applies the full decay
          accumulated while scale-ups were being tried first).
        """
        self.drains += 1
        pol = self.policy
        actions: List[dict] = []
        cooled = (
            self.drains - self._last_action_drain > pol.cooldown_drains
        )

        if status["alarm"]:
            # Rung 1: the alarm is latched — try capacity first.
            self._trough_streak = 0
            role = self._pick_grow() if cooled else None
            if role is not None:
                cap, _ = self.roles[role]
                frm = self.targets[role]
                to = min(cap, frm + self._up_step())
                self.targets[role] = to
                self._last_action_drain = self.drains
                self.scale_up_events += 1
                actions.append({"role": role, "from": frm, "to": to})
                self._event(
                    "scale_up", role=role, frm=frm, to=to,
                    p99=status["p99"], feedforward=self.feedforward(),
                )
            elif self._pick_grow() is None and not self.clamp_engaged:
                # Rung 2: every role is at padded capacity — only now
                # may the admission clamp bind (the last resort).
                self.clamp_engaged = True
                self.clamp_engagements += 1
                self._event(
                    "clamp_engage", p99=status["p99"],
                    scale=status["scale"],
                )
        else:
            if self.clamp_engaged:
                # Recovery rung 1: release the clamp BEFORE shrinking
                # anything — admission is restored first, capacity is
                # given back only after the trough proves itself.
                self.clamp_engaged = False
                self.clamp_releases += 1
                self._trough_streak = 0
                self._event("clamp_release", p99=status["p99"])
            else:
                deep = (
                    status["p99"] < 0
                    or status["p99"]
                    <= pol.trough_frac * status["p99_target"]
                ) and not status["shed_breach"]
                self._trough_streak = (
                    self._trough_streak + 1 if deep else 0
                )
                if self._trough_streak >= pol.trough_after and cooled:
                    role = self._pick_shrink()
                    if role is not None:
                        _, floor = self.roles[role]
                        frm = self.targets[role]
                        to = max(floor, frm - pol.step)
                        self.targets[role] = to
                        self._last_action_drain = self.drains
                        self.scale_down_events += 1
                        actions.append(
                            {"role": role, "from": frm, "to": to}
                        )
                        self._event(
                            "scale_down", role=role, frm=frm, to=to,
                            p99=status["p99"],
                            feedforward=self.feedforward(),
                        )

        return {
            "actions": actions,
            "clamp_engaged": self.clamp_engaged,
            "effective_scale": (
                float(status["scale"]) if self.clamp_engaged else 1.0
            ),
            "targets": dict(self.targets),
        }

    # -- reporting / checkpoint-restore -------------------------------------

    def summary(self) -> dict:
        return {
            "policy": self.policy.to_dict(),
            "roles": {
                r: {
                    "target": self.targets[r],
                    "capacity": self.roles[r][0],
                    "floor": self.roles[r][1],
                }
                for r in sorted(self.roles)
            },
            "clamp_engaged": self.clamp_engaged,
            "scale_up_events": self.scale_up_events,
            "scale_down_events": self.scale_down_events,
            "clamp_engagements": self.clamp_engagements,
            "clamp_releases": self.clamp_releases,
            "events": list(self.events),
            "feedforward": self.feedforward(),
        }

    def to_state(self) -> dict:
        """The FULL decision state (the bit-exact-resume contract the
        SLO engine set: a resumed run's ladder decisions replay the
        uninterrupted twin's)."""
        return {
            "targets": dict(self.targets),
            "clamp_engaged": bool(self.clamp_engaged),
            "drains": int(self.drains),
            "scale_up_events": int(self.scale_up_events),
            "scale_down_events": int(self.scale_down_events),
            "clamp_engagements": int(self.clamp_engagements),
            "clamp_releases": int(self.clamp_releases),
            "last_action_drain": int(self._last_action_drain),
            "trough_streak": int(self._trough_streak),
            "events": self._events_restored + len(self.events),
        }

    def restore_state(self, s: dict) -> None:
        assert set(s["targets"]) == set(self.targets), (
            "restored autoscaler targets name different roles"
        )
        self.targets = {r: int(n) for r, n in s["targets"].items()}
        self.clamp_engaged = bool(s["clamp_engaged"])
        self.drains = int(s["drains"])
        self.scale_up_events = int(s["scale_up_events"])
        self.scale_down_events = int(s["scale_down_events"])
        self.clamp_engagements = int(s["clamp_engagements"])
        self.clamp_releases = int(s["clamp_releases"])
        self._last_action_drain = int(s["last_action_drain"])
        self._trough_streak = int(s["trough_streak"])
        # events is reporting, not decision state (the SLO history
        # convention): a resumed process logs fresh but keeps the count.
        self.events = []
        self._events_restored = int(s.get("events", 0))
