"""SLO engine for the live serve loop: rolling p99-vs-target and
shed-rate alarms computed from the streaming drains, plus the admission
clamp recommendation the serve loop's control plane applies through
``workload.set_rate`` (the autoscaler seed ROADMAP's serving-shape item
asks for).

Everything here is pure host-side arithmetic over the histogram DELTAS
the :class:`frankenpaxos_tpu.tpu.telemetry.DrainCursor` drains hand
over — the engine never touches the device. Per drain:

  * the commit-latency and queue-wait histograms' deltas are pushed
    into a rolling window of the last ``window_chunks`` drains;
  * the windowed p99 (nearest-rank over the summed window histogram)
    compares against ``p99_target_ticks`` — an alarm fires only when
    the p99 is STRICTLY above target (exactly-at-target is within SLO),
    and an empty window histogram (no samples) never alarms;
  * the windowed shed fraction (shed / offered over the window)
    compares against ``shed_rate_target`` the same way;
  * alarms latch: once fired, an alarm clears only after
    ``clear_after`` consecutive in-SLO drains (hysteresis, so a p99
    oscillating at the boundary doesn't flap the admission clamp);
  * while an alarm is latched, the recommended admission scale decays
    multiplicatively by ``clamp_factor`` per alarmed drain (floored at
    ``min_scale``); after it clears, the scale recovers by
    ``recover_factor`` per clean drain back up to 1.0 (the plan rate).

The serve loop multiplies the workload plan's offered rate by
``scale`` between chunks — a traced-state update, never a recompile.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, Optional

import numpy as np


def hist_p99(hist, q: float = 0.99) -> int:
    """Nearest-rank percentile of an integer histogram (bin index =
    value in ticks); -1 on an empty histogram."""
    h = np.asarray(hist, np.int64)
    total = int(h.sum())
    if total == 0:
        return -1
    rank = max(1, int(np.ceil(q * total)))
    return int((h.cumsum() >= rank).argmax())


@dataclasses.dataclass(frozen=True)
class SloPolicy:
    """The SLO + clamp configuration (JSON-roundtrippable via
    ``to_dict``/``from_dict`` so serve configs serialize)."""

    p99_target_ticks: int  # windowed p99 must stay <= this
    # Which latency histogram the p99 tracks: the queue-wait histogram
    # (arrival -> admission, the load signal), the commit-latency
    # histogram (admission -> chosen, the protocol signal), or their
    # conservative sum of p99s ("client").
    source: str = "queue_wait"
    shed_rate_target: float = 1.0  # windowed shed fraction above = alarm
    window_chunks: int = 4  # rolling window length (drains)
    clear_after: int = 2  # consecutive in-SLO drains to clear a latch
    clamp_factor: float = 0.5  # scale *= this per alarmed drain
    recover_factor: float = 1.25  # scale *= this per clean drain
    min_scale: float = 0.05  # clamp floor

    def __post_init__(self):
        assert self.p99_target_ticks >= 0
        assert self.source in ("queue_wait", "commit_latency", "client")
        assert 0.0 < self.shed_rate_target <= 1.0
        assert self.window_chunks >= 1
        assert self.clear_after >= 1
        assert 0.0 < self.clamp_factor < 1.0
        assert self.recover_factor > 1.0
        assert 0.0 < self.min_scale <= 1.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SloPolicy":
        return cls(**d)


class SloEngine:
    """Feed one :meth:`observe` per drain; read ``alarm``/``scale``."""

    def __init__(self, policy: SloPolicy):
        self.policy = policy
        self.alarm = False  # latched alarm state
        self.scale = 1.0  # recommended admission scale (0, 1]
        self.alarms_fired = 0  # latch transitions off -> on
        self.clamps_applied = 0  # alarmed drains (scale decays each)
        self._clean_streak = 0
        self._lat: Deque[np.ndarray] = collections.deque(
            maxlen=policy.window_chunks
        )
        self._wait: Deque[np.ndarray] = collections.deque(
            maxlen=policy.window_chunks
        )
        self._flow: Deque[tuple] = collections.deque(
            maxlen=policy.window_chunks
        )  # (offered, shed) deltas
        self.history: list = []  # one status dict per observe()
        self._observations_restored = 0  # pre-resume observe() count

    # -- windowed signals ---------------------------------------------------

    def _window_sum(self, dq: Deque[np.ndarray]) -> Optional[np.ndarray]:
        if not dq:
            return None
        out = np.zeros_like(dq[0])
        for h in dq:
            out = out + h
        return out

    def windowed_p99(self) -> int:
        """The policy-source p99 over the rolling window (-1 when the
        window holds no samples)."""
        lat = self._window_sum(self._lat)
        wait = self._window_sum(self._wait)
        if self.policy.source == "commit_latency":
            return hist_p99(lat) if lat is not None else -1
        if self.policy.source == "queue_wait":
            return hist_p99(wait) if wait is not None else -1
        # "client": conservative sum of the two stage p99s.
        p_l = hist_p99(lat) if lat is not None else -1
        p_w = hist_p99(wait) if wait is not None else -1
        if p_l < 0 and p_w < 0:
            return -1
        return max(p_l, 0) + max(p_w, 0)

    def windowed_shed_rate(self) -> float:
        offered = sum(f[0] for f in self._flow)
        shed = sum(f[1] for f in self._flow)
        if offered + shed <= 0:
            return 0.0
        return shed / float(offered + shed)

    # -- the per-drain step -------------------------------------------------

    def observe(
        self,
        *,
        lat_hist_delta=None,
        wait_hist_delta=None,
        offered_delta: int = 0,
        shed_delta: int = 0,
    ) -> dict:
        """One drain's deltas in; the updated alarm/scale status out.
        A missing histogram (None) contributes nothing to the window;
        an all-zero delta window (no samples yet) never alarms."""
        if lat_hist_delta is not None:
            self._lat.append(np.asarray(lat_hist_delta, np.int64))
        if wait_hist_delta is not None:
            self._wait.append(np.asarray(wait_hist_delta, np.int64))
        self._flow.append((int(offered_delta), int(shed_delta)))

        p99 = self.windowed_p99()
        shed_rate = self.windowed_shed_rate()
        # Strictly-above-target fires; exactly-at-target and an empty
        # window (p99 == -1) are in SLO.
        p99_breach = p99 > self.policy.p99_target_ticks
        shed_breach = shed_rate > self.policy.shed_rate_target
        breach = p99_breach or shed_breach

        fired = cleared = False
        if breach:
            self._clean_streak = 0
            if not self.alarm:
                self.alarm = True
                fired = True
                self.alarms_fired += 1
            # Decay the admission scale while the alarm is latched.
            self.scale = max(
                self.policy.min_scale,
                self.scale * self.policy.clamp_factor,
            )
            self.clamps_applied += 1
        else:
            self._clean_streak += 1
            if self.alarm and self._clean_streak >= self.policy.clear_after:
                self.alarm = False
                cleared = True
            if not self.alarm and self.scale < 1.0:
                self.scale = min(
                    1.0, self.scale * self.policy.recover_factor
                )
        status = {
            "p99": p99,
            "p99_target": self.policy.p99_target_ticks,
            "p99_breach": p99_breach,
            "shed_rate": round(shed_rate, 6),
            "shed_breach": shed_breach,
            "alarm": self.alarm,
            "fired": fired,
            "cleared": cleared,
            "scale": round(self.scale, 6),
        }
        self.history.append(status)
        return status

    def summary(self) -> dict:
        return {
            "policy": self.policy.to_dict(),
            "alarm": self.alarm,
            "scale": round(self.scale, 6),
            "alarms_fired": self.alarms_fired,
            "clamps_applied": self.clamps_applied,
            "observations": self._observations_restored + len(self.history),
        }

    # -- checkpoint/restore (tpu/checkpoint.py manifests) -------------------
    # The engine is pure host arithmetic, so its FULL decision state is
    # a small JSON blob: restoring it makes a resumed serve loop's
    # clamp decisions replay the uninterrupted twin's exactly (the
    # bit-exact-resume contract extends through the control plane).

    def to_state(self) -> dict:
        return {
            "alarm": bool(self.alarm),
            "scale": float(self.scale),
            "alarms_fired": int(self.alarms_fired),
            "clamps_applied": int(self.clamps_applied),
            "clean_streak": int(self._clean_streak),
            "observations": self._observations_restored + len(self.history),
            "lat": [h.tolist() for h in self._lat],
            "wait": [h.tolist() for h in self._wait],
            "flow": [list(f) for f in self._flow],
        }

    def restore_state(self, s: dict) -> None:
        self.alarm = bool(s["alarm"])
        self.scale = float(s["scale"])
        self.alarms_fired = int(s["alarms_fired"])
        self.clamps_applied = int(s["clamps_applied"])
        self._clean_streak = int(s["clean_streak"])
        self._lat.clear()
        self._lat.extend(np.asarray(h, np.int64) for h in s["lat"])
        self._wait.clear()
        self._wait.extend(np.asarray(h, np.int64) for h in s["wait"])
        self._flow.clear()
        self._flow.extend(tuple(f) for f in s["flow"])
        # history is reporting, not decision state: a resumed process
        # starts a fresh log but keeps the observation count.
        self.history = []
        self._observations_restored = int(s.get("observations", 0))


class FleetSloEngine:
    """Per-instance SLO evaluation for a FLEET serve loop: one
    independent :class:`SloEngine` per instance, fed that instance's
    own histogram deltas each drain — instance 7 breaching its p99
    clamps instance 7's admission scale and NOBODY else's (the
    per-instance control loop ``harness/serve.FleetServeLoop`` closes
    through ``parallel.sharding.set_fleet_rates``). Pure host
    arithmetic, like the single-instance engine."""

    def __init__(self, policy: SloPolicy, n: int):
        assert n >= 1
        self.policy = policy
        self.engines = [SloEngine(policy) for _ in range(n)]

    def __len__(self) -> int:
        return len(self.engines)

    def observe(self, per_instance: list) -> list:
        """One drain: ``per_instance`` is a list of n kwarg dicts for
        :meth:`SloEngine.observe` (lat_hist_delta / wait_hist_delta /
        offered_delta / shed_delta). Returns the n status dicts."""
        assert len(per_instance) == len(self.engines)
        return [
            eng.observe(**kw)
            for eng, kw in zip(self.engines, per_instance)
        ]

    @property
    def scales(self) -> list:
        """The per-instance admission scales (the clamp vector the
        serve loop multiplies into the base rates)."""
        return [eng.scale for eng in self.engines]

    @property
    def alarms(self) -> list:
        return [eng.alarm for eng in self.engines]

    def summary(self) -> dict:
        return {
            "policy": self.policy.to_dict(),
            "instances": len(self.engines),
            "alarms": [eng.alarm for eng in self.engines],
            "scales": [round(eng.scale, 6) for eng in self.engines],
            "alarms_fired": [eng.alarms_fired for eng in self.engines],
            "clamps_applied": [
                eng.clamps_applied for eng in self.engines
            ],
        }
