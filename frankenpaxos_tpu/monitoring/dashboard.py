"""Dashboard rendering from a metrics capture (the analog of the
reference's 15 Grafana dashboards, ``grafana/dashboards/*.json``): one
command turns a benchmark's ``metrics.csv`` into a multi-panel figure of
per-role request rates and handler latencies — or a DEVICE-SIDE
telemetry capture (``tpu/telemetry.py`` ``to_dict()`` JSON, e.g. the
``telemetry`` block of ``bench.py --telemetry`` results) into
commit-rate, phase-mix, latency-histogram, and queue-depth panels.

    python -m frankenpaxos_tpu.monitoring.dashboard <bench_dir_or_csv> \\
        [-o dashboard.png]
    python -m frankenpaxos_tpu.monitoring.dashboard telemetry.json \\
        [-o dashboard.png]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from frankenpaxos_tpu.monitoring.scrape import MetricsCapture


def render_dashboard(
    capture: MetricsCapture,
    output: str,
    window_ms: float = 1000.0,
) -> Optional[str]:
    """One panel per *_requests_total metric (rate per series) plus one
    per *_handler_latency_seconds (mean latency per series). Returns the
    output path, or None if the capture holds no plottable metrics."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    names = set(capture.names())
    panels = []
    for name in sorted(names):
        # Host-role request counters AND the device-side telemetry
        # counters a serve loop streams into the same CSV schema
        # (fpx_device_*_total, monitoring/scrape.append_device_samples)
        # both render as rate panels — the --live serve view.
        # queue_depth is a GAUGE (its exposition total is a sum of
        # end-of-tick depths, not an event count): rate() of it is
        # meaningless, so it stays off the panel list.
        if name.endswith("_requests_total") or (
            name.startswith("fpx_device_")
            and name.endswith("_total")
            and "queue_depth" not in name
        ):
            panels.append(("rate", name))
    for count_name in sorted(names):
        if not count_name.endswith("_handler_latency_seconds_count"):
            continue
        base = count_name[: -len("_count")]
        if f"{base}_sum" in names:
            panels.append(("latency", base))
    # Efficiency lane (the roofline observatory's serve view): the
    # fpx_efficiency_* gauges a serve loop appends each drain —
    # observed vs model-predicted commits/tick plus their ratio, all
    # x1000 fixed-point (scrape.append_efficiency_samples).
    if "fpx_efficiency_ratio_x1000" in names:
        panels.append(("efficiency", "fpx_efficiency"))
    if not panels:
        return None

    fig, axes = plt.subplots(
        len(panels), 1, figsize=(9, 3 * len(panels)), squeeze=False
    )
    for ax_row, (kind, name) in zip(axes, panels):
        ax = ax_row[0]
        if kind == "rate":
            wide = capture.rate(name, window_ms=window_ms)
            title = f"{name} (rate/s, {int(window_ms)}ms windows)"
        elif kind == "efficiency":
            for gauge, label in (
                ("fpx_efficiency_observed_commits_per_tick_x1000",
                 "observed/tick"),
                ("fpx_efficiency_predicted_commits_per_tick_x1000",
                 "model predicted/tick"),
            ):
                if gauge not in names:
                    continue
                g = capture.query(gauge).sum(axis=1) / 1000.0
                ax.plot(g.index, g.values, label=label)
            ratio = (
                capture.query("fpx_efficiency_ratio_x1000").sum(axis=1)
                / 1000.0
            )
            ax2 = ax.twinx()
            ax2.plot(
                ratio.index, ratio.values, color="tab:red", ls="--",
                label="efficiency ratio",
            )
            ax2.axhline(1.0, color="tab:red", lw=0.5, alpha=0.5)
            ax2.set_ylabel("measured/predicted", fontsize=7)
            ax.set_title(
                "efficiency: commits/tick vs cost model", fontsize=9
            )
            ax.set_ylabel("commits/tick")
            ax.grid(True)
            ax.legend(fontsize=6, loc="upper left")
            continue
        else:
            # Mean handler latency = d(sum)/d(count) over the window.
            total = capture.query(f"{name}_sum")
            count = capture.query(f"{name}_count")
            wide = (
                total.ffill().diff().sum(axis=1)
                / count.ffill().diff().sum(axis=1).replace(0, float("nan"))
            ).to_frame("mean_s") * 1000.0
            title = f"{name} (mean ms between scrapes)"
        for col in wide.columns:
            series = wide[col].dropna()
            # Aggregate labelled series lightly: plot each, thin legend.
            ax.plot(series.index, series.values, label=str(col)[:60])
        ax.set_title(title, fontsize=9)
        ax.grid(True)
        if 0 < len(wide.columns) <= 8:
            ax.legend(fontsize=6, loc="best")
    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def render_telemetry_dashboard(capture: dict, output: str) -> Optional[str]:
    """Render a device-side telemetry capture (``tpu/telemetry.py``
    ``to_dict()`` shape: ``{"series": {...}, "lat_hist": [...],
    "queue_hist": [...], ...}``) as a four-panel figure:

      1. commit/execute/proposal rate per tick over the retained ring
         (the commit-rate panel of the acceptance criteria);
      2. phase message mix per tick (phase1/phase2/retries/drops);
      3. the commit-latency histogram (fixed LAT_BINS tick bins);
      4. queue depth per tick + the occupancy-fraction histogram.

    Returns the output path, or None when the capture holds no ticks."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    series = capture.get("series", {})
    ticks = series.get("tick", [])
    if not len(ticks):
        return None

    fig, axes = plt.subplots(4, 1, figsize=(9, 12))

    ax = axes[0]
    for name in ("commits", "executes", "proposals"):
        ax.plot(ticks, series.get(name, []), label=name)
    # Capacity events (tpu/elastic.py applied resizes) as vertical
    # marks on the rate panel — the dashboard's view of the fleet
    # breathing with load.
    resizes = series.get("resizes", [])
    marked = False
    for tk, n in zip(ticks, resizes):
        if n:
            ax.axvline(tk, color="tab:purple", linestyle="--",
                       linewidth=0.8, alpha=0.7,
                       label=None if marked else "resize")
            marked = True
    ax.set_title(
        f"device commit rate per tick (last {len(ticks)} of "
        f"{capture.get('ticks', '?')} ticks)",
        fontsize=9,
    )
    ax.set_ylabel("events/tick")
    ax.legend(fontsize=7)
    ax.grid(True)

    ax = axes[1]
    for name in ("phase1_msgs", "phase2_msgs", "retries", "drops",
                 "leader_changes", "resizes"):
        vals = series.get(name, [])
        if any(vals):
            ax.plot(ticks, vals, label=name)
    ax.set_title("phase message mix per tick", fontsize=9)
    ax.set_ylabel("messages/tick")
    ax.legend(fontsize=7)
    ax.grid(True)

    ax = axes[2]
    lat_hist = capture.get("lat_hist", [])
    ax.bar(range(len(lat_hist)), lat_hist, width=1.0)
    ax.set_title("commit latency histogram (ticks)", fontsize=9)
    ax.set_xlabel("latency (ticks)")
    ax.set_ylabel("commits")
    ax.grid(True)

    ax = axes[3]
    ax.plot(ticks, series.get("queue_depth", []), label="queue depth")
    ax.set_title("in-flight queue depth per tick", fontsize=9)
    ax.set_xlabel("tick")
    ax.set_ylabel("slots")
    ax.grid(True)
    qh = capture.get("queue_hist", [])
    if any(qh):
        inset = ax.inset_axes([0.7, 0.55, 0.28, 0.4])
        inset.bar(range(len(qh)), qh, width=1.0)
        inset.set_title("occupancy hist", fontsize=6)
        inset.tick_params(labelsize=5)

    if capture.get("model_flagged"):
        fig.suptitle(
            "MODEL-FLAGGED CAPTURE: "
            + (capture.get("model_flag_reason") or "implausible vs "
               "the cost model — re-measure")[:160],
            fontsize=8, color="red",
        )

    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def render_fleet_dashboard(
    capture: MetricsCapture,
    output: str,
) -> Optional[str]:
    """FLEET view (``--fleet``): instance x time heatmaps of the
    per-instance summary metrics a ``FleetServeLoop`` streams into the
    scrape CSV (``scrape.append_fleet_summary``) — commit rate, p99
    commit latency, shed — plus the STRAGGLER LANE (the in-graph
    outlier flags) and, when present, the per-instance admission-scale
    lane the SLO control plane drove. Instance indices come from the
    ``instance`` column (``scrape.instance_index``: legacy
    single-instance names parse as instance 0, so a pre-fleet capture
    renders as a one-row fleet). Returns the output path, or None when
    the capture holds no fleet metrics."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    from frankenpaxos_tpu.monitoring.scrape import instance_index

    panels = [
        ("fpx_fleet_commit_rate_x1000", "commit rate (x1000/tick)"),
        ("fpx_fleet_p99_commit_latency_ticks", "p99 commit latency (ticks)"),
        ("fpx_fleet_shed_total", "shed (cumulative)"),
        ("fpx_fleet_straggler", "straggler lane (flagged drains)"),
        ("fpx_fleet_admission_scale", "admission scale (x1000)"),
        ("fpx_efficiency_ratio_x1000",
         "efficiency vs cost model (x1000)"),
    ]

    def matrix(name):
        """(instances x drains) value matrix for one fleet metric, or
        None when the capture has no samples of it."""
        df = capture.df[capture.df["name"] == name]
        if not len(df):
            return None
        df = df.copy()
        df["inst"] = df["instance"].map(instance_index)
        wide = df.pivot_table(
            index="inst", columns="ts", values="value", aggfunc="last"
        ).sort_index()
        return np.asarray(wide.ffill(axis=1).fillna(0.0))

    mats = []
    for name, title in panels:
        m = matrix(name)
        if m is not None:
            mats.append((m, title, name))
    if not mats:
        return None

    fig, axes = plt.subplots(
        len(mats), 1, figsize=(9, 2.2 * len(mats)), squeeze=False
    )
    for ax_row, (m, title, name) in zip(axes, mats):
        ax = ax_row[0]
        binary = name in (
            "fpx_fleet_straggler",
        )
        im = ax.imshow(
            m,
            aspect="auto",
            interpolation="nearest",
            cmap="Reds" if binary else "viridis",
            vmin=0.0 if binary else None,
            vmax=1.0 if binary else None,
        )
        ax.set_title(title, fontsize=9)
        ax.set_ylabel("instance")
        ax.set_yticks(range(m.shape[0]))
        if not binary:
            fig.colorbar(im, ax=ax, fraction=0.03, pad=0.01)
    axes[-1][0].set_xlabel("drain (scrape order)")
    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def render_roofline(envelope: dict, output: str) -> Optional[str]:
    """ROOFLINE view (``--roofline``): the performance observatory's
    predicted-vs-measured picture from a ``costmodel_envelope.json``
    payload (``microbench costmodel`` with ``FPX_WRITE_ENVELOPE=1``):

      1. per-plane measured/predicted ratio lanes, one point per
         recorded microbench capture, with the model envelope band —
         anything outside the band is what ``costmodel-drift`` flags;
      2. the roofline scatter: bytes-moved vs predicted and measured
         throughput per plane (call-overhead-bound planes sit left,
         bandwidth-bound planes right).

    Returns the output path, or None when the payload carries no
    capture verdicts."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    captures = envelope.get("captures", {})
    planes = envelope.get("planes", {})
    rows = [
        (label, r) for label in sorted(captures)
        for r in captures[label]
    ]
    if not rows:
        return None
    names = sorted({r["plane"] for _, r in rows})
    lo, hi = envelope.get("envelope", [0.0, 0.0])

    fig, (ax, ax2) = plt.subplots(2, 1, figsize=(9, 8))
    ax.axhspan(lo, hi, color="tab:green", alpha=0.12,
               label=f"model envelope [{lo}, {hi}]")
    ax.axhline(1.0, color="tab:green", lw=0.6)
    for label in sorted(captures):
        xs, ys = [], []
        for r in captures[label]:
            xs.append(names.index(r["plane"]))
            ys.append(r["ratio"])
        ax.plot(xs, ys, "o", ms=4, label=label)
    ax.set_xticks(range(len(names)))
    ax.set_xticklabels(names, rotation=30, ha="right", fontsize=6)
    ax.set_yscale("log")
    ax.set_ylabel("measured / predicted")
    ax.set_title(
        "per-plane efficiency lanes vs the cost-model envelope "
        f"(constants v{envelope.get('constants_version', '?')})",
        fontsize=9,
    )
    ax.grid(True, which="both", alpha=0.3)
    ax.legend(fontsize=6, loc="best")

    for name in names:
        p = planes.get(name)
        if not p:
            continue
        x = p["in_bytes"] + p["out_bytes"]
        ax2.plot(x, p["predicted_per_sec_cpu"], "s", color="tab:blue",
                 ms=5)
        measured = [
            r["measured_per_sec"] for _, r in rows if r["plane"] == name
        ]
        ax2.plot([x] * len(measured), measured, "o", color="tab:orange",
                 ms=4, alpha=0.7)
        ax2.annotate(name.replace("multipaxos_", "mp_"), (x, measured[0]),
                     fontsize=5, textcoords="offset points",
                     xytext=(3, 3))
    ax2.plot([], [], "s", color="tab:blue", label="predicted (cpu_jit)")
    ax2.plot([], [], "o", color="tab:orange", label="measured captures")
    ax2.set_xscale("log")
    ax2.set_yscale("log")
    ax2.set_xlabel("bytes moved per dispatch")
    ax2.set_ylabel("dispatches / s")
    ax2.set_title("roofline: traffic vs throughput per plane", fontsize=9)
    ax2.grid(True, which="both", alpha=0.3)
    ax2.legend(fontsize=6, loc="best")

    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def tail_live(
    path: str,
    output: str,
    interval_s: float = 1.0,
    max_seconds: float = 30.0,
    window_ms: float = 1000.0,
    idle_exit_s: float = 10.0,
) -> int:
    """LIVE mode: tail a scrape CSV that a serve loop (or
    ``MetricsScraper``) is still appending to, re-rendering the
    dashboard whenever the file grows — watching a long-lived run
    instead of waiting for a finished capture. Returns the number of
    renders. Exits after ``max_seconds``, or once the file has been
    idle for ``idle_exit_s`` (the run ended)."""
    import time

    renders = 0
    last_size = -1
    last_growth = time.monotonic()
    deadline = time.monotonic() + max_seconds
    while time.monotonic() < deadline:
        try:
            size = os.path.getsize(path)
        except OSError:
            size = -1  # not written yet — keep waiting
        if size != last_size and size > 0:
            last_growth = time.monotonic()
            try:
                if render_dashboard(
                    MetricsCapture(path), output, window_ms=window_ms
                ):
                    renders += 1
                    print(f"live: rendered {output} ({size} bytes)")
                # Mark this size consumed only on a clean render: a
                # torn mid-append read leaves last_size stale, so the
                # next poll retries even if the file stopped growing.
                last_size = size
            except Exception as e:
                print(f"live: render skipped ({e})", file=sys.stderr)
        elif time.monotonic() - last_growth > idle_exit_s:
            break
        time.sleep(interval_s)
    return renders


def _load_telemetry_capture(path: str) -> Optional[dict]:
    """The telemetry dict if ``path`` is a telemetry JSON capture (bare
    ``to_dict()`` output, or any JSON object carrying one under a
    ``"telemetry"`` key, e.g. a bench.py --telemetry result)."""
    if not path.endswith(".json"):
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    if "series" in payload and "lat_hist" in payload:
        return payload
    nested = payload.get("telemetry")
    if isinstance(nested, dict) and "series" in nested:
        # Stale-capture honesty (bench._prefer_last_good /
        # costmodel.flag_capture): a capture whose headline failed the
        # model plausibility check renders with an explicit banner,
        # never silently.
        if payload.get("model_flagged"):
            nested = dict(nested)
            nested["model_flagged"] = True
            nested["model_flag_reason"] = payload.get(
                "model_flag_reason", ""
            )
        return nested
    return None


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="frankenpaxos_tpu.monitoring.dashboard"
    )
    parser.add_argument(
        "path",
        help="metrics.csv, a benchmark directory, or a telemetry JSON "
        "capture (tpu/telemetry.py to_dict / bench.py --telemetry)",
    )
    parser.add_argument("-o", "--output", default=None)
    parser.add_argument(
        "--live",
        action="store_true",
        help="tail the scrape CSV of a still-running serve loop, "
        "re-rendering as it grows (instead of one post-hoc render)",
    )
    parser.add_argument(
        "--fleet",
        action="store_true",
        help="render the FLEET view: instance x time heatmaps "
        "(commit rate, p99, shed) + the straggler lane from a "
        "FleetServeLoop scrape CSV (legacy single-instance captures "
        "render as a one-row fleet)",
    )
    parser.add_argument(
        "--roofline",
        action="store_true",
        help="render the cost-model roofline view (per-plane "
        "efficiency lanes vs the model envelope + traffic-vs-"
        "throughput scatter) from a costmodel_envelope.json payload "
        "(microbench costmodel, FPX_WRITE_ENVELOPE=1)",
    )
    parser.add_argument(
        "--interval", type=float, default=1.0,
        help="--live poll interval (seconds)",
    )
    parser.add_argument(
        "--max-seconds", type=float, default=30.0,
        help="--live wall-clock bound",
    )
    args = parser.parse_args()

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.csv")
    output = args.output or os.path.join(
        os.path.dirname(os.path.abspath(path)), "dashboard.png"
    )
    if args.roofline:
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"cannot read envelope payload: {e}", file=sys.stderr)
            sys.exit(1)
        result = render_roofline(payload, output)
        if result is None:
            print("no capture verdicts in payload", file=sys.stderr)
            sys.exit(1)
        print(result)
        return
    if args.fleet:
        result = render_fleet_dashboard(MetricsCapture(path), output)
        if result is None:
            print("no fleet metrics in capture", file=sys.stderr)
            sys.exit(1)
        print(result)
        return
    if args.live:
        renders = tail_live(
            path, output, interval_s=args.interval,
            max_seconds=args.max_seconds,
        )
        if renders == 0:
            print("no plottable metrics in capture", file=sys.stderr)
            sys.exit(1)
        print(output)
        return
    telemetry = _load_telemetry_capture(path)
    if telemetry is not None:
        result = render_telemetry_dashboard(telemetry, output)
    else:
        result = render_dashboard(MetricsCapture(path), output)
    if result is None:
        print("no plottable metrics in capture", file=sys.stderr)
        sys.exit(1)
    print(result)


if __name__ == "__main__":
    sys.exit(main())
