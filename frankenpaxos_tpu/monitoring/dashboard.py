"""Dashboard rendering from a metrics capture (the analog of the
reference's 15 Grafana dashboards, ``grafana/dashboards/*.json``): one
command turns a benchmark's ``metrics.csv`` into a multi-panel figure of
per-role request rates and handler latencies.

    python -m frankenpaxos_tpu.monitoring.dashboard <bench_dir_or_csv> \\
        [-o dashboard.png]
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional

from frankenpaxos_tpu.monitoring.scrape import MetricsCapture


def render_dashboard(
    capture: MetricsCapture,
    output: str,
    window_ms: float = 1000.0,
) -> Optional[str]:
    """One panel per *_requests_total metric (rate per series) plus one
    per *_handler_latency_seconds (mean latency per series). Returns the
    output path, or None if the capture holds no plottable metrics."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    names = set(capture.names())
    panels = []
    for name in sorted(names):
        if name.endswith("_requests_total"):
            panels.append(("rate", name))
    for count_name in sorted(names):
        if not count_name.endswith("_handler_latency_seconds_count"):
            continue
        base = count_name[: -len("_count")]
        if f"{base}_sum" in names:
            panels.append(("latency", base))
    if not panels:
        return None

    fig, axes = plt.subplots(
        len(panels), 1, figsize=(9, 3 * len(panels)), squeeze=False
    )
    for ax_row, (kind, name) in zip(axes, panels):
        ax = ax_row[0]
        if kind == "rate":
            wide = capture.rate(name, window_ms=window_ms)
            title = f"{name} (rate/s, {int(window_ms)}ms windows)"
        else:
            # Mean handler latency = d(sum)/d(count) over the window.
            total = capture.query(f"{name}_sum")
            count = capture.query(f"{name}_count")
            wide = (
                total.ffill().diff().sum(axis=1)
                / count.ffill().diff().sum(axis=1).replace(0, float("nan"))
            ).to_frame("mean_s") * 1000.0
            title = f"{name} (mean ms between scrapes)"
        for col in wide.columns:
            series = wide[col].dropna()
            # Aggregate labelled series lightly: plot each, thin legend.
            ax.plot(series.index, series.values, label=str(col)[:60])
        ax.set_title(title, fontsize=9)
        ax.grid(True)
        if 0 < len(wide.columns) <= 8:
            ax.legend(fontsize=6, loc="best")
    fig.tight_layout()
    fig.savefig(output)
    plt.close(fig)
    return output


def main() -> None:
    parser = argparse.ArgumentParser(
        prog="frankenpaxos_tpu.monitoring.dashboard"
    )
    parser.add_argument("path", help="metrics.csv or a benchmark directory")
    parser.add_argument("-o", "--output", default=None)
    args = parser.parse_args()

    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.csv")
    capture = MetricsCapture(path)
    output = args.output or os.path.join(
        os.path.dirname(os.path.abspath(path)), "dashboard.png"
    )
    result = render_dashboard(capture, output)
    if result is None:
        print("no plottable metrics in capture", file=sys.stderr)
        sys.exit(1)
    print(result)


if __name__ == "__main__":
    sys.exit(main())
