"""Perfetto-loadable Chrome trace export: device lifecycle spans and
host dispatch spans in ONE timeline.

The device half comes from the in-graph span sampler
(``tpu/telemetry.py`` ``record_spans`` / ``completed_spans``): each
completed span carries per-stage tick stamps (proposed /
phase1-promised / phase2-voted / committed / executed). The host half
comes from ``TpuSimTransport.trace()`` wall-clock spans (dispatch /
wait / transfer) — the same records the serve loop wraps in
``jax.profiler`` annotations so a concurrent profiler capture sees
them too.

Ticks are a device-side clock; wall time is the host's. The
:class:`TickClock` maps between them from (tick, unix-time) marks the
serve loop records at every chunk boundary (linear interpolation
inside a chunk, extrapolation from the nearest segment outside), so
both halves land on one microsecond timeline that Perfetto or
``chrome://tracing`` loads directly:

    python -m frankenpaxos_tpu.monitoring.dashboard ... (metrics)
    # trace: open ui.perfetto.dev -> "Open trace file" -> serve_trace.json

Format: the Chrome Trace Event JSON object form
(``{"traceEvents": [...], "displayTimeUnit": "ms"}``) — "X" complete
events for spans, "M" metadata events for process/thread names.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

DEVICE_PID = 1
HOST_PID = 2
# Fleet serving: one Perfetto track GROUP (process) per fleet
# instance, pids FLEET_PID0, FLEET_PID0+1, ... — the per-instance
# lanes that carry the control plane's alarm/clamp/clear instant
# markers (FleetServeLoop, harness/serve.py).
FLEET_PID0 = 100


class TickClock:
    """tick -> microsecond mapping from (tick, unix_seconds) marks."""

    def __init__(self, marks: Optional[Sequence[Tuple[int, float]]] = None):
        self.marks: List[Tuple[int, float]] = list(marks or [])

    def add_mark(self, tick: int, unix_s: float) -> None:
        self.marks.append((int(tick), float(unix_s)))

    def to_us(self, tick) -> float:
        """Interpolated wall-clock microseconds for a device tick.
        With fewer than two marks, ticks map 1 tick == 1 us from the
        single mark (or from zero) — still a valid relative timeline."""
        import numpy as np

        marks = sorted(set(self.marks))
        if len(marks) < 2:
            base_t, base_s = marks[0] if marks else (0, 0.0)
            return (float(tick) - base_t) + base_s * 1e6
        xs = np.asarray([m[0] for m in marks], np.float64)
        ys = np.asarray([m[1] for m in marks], np.float64) * 1e6
        t = float(tick)
        if t <= xs[0]:  # extrapolate from the first segment
            slope = (ys[1] - ys[0]) / max(xs[1] - xs[0], 1.0)
            return float(ys[0] + (t - xs[0]) * slope)
        if t >= xs[-1]:  # extrapolate from the last segment
            slope = (ys[-1] - ys[-2]) / max(xs[-1] - xs[-2], 1.0)
            return float(ys[-1] + (t - xs[-1]) * slope)
        return float(np.interp(t, xs, ys))


def metadata_events(
    device_name: str = "device (ticks)",
    host_name: str = "host (transport)",
) -> List[dict]:
    return [
        {
            "ph": "M",
            "pid": DEVICE_PID,
            "name": "process_name",
            "args": {"name": device_name},
        },
        {
            "ph": "M",
            "pid": HOST_PID,
            "name": "process_name",
            "args": {"name": host_name},
        },
    ]


def device_span_events(
    spans: Sequence[Dict],
    clock: Optional[TickClock] = None,
) -> List[dict]:
    """Chrome events for completed device spans (the dict rows
    ``telemetry.completed_spans`` / ``DrainCursor.drain()["spans"]``
    return). Each span becomes one whole-lifecycle "X" slice on the
    track of its group (tid = group) plus nested stage slices for the
    stamped stages; unstamped stages (-1) are skipped."""
    clock = clock or TickClock()
    events: List[dict] = []
    for s in spans:
        proposed = s.get("proposed", -1)
        executed = s.get("executed", -1)
        if proposed < 0 or executed < proposed:
            continue  # incomplete row (ring overwrite mid-drain)
        tid = int(s.get("group", 0))
        ts = clock.to_us(proposed)
        dur = max(clock.to_us(executed) - ts, 1.0)
        args = {k: int(v) for k, v in s.items()}
        events.append(
            {
                "name": f"slot g{s.get('group', 0)}/{s.get('slot_id', 0)}",
                "cat": "lifecycle",
                "ph": "X",
                "pid": DEVICE_PID,
                "tid": tid,
                "ts": ts,
                "dur": dur,
                "args": args,
            }
        )
        # Nested stage slices: [proposed -> voted -> committed ->
        # executed], with the optional phase-1 repair as its own slice.
        stages = []
        voted = s.get("phase2_voted", -1)
        committed = s.get("committed", -1)
        if voted >= 0:
            stages.append(("phase2_vote", proposed, voted))
        if committed >= 0:
            stages.append(
                ("commit", voted if voted >= 0 else proposed, committed)
            )
            stages.append(("execute", committed, executed))
        p1 = s.get("phase1_promised", -1)
        if p1 >= 0:
            stages.append(("phase1_repair", p1, min(p1 + 1, executed)))
        for name, t0, t1 in stages:
            if t1 < t0:
                continue
            u0 = clock.to_us(t0)
            events.append(
                {
                    "name": name,
                    "cat": "stage",
                    "ph": "X",
                    "pid": DEVICE_PID,
                    "tid": tid,
                    "ts": u0,
                    "dur": max(clock.to_us(t1) - u0, 1.0),
                }
            )
    return events


def fleet_metadata_events(n: int) -> List[dict]:
    """Process-name metadata for ``n`` per-instance track groups
    (pid = FLEET_PID0 + i) — Perfetto renders each fleet instance as
    its own collapsible group."""
    return [
        {
            "ph": "M",
            "pid": FLEET_PID0 + i,
            "name": "process_name",
            "args": {"name": f"fleet instance {i}"},
        }
        for i in range(n)
    ]


def fleet_marker_events(
    markers: Sequence[Dict],
    clock: Optional[TickClock] = None,
) -> List[dict]:
    """Instant events for the fleet control plane's per-instance
    marks (``FleetServeLoop.markers``: dicts with ``instance``,
    ``tick``, ``kind`` in {alarm, clamp, clear, scale_up, scale_down}
    + extras). Each lands on its instance's track group, thread-
    scoped, at the tick's interpolated wall clock; FLEET-WIDE marks
    (``instance`` < 0 — the elastic set_active_instances capacity
    events) land on the host control track instead."""
    clock = clock or TickClock()
    events: List[dict] = []
    for m in markers:
        args = {
            k: v
            for k, v in m.items()
            if k not in ("instance", "tick", "kind")
        }
        instance = int(m["instance"])
        events.append(
            {
                "name": str(m["kind"]),
                "cat": "fleet-control",
                "ph": "i",
                "s": "t",
                "pid": (
                    FLEET_PID0 + instance if instance >= 0 else HOST_PID
                ),
                "tid": 0,
                "ts": clock.to_us(int(m["tick"])),
                "args": args,
            }
        )
    return events


def host_span_events(trace_spans: Sequence[Dict]) -> List[dict]:
    """Chrome events for host-side wall-clock spans (the dict records
    ``TpuSimTransport.trace()`` returns: name/start_unix/duration_s +
    metadata)."""
    events: List[dict] = []
    for s in trace_spans:
        args = {
            k: v
            for k, v in s.items()
            if k not in ("name", "start_unix", "duration_s", "instant")
        }
        if s.get("instant"):
            # Marker spans (e.g. the serve loop's crash-recovery
            # "restore" record) render as global instant events — a
            # vertical restart marker across the whole timeline.
            events.append(
                {
                    "name": str(s["name"]),
                    "cat": "marker",
                    "ph": "i",
                    "s": "g",
                    "pid": HOST_PID,
                    "tid": 0,
                    "ts": float(s["start_unix"]) * 1e6,
                    "args": args,
                }
            )
            continue
        events.append(
            {
                "name": str(s["name"]),
                "cat": "host",
                "ph": "X",
                "pid": HOST_PID,
                "tid": 0,
                "ts": float(s["start_unix"]) * 1e6,
                "dur": max(float(s["duration_s"]) * 1e6, 1.0),
                "args": args,
            }
        )
    return events


def write_chrome_trace(
    path: str,
    device_spans: Sequence[Dict] = (),
    host_spans: Sequence[Dict] = (),
    clock: Optional[TickClock] = None,
    extra_events: Sequence[Dict] = (),
) -> str:
    """Assemble + write one Perfetto-loadable trace file; returns the
    path. Either half may be empty (a device-only or host-only
    capture is still loadable)."""
    events = (
        metadata_events()
        + device_span_events(device_spans, clock)
        + host_span_events(host_spans)
        + list(extra_events)
    )
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def load_chrome_trace(path: str) -> dict:
    """Load + structurally validate a trace file written by
    :func:`write_chrome_trace` (used by the serve smoke + tests):
    asserts the object form, that every event carries the required
    keys, and that "X" events have nonnegative durations."""
    with open(path) as f:
        payload = json.load(f)
    assert isinstance(payload, dict) and "traceEvents" in payload
    for ev in payload["traceEvents"]:
        assert "ph" in ev and "pid" in ev and "name" in ev
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and "ts" in ev and "tid" in ev
    return payload
