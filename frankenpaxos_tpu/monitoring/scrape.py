"""Per-benchmark metrics capture and post-hoc querying (the analog of
``benchmarks/prometheus.py``: scrape-config generation, a per-benchmark
metrics store, and PromQL-into-pandas queries).

The reference launches a real Prometheus server per benchmark and later
re-launches one over the captured tsdb to run PromQL
(``prometheus.py:10-135``). The re-design keeps the capability without
the external binary: a ``MetricsScraper`` thread polls each role's
``/metrics`` endpoint (the text exposition format served by
``PrometheusCollectors``) on an interval and appends samples to a CSV;
``MetricsCapture`` loads the CSV into pandas and answers the queries the
analysis layer needs — instant vectors, range series per labelset, and
counter rates (``analysis.rate`` is the PromQL ``rate()`` analog).
"""

from __future__ import annotations

import csv
import re
import threading
import time
import urllib.request
from typing import Dict, Iterable, List, Optional, Tuple

# CSV schema: version 1 was ``ts,job,name,labels,value`` (no instance
# column); version 2 — every writer below — is
# ``ts,job,instance,name,labels,value``. Readers (``MetricsCapture``)
# accept BOTH: a v1 capture parses with every sample on instance 0, so
# old single-instance captures keep rendering (``dashboard --live``)
# while fleet captures carry one instance per fleet row.
CSV_SCHEMA_VERSION = 2
CSV_COLUMNS = ["ts", "job", "instance", "name", "labels", "value"]


def instance_index(value) -> int:
    """The FLEET instance index of a CSV ``instance`` cell: numeric
    strings are fleet rows; legacy single-instance names ("serve",
    "sim", a host:port target, a missing v1 column) all map to
    instance 0 — the backward-compat rule the fleet dashboard and the
    round-trip test pin."""
    try:
        return int(str(value).strip())
    except (TypeError, ValueError):
        return 0


def scrape_config(scrape_interval_ms: int, jobs: Dict[str, List[str]]) -> dict:
    """A prometheus.yml-shaped dict (prometheus.py:10-25), kept for config
    parity: jobs maps job names to host:port targets."""
    return {
        "global": {"scrape_interval": f"{scrape_interval_ms}ms"},
        "scrape_configs": [
            {
                "job_name": job,
                "static_configs": [{"targets": targets}],
            }
            for job, targets in jobs.items()
        ],
    }


def parse_exposition(text: str) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
    """Parse the Prometheus text exposition format into
    ``(name, sorted label pairs, value)`` samples."""
    samples = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            metric, value_str = line.rsplit(None, 1)
            value = float(value_str)
        except ValueError:
            continue
        if "{" in metric:
            name, rest = metric.split("{", 1)
            label_str = rest.rsplit("}", 1)[0]
            labels = []
            for pair in filter(None, label_str.split(",")):
                k, v = pair.split("=", 1)
                labels.append((k.strip(), v.strip().strip('"')))
            samples.append((name, tuple(sorted(labels)), value))
        else:
            samples.append((metric, (), value))
    return samples


def append_device_samples(
    csv_path: str,
    telemetry,
    job: str = "device",
    instance: str = "sim",
    ts: Optional[float] = None,
) -> int:
    """Append one scrape of a device-side Telemetry ring
    (``tpu/telemetry.py``) to a scraper CSV, unifying device metrics
    with the host scraper's schema (``ts,job,instance,name,labels,
    value``) so ``MetricsCapture`` / the dashboard query both under the
    one ``fpx_*`` naming scheme. Accepts a live/fetched Telemetry (its
    exposition lines are rendered here) and returns the number of
    samples appended. Creates the file with a header when absent."""
    import os

    from frankenpaxos_tpu.tpu import telemetry as telemetry_mod

    text = "\n".join(telemetry_mod.exposition_lines(telemetry))
    samples = parse_exposition(text)
    ts = time.time() if ts is None else ts
    new_file = not os.path.exists(csv_path)
    with open(csv_path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(
                ["ts", "job", "instance", "name", "labels", "value"]
            )
        for name, labels, value in samples:
            label_str = ";".join(f"{k}={v}" for k, v in labels)
            writer.writerow([ts, job, instance, name, label_str, value])
    return len(samples)


def append_host_spans(
    csv_path: str,
    spans: List[dict],
    job: str = "host",
    instance: str = "transport",
) -> int:
    """Append a transport's host-side trace spans (``TpuSimTransport.
    trace()``) to the same scraper CSV as ``fpx_host_span_seconds``
    samples (labels: span name + compile flag), stamped with each
    span's own wall clock — the host half of the unified scheme."""
    import os

    new_file = not os.path.exists(csv_path)
    n = 0
    with open(csv_path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(
                ["ts", "job", "instance", "name", "labels", "value"]
            )
        for span in spans:
            labels = f"span={span['name']}"
            if span.get("compile"):
                labels += ";compile=true"
            writer.writerow(
                [
                    span["start_unix"],
                    job,
                    instance,
                    "fpx_host_span_seconds",
                    labels,
                    span["duration_s"],
                ]
            )
            n += 1
    return n


def append_capacity_events(
    csv_path: str,
    events: List[dict],
    job: str = "autoscaler",
    instance: str = "serve",
) -> int:
    """Append elastic-capacity ladder events (``monitoring/autoscaler
    .py`` ``Autoscaler.events``: scale_up / scale_down / clamp_engage /
    clamp_release dicts) as ``fpx_capacity_event`` samples — one row
    per event, value 1, labels carrying the rung, role, and the
    from/to counts, so a capture queries the ladder's history the same
    way it queries any other counter."""
    import os

    new_file = not os.path.exists(csv_path)
    n = 0
    with open(csv_path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(
                ["ts", "job", "instance", "name", "labels", "value"]
            )
        for ev in events:
            labels = f"kind={ev['kind']}"
            if "role" in ev:
                labels += (
                    f";role={ev['role']};from={ev['frm']};to={ev['to']}"
                )
            writer.writerow(
                [time.time(), job, instance, "fpx_capacity_event",
                 labels, 1]
            )
            n += 1
    return n


# Efficiency gauges: measured-vs-model commit throughput, the serve
# loop's MFU analog. One row each per drain, labels carrying the
# parameter-set name so a capture replays against the exact model
# that judged it. Values follow the fleet x1000 fixed-point
# convention (the value column stays integer-friendly and the
# dashboard divides back out).
EFFICIENCY_METRICS = (
    "fpx_efficiency_observed_commits_per_tick_x1000",
    "fpx_efficiency_predicted_commits_per_tick_x1000",
    "fpx_efficiency_ratio_x1000",
)


def append_efficiency_samples(
    csv_path: str,
    *,
    observed_per_tick: float,
    predicted_per_tick: float,
    params: str,
    job: str = "device",
    instance: str = "serve",
    ts: Optional[float] = None,
) -> int:
    """Append one drain's efficiency gauges (observed and
    model-predicted commits/tick plus their ratio, x1000) to the
    scraper CSV under schema v2 — same ``instance`` semantics as
    ``append_device_samples`` (per-serve-loop name, or the fleet row
    index). Returns rows appended."""
    import os

    ts = time.time() if ts is None else ts
    ratio = (
        observed_per_tick / predicted_per_tick
        if predicted_per_tick > 0
        else 0.0
    )
    values = (observed_per_tick, predicted_per_tick, ratio)
    new_file = not os.path.exists(csv_path)
    with open(csv_path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(CSV_COLUMNS)
        for metric, value in zip(EFFICIENCY_METRICS, values):
            writer.writerow([
                ts, job, instance, metric,
                f"params={params}", int(round(value * 1000)),
            ])
    return len(EFFICIENCY_METRICS)


# The per-instance summary metrics a FLEET serve loop appends each
# drain (telemetry.fleet_summary columns worth exposing): the
# instance x time matrices ``dashboard --fleet`` renders as heatmaps,
# plus the straggler lane and the per-instance admission scale.
FLEET_SUMMARY_METRICS = {
    "commit_rate_x1000": "fpx_fleet_commit_rate_x1000",
    "p50_commit_latency": "fpx_fleet_p50_commit_latency_ticks",
    "p99_commit_latency": "fpx_fleet_p99_commit_latency_ticks",
    "p50_queue_wait": "fpx_fleet_queue_wait_p50_ticks",
    "p99_queue_wait": "fpx_fleet_queue_wait_p99_ticks",
    "shed": "fpx_fleet_shed_total",
    "rotations": "fpx_fleet_rotations",
    "straggler": "fpx_fleet_straggler",
}


def append_fleet_summary(
    csv_path: str,
    summary_rows: List[dict],
    job: str = "fleet",
    ts: Optional[float] = None,
    scales: Optional[List[float]] = None,
) -> int:
    """Append one fleet drain's per-instance summary vectors
    (``telemetry.summary_row_dict`` dicts, one per instance) to the
    scraper CSV — instance column = the fleet row index, so the
    ``--fleet`` dashboard pivots instance x time directly. ``scales``
    optionally adds the per-instance admission scale
    (``fpx_fleet_admission_scale``, x1000). Returns rows appended."""
    import os

    ts = time.time() if ts is None else ts
    new_file = not os.path.exists(csv_path)
    n = 0
    with open(csv_path, "a", newline="") as f:
        writer = csv.writer(f)
        if new_file:
            writer.writerow(CSV_COLUMNS)
        for i, row in enumerate(summary_rows):
            for col, metric in FLEET_SUMMARY_METRICS.items():
                writer.writerow(
                    [ts, job, str(i), metric, "", row[col]]
                )
                n += 1
            if scales is not None:
                writer.writerow([
                    ts, job, str(i), "fpx_fleet_admission_scale",
                    "", int(round(scales[i] * 1000)),
                ])
                n += 1
    return n


class MetricsScraper:
    """Polls each job's targets and appends samples to a CSV with columns
    ``ts,job,instance,name,labels,value`` (labels as ``k=v;k=v``)."""

    def __init__(
        self,
        jobs: Dict[str, List[str]],
        output_path: str,
        scrape_interval_ms: int = 200,
        timeout_s: float = 1.0,
    ):
        self.jobs = jobs
        self.output_path = output_path
        self.interval_s = scrape_interval_ms / 1000.0
        self.timeout_s = timeout_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "MetricsScraper":
        self._file = open(self.output_path, "w", newline="")
        self._writer = csv.writer(self._file)
        self._writer.writerow(["ts", "job", "instance", "name", "labels", "value"])
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # Wait for the worker to actually exit before touching the
            # shared csv writer or closing the file: a sweep over many
            # hung targets can outlast any single join timeout.
            while self._thread.is_alive():
                self._thread.join(timeout=5.0)
            self._thread = None
            self._scrape_once()  # one final sample after the run
            self._file.close()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._scrape_once()
            self._stop.wait(self.interval_s)

    def _scrape_once(self) -> None:
        now = time.time()
        rows = []
        for job, targets in self.jobs.items():
            for target in targets:
                try:
                    with urllib.request.urlopen(
                        f"http://{target}/metrics", timeout=self.timeout_s
                    ) as resp:
                        text = resp.read().decode()
                except OSError:
                    continue  # role not up yet / already gone
                for name, labels, value in parse_exposition(text):
                    label_str = ";".join(f"{k}={v}" for k, v in labels)
                    rows.append([now, job, target, name, label_str, value])
        self._writer.writerows(rows)
        self._file.flush()


class MetricsCapture:
    """Post-hoc queries over a scraper CSV, into pandas (the
    PrometheusQueryer analog, prometheus.py:28-135)."""

    def __init__(self, path: str):
        import pandas as pd

        self.df = pd.read_csv(path, header=0)
        # Schema-version shim (CSV_SCHEMA_VERSION): a v1 capture has no
        # ``instance`` column — parse it as instance 0 so old
        # single-instance captures keep answering every query and
        # ``dashboard --live`` unchanged (round-trip-pinned by
        # tests/test_metrics_capture.py).
        if "instance" not in self.df.columns:
            self.df["instance"] = "0"
        # Fleet captures carry NUMERIC instance cells (the fleet row
        # index) which pandas infers as int64 — normalize to str so
        # query()'s series labels concatenate for every schema.
        self.df["instance"] = self.df["instance"].astype(str)
        if len(self.df):
            self.df["ts"] = pd.to_datetime(self.df["ts"], unit="s")

    def names(self) -> List[str]:
        return sorted(self.df["name"].unique())

    def query(self, name: str, **label_filters: str):
        """Range series for one metric: a DataFrame indexed by scrape
        time with one column per (instance, labelset)."""
        import pandas as pd

        df = self.df[self.df["name"] == name]
        if label_filters:
            for k, v in label_filters.items():
                # Anchored per-label match: 'type=ClientRequest' must not
                # also match 'type=ClientRequestBatch'.
                pattern = f"(?:^|;){re.escape(k)}={re.escape(str(v))}(?:;|$)"
                df = df[df["labels"].fillna("").str.contains(pattern)]
        if not len(df):
            return pd.DataFrame()
        df = df.copy()
        df["series"] = df["instance"] + "{" + df["labels"].fillna("") + "}"
        return df.pivot_table(
            index="ts", columns="series", values="value", aggfunc="last"
        )

    def rate(self, name: str, window_ms: float = 1000.0, **label_filters):
        """Counter rate per series (PromQL ``rate()``), via the analysis
        layer's rolling-window derivative."""
        from frankenpaxos_tpu.harness.analysis import rate as _rate

        wide = self.query(name, **label_filters)
        return wide.apply(lambda col: _rate(col.dropna(), window_ms))

    def total(self, name: str, **label_filters) -> float:
        """Sum of each series' final sample (e.g. total requests)."""
        wide = self.query(name, **label_filters)
        if not len(wide):
            return 0.0
        return float(wide.ffill().iloc[-1].sum())
