"""Prometheus-shaped metrics facade.

Mirrors the reference ``monitoring`` package
(``shared/src/main/scala/frankenpaxos/monitoring/Collectors.scala:6-15``):
a ``Collectors`` interface providing Counter/Gauge/Summary builders, with a
real Prometheus-style implementation for deployments and a no-op/fake for
simulation and tests. Dependency-free: we keep our own registry and emit
Prometheus text exposition format, served by a tiny HTTP exporter thread
(the analog of ``jvm/.../PrometheusUtil.scala:6-15``).
"""

from __future__ import annotations

import http.server
import math
import threading
from typing import Dict, List, Optional, Tuple


class _Metric:
    def __init__(self, name: str, help: str, label_names: Tuple[str, ...]):
        self.name = name
        self.help = help
        self.label_names = label_names
        self._children: Dict[Tuple[str, ...], "_Metric"] = {}

    def labels(self, *values: str) -> "_Metric":
        values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected {len(self.label_names)} labels, got {values}"
            )
        child = self._children.get(values)
        if child is None:
            child = type(self)(self.name, self.help, ())
            self._children[values] = child
        return child

    def _label_str(self, values: Tuple[str, ...]) -> str:
        if not values:
            return ""
        pairs = ",".join(
            f'{k}="{v}"' for k, v in zip(self.label_names, values)
        )
        return "{" + pairs + "}"


class Counter(_Metric):
    def __init__(self, name: str, help: str, label_names: Tuple[str, ...] = ()):
        super().__init__(name, help, label_names)
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def get(self) -> float:
        return self.value

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        if self._children:
            for values, child in self._children.items():
                lines.append(f"{self.name}{self._label_str(values)} {child.value}")
        else:
            lines.append(f"{self.name} {self.value}")
        return lines


class Gauge(_Metric):
    def __init__(self, name: str, help: str, label_names: Tuple[str, ...] = ()):
        super().__init__(name, help, label_names)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount

    def get(self) -> float:
        return self.value

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        if self._children:
            for values, child in self._children.items():
                lines.append(f"{self.name}{self._label_str(values)} {child.value}")
        else:
            lines.append(f"{self.name} {self.value}")
        return lines


class Summary(_Metric):
    """Count/sum summary with streaming reservoir-free quantile estimates
    (p50/p90/p99 over a bounded ring of recent observations)."""

    RING = 4096

    def __init__(self, name: str, help: str, label_names: Tuple[str, ...] = ()):
        super().__init__(name, help, label_names)
        self.count = 0
        self.sum = 0.0
        self._ring: List[float] = []

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        if len(self._ring) < self.RING:
            self._ring.append(v)
        else:
            self._ring[self.count % self.RING] = v

    def quantile(self, q: float) -> float:
        if not self._ring:
            return math.nan
        s = sorted(self._ring)
        return s[min(len(s) - 1, int(q * len(s)))]

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} summary"]
        targets = self._children.items() if self._children else [((), self)]
        for values, child in targets:
            base = self._label_str(values)
            lines.append(f"{self.name}_count{base} {child.count}")
            lines.append(f"{self.name}_sum{base} {child.sum}")
        return lines


class Collectors:
    """Factory + registry for metrics (Collectors.scala:6-15). Use
    ``PrometheusCollectors`` in deployments and ``FakeCollectors`` in
    sims/tests; both share this implementation — Fake simply never exposes."""

    def __init__(self) -> None:
        self._metrics: Dict[str, _Metric] = {}

    def counter(self, name: str, help: str = "", labels: Tuple[str, ...] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Tuple[str, ...] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def summary(self, name: str, help: str = "", labels: Tuple[str, ...] = ()) -> Summary:
        return self._get_or_create(Summary, name, help, labels)

    def _get_or_create(self, cls, name, help, labels):
        m = self._metrics.get(name)
        if m is None:
            m = cls(name, help, tuple(labels))
            self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name} re-registered as different type")
        return m

    def expose_text(self) -> str:
        lines: List[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class FakeCollectors(Collectors):
    """No-op-exposure collectors for tests/sims (FakeCollectors.scala); the
    metrics still record values so tests can assert on them."""


class PrometheusCollectors(Collectors):
    """Collectors with an HTTP /metrics exporter
    (PrometheusUtil.scala:6-15). ``port=-1`` disables the server."""

    def start_http_server(self, port: int, host: str = "0.0.0.0"):
        if port == -1:
            return None
        collectors = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                body = collectors.expose_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence
                pass

        server = http.server.ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        return server
