"""Shared grid/blocking helpers for the fused kernels.

Every plane grids over ONE batch axis (groups / leaders / chains). The
helpers keep the blocking discipline uniform across kernels:
``balanced_block`` picks bg = ceil(N / nblocks) for the smallest block
count with bg <= requested block, bounding padding waste by one block's
remainder (min(block, N) would pad N=257 up to 512); ``pad_axis`` pads
the batch axis up to a block multiple (padded rows compute garbage that
the wrapper slices off — no cross-row dataflow exists in any plane).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

# Weakly-typed mirror of common.INF for use INSIDE kernel bodies (the
# jnp scalar would be a captured constant, which pallas_call rejects).
INF_I = 2**30


def balanced_block(n: int, block: int) -> Tuple[int, int]:
    """Returns ``(bg, pad)``: the balanced block size and the padding
    needed to make the axis a block multiple."""
    block = max(1, min(block, n))
    nblocks = -(-n // block)
    bg = -(-n // nblocks)
    return bg, (-n) % bg


def pad_axis(x: jnp.ndarray, axis: int, pad: int) -> jnp.ndarray:
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def t_arr(t) -> jnp.ndarray:
    """The tick counter as the (1,)-shaped SMEM operand kernels take."""
    return jnp.asarray(t, jnp.int32).reshape((1,))


def t_space(interpret: bool):
    """Memory space for the tick-counter operand: SMEM on the compiled
    TPU path; interpret mode accepts the same spec with ``None``."""
    if interpret:
        return None
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.SMEM
