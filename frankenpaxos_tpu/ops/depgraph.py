"""Bounded-window dependency-graph execution as one XLA-native plane.

The reference executes committed commands through a dependency graph by
POINTER CHASING: ``depgraph/TarjanDependencyGraph.scala`` walks
vertices one at a time, pushes Tarjan frames, pops strongly-connected
components, and appends them in reverse topological order. That shape
is hostile to an accelerator — serial, branchy, allocation-heavy — and
it is why ``tpu/epaxos_batched.py`` only ever supported FACTORED
dependency vectors (per-column watermarks): arbitrary dependency sets
and SCC cycles had no device-side path at all.

``depgraph_execute`` is that path. The per-replica graph over a bounded
instance window of ``V`` vertices is a ``[V, ceil(V/32)]`` uint32
adjacency bitmask (bit ``j`` of row ``i`` = instance ``i`` depends on
instance ``j``; vertex -> word ``j // 32``, lane ``j % 32`` — the same
little-endian packing as ``tpu/packing.py``). One call computes, for a
batch of ``B`` graphs at once:

  * **transitive closure** by iterated masked AND/OR matrix squaring:
    ``R <- R | R@R`` on the active subgraph, ``ceil(log2(V))`` times
    (log-depth doubling — no pointer chasing, every step one
    MXU-shaped 0/1 matmul);
  * **eligibility**: a vertex executes iff every vertex in its closure
    is committed — exactly the ELIGIBLE set of
    ``DependencyGraph.scala``, cycles included (an SCC's members share
    a closure, so they become eligible together);
  * **SCC condensation**: ``scc_root`` = the smallest vertex id
    mutually reachable with each vertex (``R & R^T``) — members of a
    component agree on the root, which is how consumers count
    co-executed components without a Tarjan stack;
  * **deterministic batch order**: eligible vertices are ranked by
    ``(closure size, vertex id)``. A dependency's closure is a strict
    subset of its dependents' closures, so dependencies always rank
    first; SCC members (equal closures) order by id. The rank is a
    dense ``order`` permutation — the execution schedule.

Eligible-set closure property (what makes the order safe): if ``v`` is
eligible, every vertex in ``closure(v)`` is also eligible — its own
closure is a subset, so the all-committed test it passed is inherited.

All arithmetic is exact: the 0/1 closure matmuls run in float32 (counts
bounded by ``V <= 2**24``), every comparison is integral, so the Pallas
kernel and the pure-jnp reference are bit-identical by construction
(pinned 3-seed in ``tests/test_kernel_registry.py``).

The module also owns every helper that touches packed adjacency words —
the ``depgraph-containment`` analysis rule keeps bitmask twiddling on
``.adj`` planes inside this file, exactly like ``packing-containment``
does for ``tpu/packing.py``:

  * :func:`pack_mask` / :func:`clear_vertices` / :func:`rows_subset` —
    build, retire (row AND column clears — a freed ring slot must not
    leave stale dependency bits pointing at its future tenant), and
    audit adjacency rows;
  * :func:`bernoulli_words_k16` — the bit-sliced Bernoulli sampler of
    ``epaxos_batched`` generalized to a TRACED ``k/16`` rate, so the
    workload engine's ``conflict_rate`` knob sweeps conflict density
    without retracing (one compile for the whole [conflict x load]
    surface);
  * :func:`oracle_execute` — the host-side sequential pointer-walk
    twin (iterative Tarjan + condensation reach sets), the equivalence
    oracle for tests AND the baseline the ``depgraph`` microbench
    times the batched closure against.

Consumers: ``tpu/bpaxos_batched.py`` (the Bipartisan Paxos backend —
leaderless proposers whose consensus-chosen dependency sets form
exactly these graphs) and ``tpu/epaxos_batched.py`` under
``general_deps=True`` (factored snapshots materialized as adjacency
rows and executed through the same plane).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import balanced_block, pad_axis

_LANES = 32


def num_words(n: int) -> int:
    """Packed uint32 words covering ``n`` vertices."""
    return -(-n // _LANES)


# ---------------------------------------------------------------------------
# Packed-word helpers (the only legal home for .adj bit twiddling)
# ---------------------------------------------------------------------------


def pack_mask(b: jnp.ndarray) -> jnp.ndarray:
    """[..., n] bool -> [..., ceil(n/32)] uint32 (vertex v -> word
    v // 32, lane v % 32)."""
    n = b.shape[-1]
    nw = num_words(n)
    pad = nw * _LANES - n
    if pad:
        b = jnp.concatenate(
            [b, jnp.zeros(b.shape[:-1] + (pad,), bool)], axis=-1
        )
    lanes = jnp.uint32(1) << jnp.arange(_LANES, dtype=jnp.uint32)
    words = b.reshape(b.shape[:-1] + (nw, _LANES))
    return jnp.sum(words.astype(jnp.uint32) * lanes, axis=-1)


def unpack_mask(words: jnp.ndarray, n: int) -> jnp.ndarray:
    """[..., ceil(n/32)] uint32 -> [..., n] bool — pack_mask's inverse
    (consumers turn packed visibility words back into per-vertex flags
    without doing their own lane arithmetic)."""
    vw = words.shape[-1]
    assert vw * _LANES >= n
    bits = _unpack_bits(words[..., None, :])[..., 0, :n]
    return bits.astype(bool)


def clear_vertices(adj: jnp.ndarray, vmask: jnp.ndarray) -> jnp.ndarray:
    """Retire vertices from a graph: zero their ROWS (the retired
    instance's own dependencies) and their COLUMNS (every other row's
    edges onto them). The column clear is what makes ring-slot reuse
    safe — a stale bit would otherwise point at the slot's next tenant
    and fabricate a dependency on a future instance.

    ``adj``: [..., V, VW] uint32; ``vmask``: [..., V] bool."""
    words = pack_mask(vmask)  # [..., VW]
    rows_cleared = jnp.where(vmask[..., :, None], jnp.uint32(0), adj)
    return rows_cleared & ~words[..., None, :]


def rows_subset(adj: jnp.ndarray, allowed: jnp.ndarray) -> jnp.ndarray:
    """[..., V] bool: every dependency bit of each row lies inside the
    ``allowed`` packed word mask ([..., VW]) — the dep-graph safety
    audit (an executed instance's deps must all be executed or
    retired)."""
    return jnp.all(
        (adj & ~allowed[..., None, :]) == jnp.uint32(0), axis=-1
    )


def bernoulli_words_k16(
    key: jnp.ndarray, k16: jnp.ndarray, shape: Tuple[int, ...]
) -> jnp.ndarray:
    """Per-BIT Bernoulli(k16/16) over packed uint32 words, with a
    TRACED rate: ``k16`` is an int32 scalar in [0, 16] (the workload
    engine's conflict knob quantized to 16ths). A bit-sliced 4-bit
    comparator — each of 4 random planes is one bit of a per-lane
    4-bit value; the lane sets iff value < k16 — so one sweep of 4
    words replaces 32 uniform draws, and the data-dependent rate costs
    four selects instead of a retrace (``epaxos_batched`` keeps the
    static-rate variant; this one rides ``WorkloadState``)."""
    k16 = jnp.asarray(k16, jnp.int32)
    planes = jax.random.bits(key, (4,) + tuple(shape))  # uint32
    lt = jnp.zeros(shape, jnp.uint32)
    eq = jnp.full(shape, 0xFFFFFFFF, jnp.uint32)
    for i in (3, 2, 1, 0):  # MSB -> LSB of the 4-bit value
        b = planes[i]
        take = ((k16 >> i) & 1) == 1
        lt = jnp.where(take, lt | (eq & ~b), lt)
        eq = jnp.where(take, eq & b, eq & ~b)
    full = jnp.full(shape, 0xFFFFFFFF, jnp.uint32)
    return jnp.where(k16 >= 16, full, lt)


# ---------------------------------------------------------------------------
# The execute pass (shared math: reference and kernel trace this code)
# ---------------------------------------------------------------------------


def _unpack_bits(adj: jnp.ndarray) -> jnp.ndarray:
    """[..., V, VW] uint32 -> [..., V, VW*32] int32 0/1 bits."""
    vw = adj.shape[-1]
    shifts = jax.lax.broadcasted_iota(jnp.uint32, (vw, _LANES), 1)
    bits = (adj[..., :, :, None] >> shifts) & jnp.uint32(1)
    return bits.reshape(adj.shape[:-1] + (vw * _LANES,)).astype(jnp.int32)


def _pad_tail(x: jnp.ndarray, axis: int, pad: int) -> jnp.ndarray:
    if not pad:
        return x
    shape = list(x.shape)
    shape[axis] = pad
    return jnp.concatenate(
        [x, jnp.zeros(shape, x.dtype)], axis=axis
    )


def _execute_math(bits, com, act):
    """The whole pass on an UNPACKED padded square graph. ``bits``:
    [..., Vp, Vp] int32 0/1 (Vp = VW*32); ``com`` / ``act``: [..., Vp]
    int32 0/1. Returns (eligible int32 0/1, order int32, scc_root
    int32), each [..., Vp]. Exact arithmetic only — float32 carries 0/1
    values and counts bounded by Vp, so every compare is integral and
    the result is schedule-independent (kernel == reference bitwise)."""
    vp = bits.shape[-1]
    # 2**steps >= longest simple path (<= Vp - 1 edges).
    steps = max(1, int(vp - 1).bit_length()) if vp > 1 else 1
    act_f = act.astype(jnp.float32)
    rid = jax.lax.broadcasted_iota(jnp.int32, (vp, vp), 0)
    cid = jax.lax.broadcasted_iota(jnp.int32, (vp, vp), 1)
    eye = (rid == cid).astype(jnp.float32)
    # Edges restricted to the active subgraph: a dependency on an
    # inactive vertex (executed / retired / empty) is satisfied, and
    # closure never flows THROUGH an inactive vertex either — its
    # transitive deps were satisfied before it executed.
    m = bits.astype(jnp.float32) * act_f[..., None, :] * act_f[..., :, None]
    r = jnp.minimum(m + eye, 1.0)  # reflexive closure seed
    for _ in range(steps):  # log-depth doubling: R <- R | R@R
        r = jnp.minimum(
            r + jnp.matmul(r, r, preferred_element_type=jnp.float32), 1.0
        )
    # Eligible: active, and NO vertex in the closure is an active
    # uncommitted one (the closure includes self, so own commitment is
    # part of the same test).
    uncommitted = act_f * (1.0 - com.astype(jnp.float32))
    bad = jnp.sum(r * uncommitted[..., None, :], axis=-1) > 0.0
    eligible = (act == 1) & ~bad
    # Closure size (incl. self): strict-subset ordering across SCCs.
    n = jnp.sum(r, axis=-1).astype(jnp.int32)
    # SCC root: least id with MUTUAL reachability (diagonal is always
    # mutual, so root <= id; equal roots <=> same component).
    mutual = (r * jnp.swapaxes(r, -1, -2)) > 0.0
    root = jnp.min(jnp.where(mutual, cid, vp), axis=-1)
    root = jnp.where(act == 1, root, -1)
    # Dense rank of eligible vertices by (closure size, id): deps rank
    # strictly before dependents, SCC members tie-break by id.
    n_i = n[..., :, None]
    n_k = n[..., None, :]
    less = (n_k < n_i) | ((n_k == n_i) & (cid < rid))
    rank = jnp.sum(
        (less & eligible[..., None, :]).astype(jnp.int32), axis=-1
    )
    order = jnp.where(eligible, rank, -1)
    return eligible.astype(jnp.int32), order, root


def _execute_padded(adj, com, act):
    """Unpack + pad to the word-aligned square and run the pass.
    ``adj``: [..., V, VW] uint32; ``com`` / ``act``: [..., V] int32.
    Outputs sliced back to V. Lanes >= V are forced inactive, so
    garbage bits in the padding lanes of ``adj`` cannot influence the
    result (the padding-edge contract ``tests/test_ops.py`` pins)."""
    v = adj.shape[-2]
    vp = adj.shape[-1] * _LANES
    bits = _pad_tail(_unpack_bits(adj), adj.ndim - 2, vp - v)
    comp = _pad_tail(com, com.ndim - 1, vp - v)
    actp = _pad_tail(act, act.ndim - 1, vp - v)
    elig, order, root = _execute_math(bits, comp, actp)
    return elig[..., :v], order[..., :v], root[..., :v]


def reference_depgraph_execute(
    adj: jnp.ndarray,  # [B, V, VW] uint32 packed adjacency
    committed: jnp.ndarray,  # [B, V] bool
    active: jnp.ndarray,  # [B, V] bool
):
    """Pure-jnp twin. Returns ``(eligible [B, V] bool, order [B, V]
    int32 — dense execution rank, -1 for non-eligible, scc_root [B, V]
    int32 — least mutual-reach id, -1 for inactive)``."""
    elig, order, root = _execute_padded(
        adj, committed.astype(jnp.int32), active.astype(jnp.int32)
    )
    return elig.astype(bool), order, root


def _depgraph_kernel_factory(V, VW):
    def kernel(adj_ref, com_ref, act_ref, out_e, out_o, out_r):
        elig, order, root = _execute_padded(
            adj_ref[...], com_ref[...], act_ref[...]
        )
        out_e[...] = elig
        out_o[...] = order
        out_r[...] = root

    return kernel


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def fused_depgraph_execute(
    adj,
    committed,
    active,
    block: int = 8,
    interpret: bool = False,
):
    """Fused :func:`reference_depgraph_execute`: the batch axis grids
    over blocks of whole graphs (each step keeps one block's [V, Vp]
    closure VMEM-resident; the doubling matmuls are the MXU shape the
    plane exists for)."""
    from jax.experimental import pallas as pl

    B, V, VW = adj.shape
    bs, pad = balanced_block(B, block)
    com = committed.astype(jnp.int32)
    act = active.astype(jnp.int32)
    if pad:
        adj = pad_axis(adj, 0, pad)
        com = pad_axis(com, 0, pad)
        act = pad_axis(act, 0, pad)
    Bp = B + pad
    spec3 = pl.BlockSpec((bs, V, VW), lambda i: (i, 0, 0))
    spec2 = pl.BlockSpec((bs, V), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Bp // bs,),
        in_specs=[spec3, spec2, spec2],
        out_specs=[spec2, spec2, spec2],
    )
    out_shape = [
        jax.ShapeDtypeStruct((Bp, V), jnp.int32),
        jax.ShapeDtypeStruct((Bp, V), jnp.int32),
        jax.ShapeDtypeStruct((Bp, V), jnp.int32),
    ]
    elig, order, root = pl.pallas_call(
        _depgraph_kernel_factory(V, VW),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(adj, com, act)
    if pad:
        elig, order, root = elig[:B], order[:B], root[:B]
    return elig.astype(bool), order, root


# ---------------------------------------------------------------------------
# Host-side sequential pointer-walk twin (oracle + microbench baseline)
# ---------------------------------------------------------------------------


def oracle_execute(adj, committed, active):
    """The reference semantics by SEQUENTIAL POINTER WALK — an
    iterative Tarjan over the active subgraph plus condensation reach
    sets, one vertex at a time, exactly the control flow of
    ``TarjanDependencyGraph.scala``. Host-only (numpy/python ints).

    Single graph: ``adj`` [V, VW] uint32, ``committed`` / ``active``
    [V] bool. Returns ``(eligible, order, scc_root)`` as numpy arrays
    with EXACTLY the plane's values — the equivalence oracle for
    ``tests/test_ops.py`` and the baseline the ``depgraph`` microbench
    times the batched closure against."""
    import numpy as np

    adj = np.asarray(adj, dtype=np.uint32)
    committed = np.asarray(committed, dtype=bool)
    active = np.asarray(active, dtype=bool)
    V = adj.shape[0]

    # Dependency sets as python int bitmasks, restricted to active.
    act_int = 0
    for v in range(V):
        if active[v]:
            act_int |= 1 << v
    deps = []
    for v in range(V):
        row = 0
        for w in range(adj.shape[1]):
            row |= int(adj[v, w]) << (w * _LANES)
        row &= (1 << V) - 1
        deps.append(row & act_int if active[v] else 0)

    # Iterative Tarjan over active vertices.
    index = [-1] * V
    lowlink = [0] * V
    on_stack = [False] * V
    stack: list = []
    comp_of = [-1] * V
    comps: list = []  # per component: member bitmask (pop order =
    # reverse topological: successors pop first)
    counter = 0
    for start in range(V):
        if not active[start] or index[start] >= 0:
            continue
        work = [(start, iter_bits(deps[start]))]
        index[start] = lowlink[start] = counter
        counter += 1
        stack.append(start)
        on_stack[start] = True
        while work:
            v, it = work[-1]
            advanced = False
            for u in it:
                if index[u] < 0:
                    index[u] = lowlink[u] = counter
                    counter += 1
                    stack.append(u)
                    on_stack[u] = True
                    work.append((u, iter_bits(deps[u])))
                    advanced = True
                    break
                if on_stack[u]:
                    lowlink[v] = min(lowlink[v], index[u])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[v])
            if lowlink[v] == index[v]:
                members = 0
                while True:
                    u = stack.pop()
                    on_stack[u] = False
                    comp_of[u] = len(comps)
                    members |= 1 << u
                    if u == v:
                        break
                comps.append(members)

    # Condensation reach sets: components pop in reverse topological
    # order, so every successor's reach is final when a component pops.
    reach = []
    for ci, members in enumerate(comps):
        r = members
        succ = 0
        for u in iter_bits(members):
            succ |= deps[u]
        for u in iter_bits(succ & ~members):
            r |= reach[comp_of[u]]
        reach.append(r)

    committed_int = 0
    for v in range(V):
        if committed[v]:
            committed_int |= 1 << v

    eligible = np.zeros((V,), bool)
    n = np.zeros((V,), np.int64)
    root = np.full((V,), -1, np.int32)
    for v in range(V):
        if not active[v]:
            continue
        rv = reach[comp_of[v]]
        eligible[v] = (rv & ~committed_int) == 0
        n[v] = bin(rv).count("1")
        root[v] = _lowest_bit(comps[comp_of[v]])
    order = np.full((V,), -1, np.int32)
    elig_ids = [v for v in range(V) if eligible[v]]
    for rank, v in enumerate(sorted(elig_ids, key=lambda v: (n[v], v))):
        order[v] = rank
    return eligible, order, root


def iter_bits(mask: int):
    """Iterate set-bit positions of a python int, lowest first."""
    while mask:
        low = mask & -mask
        yield low.bit_length() - 1
        mask ^= low


def _lowest_bit(mask: int) -> int:
    return (mask & -mask).bit_length() - 1


registry.register(
    registry.Plane(
        name="depgraph_execute",
        backend="bpaxos",
        reference=reference_depgraph_execute,
        kernel=fused_depgraph_execute,
        key_of=lambda args: args[0].shape,  # adj: (B, V, VW)
        batch_axis=0,  # grids over whole graphs
        default_block=8,
        # Every array is graph-local: the batch axis shards with no
        # cross-device dataflow (bpaxos batches per-replica graphs
        # along it, so a replica-axis mesh tiles the closure).
        shard=registry.ShardSpec(
            arg_axes=(0, 0, 0), out_axes=(0, 0, 0)
        ),
    )
)
