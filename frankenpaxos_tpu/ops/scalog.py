"""Fused Pallas kernel for the batched Scalog cut-commit plane.

``scalog_cut_commit`` covers tick step 2 of ``tpu/scalog_batched.py``:
the in-order commit scan over the in-flight cut ring (a cut commits
only once every earlier cut has — the running max over issue order
models the Paxos log of cuts), the newest-committed-cut projection onto
the global log, the per-cut record/latency attribution (each committing
cut's records waited from ITS OWN snapshot — head-of-line blocking
stays visible), and the ring-slot frees. In XLA this is an
associative_scan plus half a dozen gathers over the [P, S] ring; here
the ring walk is a static unrolled loop over the tiny pipeline depth P
with the [S] shard axis gridded, and the cross-shard record counts
accumulate across grid blocks (integer adds — order-exact).

The aggregator's snapshot issue (tick step 3, PRNG + FaultPlan gating)
stays in XLA: it is [P]-space control. FaultPlans compose from OUTSIDE
— partition/crash gate the issue, drops/jitter stretch the ordering
round's latency — so faulty runs ride the kernel unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import INF_I, balanced_block, pad_axis, t_space
from frankenpaxos_tpu.tpu.common import INF


def reference_scalog_cut_commit(
    cut_vec: jnp.ndarray,  # [P, S] in-flight cut vectors
    cut_commit_tick: jnp.ndarray,  # [P] commit tick per ring slot (INF)
    cut_snap_tick: jnp.ndarray,  # [P] snapshot tick per ring slot
    cut_prev_snap: jnp.ndarray,  # [P] the PREVIOUS cut's snapshot tick
    last_committed_cut: jnp.ndarray,  # [S]
    committed_cuts: jnp.ndarray,  # [] cuts committed so far
    next_cut: jnp.ndarray,  # [] cuts issued so far
    t: jnp.ndarray,  # []
):
    """The pure-jnp specification (tick step 2 of scalog_batched).
    Returns ``(new_cut [S], committed_now_asc [P], recs_asc [P],
    lag_asc [P], slot_committed [P], cut_commit_tick' [P],
    cut_snap_tick' [P])`` — the issue-order commit mask, per-cut record
    counts and lags (for the latency stats the tick keeps outside), and
    the freed ring-slot clocks."""
    P = cut_vec.shape[0]
    ids_asc = committed_cuts + jnp.arange(P, dtype=jnp.int32)
    live = ids_asc < next_cut
    slots_asc = ids_asc % P
    ticks_asc = jnp.where(live, cut_commit_tick[slots_asc], INF)
    eff_asc = jax.lax.associative_scan(jnp.maximum, ticks_asc)
    committed_now_asc = live & (eff_asc <= t)
    n_new_commits = jnp.sum(committed_now_asc.astype(jnp.int32))

    any_commit = n_new_commits > 0
    newest_idx = jnp.clip(n_new_commits - 1, 0, P - 1)
    newest_slot = slots_asc[newest_idx]
    new_cut = jnp.where(
        any_commit, cut_vec[newest_slot], last_committed_cut
    )

    vec_asc = cut_vec[slots_asc]  # [P, S] in issue order
    prev_vec_asc = jnp.concatenate(
        [last_committed_cut[None, :], vec_asc[:-1]], axis=0
    )
    recs_asc = jnp.where(
        committed_now_asc, jnp.sum(vec_asc - prev_vec_asc, axis=1), 0
    )
    snap_wait_asc = (
        cut_snap_tick[slots_asc] - cut_prev_snap[slots_asc] + 1
    ) // 2
    lag_asc = jnp.where(
        committed_now_asc,
        (t - cut_snap_tick[slots_asc]) + snap_wait_asc,
        0,
    )

    slot_committed = jnp.zeros((P,), bool)
    slot_committed = slot_committed.at[slots_asc].set(committed_now_asc)
    new_commit_tick = jnp.where(slot_committed, INF, cut_commit_tick)
    new_snap_tick = jnp.where(slot_committed, INF, cut_snap_tick)
    return (
        new_cut, committed_now_asc, recs_asc, lag_asc, slot_committed,
        new_commit_tick, new_snap_tick,
    )


def _scalog_kernel_factory(P):
    def kernel(
        s_ref,  # SMEM (3,): [t, committed_cuts, next_cut]
        vec_ref,  # [P, BS]
        commit_ref, snap_ref, prev_ref,  # [P]
        last_ref,  # [BS]
        out_cut,  # [BS]
        out_committed,  # [P] int8 (issue order)
        out_recs,  # [P] int32 (accumulated across shard blocks)
        out_lag,  # [P] int32
        out_slotc,  # [P] int8 (ring order)
        out_commit_tick, out_snap_tick,  # [P]
    ):
        from jax.experimental import pallas as pl

        t = s_ref[0]
        cc = s_ref[1]
        nc = s_ref[2]

        # The [P]-space ring walk (recomputed per block — P is the tiny
        # static pipeline depth, so this costs scalar ops only). The
        # commit predicate avoids the reference's associative cummax:
        # eff_i <= t  <=>  every tick up to i is <= t  <=>  i precedes
        # the first in-order cut whose decision is still out — the same
        # masked-min trick as the ring-retire helpers, value-identical.
        live = []
        slot = []
        ok = []
        for i in range(P):
            idx = cc + i
            live_i = idx < nc
            slot_i = idx % P
            tick_i = jnp.int32(INF_I)
            for j in range(P):
                tick_i = jnp.where(slot_i == j, commit_ref[j], tick_i)
            tick_i = jnp.where(live_i, tick_i, INF_I)
            live.append(live_i)
            slot.append(slot_i)
            ok.append(tick_i <= t)
        committed = []
        prefix_ok = None
        for i in range(P):
            prefix_ok = ok[i] if prefix_ok is None else prefix_ok & ok[i]
            committed.append(live[i] & prefix_ok)

        # Newest committed cut projection + per-cut record deltas, in
        # ascending issue order (committed cuts form a prefix, so the
        # last where() write is the newest committed vector).
        new_cut = last_ref[:]
        prev_vec = last_ref[:]
        init = pl.program_id(0) == 0
        for i in range(P):
            vec_i = jnp.zeros(new_cut.shape, vec_ref.dtype)
            for j in range(P):
                vec_i = jnp.where(slot[i] == j, vec_ref[j], vec_i)
            new_cut = jnp.where(committed[i], vec_i, new_cut)
            partial = jnp.where(
                committed[i], jnp.sum(vec_i - prev_vec), 0
            )
            # recs accumulates across shard blocks: zero on the first
            # grid step, then integer adds (order-exact).
            prior = jnp.where(init, 0, out_recs[i])
            out_recs[i] = prior + partial
            prev_vec = vec_i
        out_cut[:] = new_cut

        # [P]-space outputs (identical from every block; the last grid
        # step's write wins with the same values).
        for i in range(P):
            snap_i = jnp.int32(0)
            prevs_i = jnp.int32(0)
            for j in range(P):
                snap_i = jnp.where(slot[i] == j, snap_ref[j], snap_i)
                prevs_i = jnp.where(slot[i] == j, prev_ref[j], prevs_i)
            lag_i = jnp.where(
                committed[i],
                (t - snap_i) + (snap_i - prevs_i + 1) // 2,
                0,
            )
            out_lag[i] = lag_i
            out_committed[i] = committed[i].astype(jnp.int8)
        for j in range(P):
            sc_j = jnp.asarray(False)
            for i in range(P):
                sc_j = jnp.where(slot[i] == j, committed[i], sc_j)
            out_slotc[j] = sc_j.astype(jnp.int8)
            out_commit_tick[j] = jnp.where(sc_j, INF_I, commit_ref[j])
            out_snap_tick[j] = jnp.where(sc_j, INF_I, snap_ref[j])

    return kernel


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def fused_scalog_cut_commit(
    cut_vec,
    cut_commit_tick,
    cut_snap_tick,
    cut_prev_snap,
    last_committed_cut,
    committed_cuts,
    next_cut,
    t,
    block: int = 512,
    interpret: bool = False,
):
    """Fused :func:`reference_scalog_cut_commit`, gridded over shard
    blocks with the pipeline-depth ring walk unrolled per block."""
    from jax.experimental import pallas as pl

    P, S = cut_vec.shape
    bs, pad = balanced_block(S, block)
    if pad:
        cut_vec = pad_axis(cut_vec, 1, pad)
        last_committed_cut = pad_axis(last_committed_cut, 0, pad)
    Sp = S + pad

    spec_ps = pl.BlockSpec((P, bs), lambda i: (0, i))
    spec_p = pl.BlockSpec((P,), lambda i: (0,))
    spec_s = pl.BlockSpec((bs,), lambda i: (i,))
    grid_spec = pl.GridSpec(
        grid=(Sp // bs,),
        in_specs=[
            pl.BlockSpec((3,), lambda i: (0,), memory_space=t_space(interpret)),
            spec_ps,  # cut_vec
            spec_p,  # cut_commit_tick
            spec_p,  # cut_snap_tick
            spec_p,  # cut_prev_snap
            spec_s,  # last_committed_cut
        ],
        out_specs=[
            spec_s,  # new_cut
            spec_p,  # committed_now (issue order)
            spec_p,  # recs (accumulated)
            spec_p,  # lag
            spec_p,  # slot_committed
            spec_p,  # commit_tick'
            spec_p,  # snap_tick'
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((Sp,), cut_vec.dtype),
        jax.ShapeDtypeStruct((P,), jnp.int8),
        jax.ShapeDtypeStruct((P,), jnp.int32),
        jax.ShapeDtypeStruct((P,), jnp.int32),
        jax.ShapeDtypeStruct((P,), jnp.int8),
        jax.ShapeDtypeStruct((P,), cut_commit_tick.dtype),
        jax.ShapeDtypeStruct((P,), cut_snap_tick.dtype),
    ]
    scalars = jnp.stack(
        [
            jnp.asarray(t, jnp.int32),
            jnp.asarray(committed_cuts, jnp.int32),
            jnp.asarray(next_cut, jnp.int32),
        ]
    )
    kernel = _scalog_kernel_factory(P)
    new_cut, committed, recs, lag, slotc, commit2, snap2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        scalars,
        cut_vec,
        cut_commit_tick,
        cut_snap_tick,
        cut_prev_snap,
        last_committed_cut,
    )
    if pad:
        new_cut = new_cut[:S]
    return (
        new_cut, committed.astype(bool), recs, lag, slotc.astype(bool),
        commit2, snap2,
    )


registry.register(
    registry.Plane(
        name="scalog_cut_commit",
        backend="scalog",
        reference=reference_scalog_cut_commit,
        kernel=fused_scalog_cut_commit,
        key_of=lambda args: args[0].shape,  # cut_vec: (P, S)
        batch_axis=1,  # grids over S (shards)
        default_block=512,
    )
)
