"""Pallas TPU kernels for the batched simulation's hot ops.

The tick's hottest phase is the acceptor step: process Phase2a arrivals,
record votes, schedule Phase2b arrivals, and count the per-slot quorum —
six elementwise passes plus a reduction over [G, W, A] arrays in the XLA
version. :func:`fused_vote_quorum` fuses all of it into ONE Pallas kernel
pass so every array is read from HBM once and stays in VMEM across the
whole phase.

Layout: the kernel works on ACCEPTOR-MAJOR ``[A, G, W]`` arrays (last dim
W maps onto the 128-lane VPU; the tiny acceptor axis A=2f+1 becomes a
static in-kernel loop) — the layout a real-TPU deployment of the batched
state would use. :func:`reference_vote_quorum` is the pure-jnp
specification the kernel is verified against (interpret mode in CI on
CPU; the compiled path targets a real TPU).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import INF


def reference_vote_quorum(
    p2a_arrival: jnp.ndarray,  # [A, G, W] int32 arrival ticks (INF = never)
    acc_round: jnp.ndarray,  # [A, G] int32 promised rounds
    leader_round: jnp.ndarray,  # [G] int32
    slot_value: jnp.ndarray,  # [G, W] int32
    vote_round: jnp.ndarray,  # [A, G, W] int32 (-1 = no vote)
    vote_value: jnp.ndarray,  # [A, G, W] int32
    p2b_arrival: jnp.ndarray,  # [A, G, W] int32 (INF = none pending)
    p2b_lat: jnp.ndarray,  # [A, G, W] int32 sampled latencies
    p2b_delivered: jnp.ndarray,  # [A, G, W] bool
    t: jnp.ndarray,  # [] int32 current tick
) -> Tuple[
    jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
    jnp.ndarray,
]:
    """The pure-jnp specification (tick steps 1-2 of multipaxos_batched,
    Acceptor.scala:184-220 + ProxyLeader.scala:217-258), acceptor-major.

    The sixth output ``nsends`` [G, W] counts the Phase2b messages the
    acceptors SENT this tick (votes cast whose reply was delivered) —
    the vote predicate is otherwise kernel-internal, and the telemetry
    phase-2 message accounting needs it to be exact under use_pallas."""
    lr = leader_round[None, :, None]  # [1, G, 1]
    arrived = p2a_arrival == t
    may_vote = arrived & (lr >= acc_round[:, :, None])
    new_vote_round = jnp.where(may_vote, lr, vote_round)
    new_vote_value = jnp.where(may_vote, slot_value[None, :, :], vote_value)
    sends = may_vote & p2b_delivered
    new_p2b = jnp.where(
        sends,
        jnp.minimum(p2b_arrival, t + p2b_lat),
        p2b_arrival,
    )
    new_acc_round = jnp.maximum(
        acc_round, jnp.max(jnp.where(may_vote, lr, -1), axis=2)
    )
    votes_in = (new_p2b <= t) & (new_vote_round == lr)
    nvotes = jnp.sum(votes_in.astype(jnp.int32), axis=0)  # [G, W]
    nsends = jnp.sum(sends.astype(jnp.int32), axis=0)  # [G, W]
    return new_vote_round, new_vote_value, new_p2b, new_acc_round, nvotes, nsends


def _vote_quorum_kernel(
    t_ref,  # SMEM (1,) current tick
    p2a_ref,  # [A, BG, W]
    accr_ref,  # [A, BG]
    lr_ref,  # [BG]
    sv_ref,  # [BG, W]
    vr_ref,  # [A, BG, W]
    vv_ref,  # [A, BG, W]
    p2b_ref,  # [A, BG, W]
    lat_ref,  # [A, BG, W]
    deliv_ref,  # [A, BG, W] int8 (0/1)
    out_vr_ref,  # [A, BG, W]
    out_vv_ref,  # [A, BG, W]
    out_p2b_ref,  # [A, BG, W]
    out_accr_ref,  # [A, BG]
    out_nv_ref,  # [BG, W]
    out_ns_ref,  # [BG, W] Phase2b sends this tick
):
    t = t_ref[0]
    A = p2a_ref.shape[0]
    lr = lr_ref[:][:, None]  # [BG, 1]
    sv = sv_ref[:]  # [BG, W]
    nvotes = jnp.zeros(sv.shape, jnp.int32)
    nsends = jnp.zeros(sv.shape, jnp.int32)
    # The acceptor axis is tiny (2f+1): a static loop keeps every slice a
    # well-tiled [BG, W] block, with values resident in VMEM across the
    # vote update AND the quorum count.
    for a in range(A):
        p2a = p2a_ref[a]
        arrived = p2a == t
        may_vote = arrived & (lr >= accr_ref[a][:, None])
        new_vr = jnp.where(may_vote, lr, vr_ref[a])
        new_vv = jnp.where(may_vote, sv, vv_ref[a])
        deliver = may_vote & (deliv_ref[a] != 0)
        new_p2b = jnp.where(
            deliver, jnp.minimum(p2b_ref[a], t + lat_ref[a]), p2b_ref[a]
        )
        out_vr_ref[a] = new_vr
        out_vv_ref[a] = new_vv
        out_p2b_ref[a] = new_p2b
        out_accr_ref[a] = jnp.maximum(
            accr_ref[a], jnp.max(jnp.where(may_vote, lr, -1), axis=1)
        )
        nvotes = nvotes + ((new_p2b <= t) & (new_vr == lr)).astype(jnp.int32)
        nsends = nsends + deliver.astype(jnp.int32)
    out_nv_ref[:] = nvotes
    out_ns_ref[:] = nsends


@functools.partial(jax.jit, static_argnames=("block_g", "interpret"))
def fused_vote_quorum(
    p2a_arrival,
    acc_round,
    leader_round,
    slot_value,
    vote_round,
    vote_value,
    p2b_arrival,
    p2b_lat,
    p2b_delivered,
    t,
    block_g: int = 256,
    interpret: bool = False,
):
    """One fused VMEM-resident pass over the acceptor step (see module
    docstring). Same semantics as :func:`reference_vote_quorum`; gridded
    over blocks of the group axis."""
    from jax.experimental import pallas as pl

    A, G, W = p2a_arrival.shape
    # Balanced blocks: bg = ceil(G / nblocks) for the smallest nblocks
    # with bg <= block_g, so padding waste is bounded by one block's
    # remainder (min(block_g, G) would pad G=257 up to 512).
    nblocks = -(-G // block_g)
    bg = -(-G // nblocks)
    # Pad the group axis up to a block multiple; padded groups compute
    # garbage that is sliced off (no cross-group dataflow exists).
    pad = (-G) % bg
    if pad:
        def pad_g(x, axis):
            widths = [(0, 0)] * x.ndim
            widths[axis] = (0, pad)
            return jnp.pad(x, widths)

        p2a_arrival = pad_g(p2a_arrival, 1)
        acc_round = pad_g(acc_round, 1)
        leader_round = pad_g(leader_round, 0)
        slot_value = pad_g(slot_value, 0)
        vote_round = pad_g(vote_round, 1)
        vote_value = pad_g(vote_value, 1)
        p2b_arrival = pad_g(p2b_arrival, 1)
        p2b_lat = pad_g(p2b_lat, 1)
        p2b_delivered = pad_g(p2b_delivered, 1)
    Gp = G + pad

    from jax.experimental.pallas import tpu as pltpu

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec2 = pl.BlockSpec((A, bg), lambda i: (0, i))
    spec_g = pl.BlockSpec((bg,), lambda i: (i,))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    t_arr = jnp.asarray(t, jnp.int32).reshape((1,))

    # Scalars live in SMEM on the compiled TPU path; interpret mode
    # accepts the same spec.
    t_space = None if interpret else pltpu.SMEM
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space),  # t
            spec3,  # p2a
            spec2,  # acc_round
            spec_g,  # leader_round
            spec_gw,  # slot_value
            spec3,  # vote_round
            spec3,  # vote_value
            spec3,  # p2b_arrival
            spec3,  # p2b_lat
            spec3,  # delivered
        ],
        out_specs=[spec3, spec3, spec3, spec2, spec_gw, spec_gw],
    )
    out_shape = [
        jax.ShapeDtypeStruct((A, Gp, W), jnp.int32),  # vote_round
        jax.ShapeDtypeStruct((A, Gp, W), jnp.int32),  # vote_value
        jax.ShapeDtypeStruct((A, Gp, W), jnp.int32),  # p2b_arrival
        jax.ShapeDtypeStruct((A, Gp), jnp.int32),  # acc_round
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # nvotes
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # Phase2b sends
    ]
    vr, vv, p2b, accr, nv, ns = pl.pallas_call(
        _vote_quorum_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr,
        p2a_arrival.astype(jnp.int32),
        acc_round.astype(jnp.int32),
        leader_round.astype(jnp.int32),
        slot_value.astype(jnp.int32),
        vote_round.astype(jnp.int32),
        vote_value.astype(jnp.int32),
        p2b_arrival.astype(jnp.int32),
        p2b_lat.astype(jnp.int32),
        p2b_delivered.astype(jnp.int8),
    )
    if pad:
        vr, vv, p2b = vr[:, :G], vv[:, :G], p2b[:, :G]
        accr, nv, ns = accr[:, :G], nv[:G], ns[:G]
    return vr, vv, p2b, accr, nv, ns
