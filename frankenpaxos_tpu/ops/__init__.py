"""Pallas TPU kernel layer for the batched simulation's hot planes.

What began as one fused kernel (the MultiPaxos acceptor step) is a
kernel SUITE with a dispatch registry:

  * :mod:`frankenpaxos_tpu.ops.registry` — the :class:`KernelPolicy`
    knob every covered backend config carries, per-plane
    pallas/interpret/reference dispatch, and the checked-in autotune
    table (``ops/autotune.json``) block-size lookup.
  * :mod:`frankenpaxos_tpu.ops.multipaxos` — the MultiPaxos planes:
    ``multipaxos_fused_tick`` (the WHOLE-TICK MEGAKERNEL: clock aging +
    vote/quorum + dispatch as one Pallas grid program — State never
    round-trips HBM between planes),
    ``multipaxos_vote_quorum`` (acceptor votes + quorum count + the
    read path's max-voted-slot feed),
    ``multipaxos_p1_promise`` (phase-1 safe-value aggregation + re-send),
    ``multipaxos_dispatch`` (choose + commit-watermark advance +
    proposals + retries).
  * :mod:`frankenpaxos_tpu.ops.fastmultipaxos` — ``fastmultipaxos_vote``
    (census/pairwise-match counting, fast choose, recovery triggers,
    the classic round, chosen stamps).
  * :mod:`frankenpaxos_tpu.ops.horizontal` — ``horizontal_vote``
    (bank-masked acceptor votes, in-bank quorum count, choose, the
    bank-isolation violation ledger).
  * :mod:`frankenpaxos_tpu.ops.mencius` — ``mencius_vote`` (per-slot
    vote/skip aggregation).
  * :mod:`frankenpaxos_tpu.ops.scalog` — ``scalog_cut_commit`` (the
    in-order cut-commit scan, newest-cut projection, per-cut record
    latency accounting).
  * :mod:`frankenpaxos_tpu.ops.craq` — ``craq_chain`` (chain
    propagate/ack with scatter-free pending-set accounting; partitioned
    plans defer cut hops to the heal tick in-kernel).
  * :mod:`frankenpaxos_tpu.ops.depgraph` — ``depgraph_execute`` (the
    bounded-window dependency-graph executor: packed-bitmask transitive
    closure by log-depth matrix doubling, SCC condensation, eligibility,
    deterministic batch execution order — the device-side replacement
    for pointer-chasing Tarjan execution, batched over per-replica
    graph views; plus the packed-adjacency helpers and the host
    pointer-walk oracle twin).
  * :mod:`frankenpaxos_tpu.ops.costmodel` — the analytical roofline
    cost model over every plane above (stated bytes-moved + FLOP terms
    per autotune key, CPU/TPU parameter sets): predicted time feeds
    the registry's block fallback for unseen shapes, the
    ``costmodel-coverage`` / ``costmodel-drift`` lint gates, the
    ``fpx_efficiency_*`` serve gauges, and ``bench.py`` saturation
    prediction.
  * :mod:`frankenpaxos_tpu.ops.compartmentalized` —
    ``compartmentalized_grid_vote`` (the acceptor-grid hot path:
    offset-clock aging, column-transversal write votes, every-row-voted
    chosen detection, per-replica watermark advance, full-grid retry
    re-sends — one VMEM-resident pass over the [R, C, G, W] grid).

Every kernel is dtype-polymorphic (int16 rounds / int16 offset clocks /
int8 statuses native — no widen/narrow casts at the boundary) and has a
pure-jnp ``reference_*`` twin with an identical signature, pinned
bit-identical by ``tests/test_ops.py`` and
``tests/test_kernel_registry.py``. The AST lint
(``tests/test_kernel_lint.py``) keeps every ``pallas_call`` inside this
package and every covered backend dispatching through the registry.

Microbenchmark + autotuner:
``python -m frankenpaxos_tpu.harness.microbench kernels``.
"""

from frankenpaxos_tpu.tpu.common import INF, INF16  # noqa: F401 (re-export)

from frankenpaxos_tpu.ops import costmodel  # noqa: F401
from frankenpaxos_tpu.ops import registry  # noqa: F401
from frankenpaxos_tpu.ops.registry import (  # noqa: F401
    KernelPolicy,
    coverage,
    dispatch,
)
from frankenpaxos_tpu.ops.multipaxos import (  # noqa: F401
    fused_mp_dispatch,
    fused_p1_promise,
    fused_tick,
    fused_vote_quorum,
    reference_fused_tick,
    reference_mp_dispatch,
    reference_p1_promise,
    reference_vote_quorum,
)
from frankenpaxos_tpu.ops.fastmultipaxos import (  # noqa: F401
    fused_fmp_vote,
    reference_fmp_vote,
)
from frankenpaxos_tpu.ops.horizontal import (  # noqa: F401
    fused_horizontal_vote,
    reference_horizontal_vote,
)
from frankenpaxos_tpu.ops.mencius import (  # noqa: F401
    fused_mencius_vote,
    reference_mencius_vote,
)
from frankenpaxos_tpu.ops.scalog import (  # noqa: F401
    fused_scalog_cut_commit,
    reference_scalog_cut_commit,
)
from frankenpaxos_tpu.ops.craq import (  # noqa: F401
    fused_craq_chain,
    reference_craq_chain,
)
from frankenpaxos_tpu.ops.compartmentalized import (  # noqa: F401
    fused_grid_vote,
    reference_grid_vote,
)
from frankenpaxos_tpu.ops.depgraph import (  # noqa: F401
    fused_depgraph_execute,
    reference_depgraph_execute,
)
