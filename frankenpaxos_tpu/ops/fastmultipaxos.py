"""Fused Pallas kernel for the batched Fast MultiPaxos vote plane.

``fastmultipaxos_vote`` covers tick steps 2-3 of
``tpu/fastmultipaxos_batched.py``: the leader observes per-slot vote
censuses (pairwise same-value counts over the tiny acceptor axis), the
fast-committed ledger records unobserved fast quorums, slots choose on
a fast quorum of identical visible votes or fall to classic recovery
(census-full / timeout triggers, Leader.scala:545, 721-730), the
classic round's acceptor votes and f+1 quorum complete, and chosen
slots stamp value + replica arrival. In XLA this is ~a dozen
elementwise passes plus two [A, A, G, W] pairwise reductions over the
[A, G, W] vote arrays; here it is ONE VMEM-resident pass per group
block with the pairwise counts as an unrolled A x A loop.

The acceptor-append scatter (tick step 1) and the [G, W, CW] command
completion join (step 4) stay in XLA — scatters and the cross-ring join
don't vectorize in a Pallas grid over groups; this plane is the
vote-traffic half that scales with [A, G, W].

Argmax tie-breaks replicate ``jnp.argmax`` (first max) via strict-``>``
first-max scans, so the kernel is bit-identical to the reference twin.
FaultPlans compose from OUTSIDE: broadcast-plane drops/cuts land in
step 1's arrival arrays and recovery-round TCP penalties land in
``rv_lat`` before dispatch, so faulty runs ride the kernel unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import (
    INF_I,
    balanced_block,
    pad_axis,
    t_arr,
    t_space,
)
from frankenpaxos_tpu.tpu.common import INF

# Mirrors of the backend's slot codes (ops must not import the backend).
# Cross-checked by tests/test_kernel_registry.
S_OPEN = 0
S_RECOVER = 1
S_CHOSEN = 2
NO_VALUE = -1


def reference_fmp_vote(
    vote_value: jnp.ndarray,  # [A, G, W] fast-round votes (NO_VALUE none)
    vote_seen: jnp.ndarray,  # [A, G, W] tick the leader sees the vote (INF)
    status: jnp.ndarray,  # [G, W] int8 S_*
    open_tick: jnp.ndarray,  # [G, W] first visible vote tick (INF)
    fast_committed: jnp.ndarray,  # [G, W] ledger (NO_VALUE none)
    rv_value: jnp.ndarray,  # [G, W] classic-round proposal value
    rv_p2a_arrival: jnp.ndarray,  # [A, G, W]
    rv_p2b_arrival: jnp.ndarray,  # [A, G, W]
    rv_voted: jnp.ndarray,  # [A, G, W] bool
    chosen_value: jnp.ndarray,  # [G, W]
    replica_arrival: jnp.ndarray,  # [G, W]
    rv_lat: jnp.ndarray,  # [G, W] classic-round hop latencies
    reply_lat: jnp.ndarray,  # [G, W] chosen -> replica latencies
    t: jnp.ndarray,  # []
    *,
    fq: int,
    f: int,
    recovery_timeout: int,
):
    """The pure-jnp specification (tick steps 2-3 of
    fastmultipaxos_batched). Returns the updated slot/vote arrays plus
    the ``newly_chosen`` / ``fast_ok`` / ``start_rec`` / ``safety``
    masks the tick's stat counters reduce outside."""
    A = vote_value.shape[0]

    # ---- 2. Leader observes votes per slot.
    visible = vote_seen <= t  # [A, G, W]
    n_visible = jnp.sum(visible, axis=0)
    open_tick = jnp.where(
        (open_tick == INF) & (n_visible > 0) & (status == S_OPEN),
        t,
        open_tick,
    )
    same = (
        (vote_value[:, None] == vote_value[None, :])
        & (vote_value[None, :] != NO_VALUE)
        & visible[:, None]
        & visible[None, :]
    )  # [A, A, G, W]
    match_count = jnp.sum(same, axis=1)  # [A, G, W]
    best_count = jnp.max(match_count, axis=0)  # [G, W]
    best_a = jnp.argmax(match_count, axis=0)
    best_value = jnp.take_along_axis(
        vote_value, best_a[None, :, :], axis=0
    )[0]
    same_all = (
        (vote_value[:, None] == vote_value[None, :])
        & (vote_value[None, :] != NO_VALUE)
    )
    full_count = jnp.max(jnp.sum(same_all, axis=1), axis=0)
    full_a = jnp.argmax(jnp.sum(same_all, axis=1), axis=0)
    full_value = jnp.take_along_axis(
        vote_value, full_a[None, :, :], axis=0
    )[0]
    fast_committed = jnp.where(
        (fast_committed == NO_VALUE) & (full_count >= fq),
        full_value,
        fast_committed,
    )

    fast_ok = (status == S_OPEN) & (best_count >= fq)
    census_full = n_visible >= A
    timed_out = (
        (open_tick < INF)
        & (t - open_tick >= recovery_timeout)
        & (n_visible >= A - f)
    )
    start_rec = (status == S_OPEN) & ~fast_ok & (census_full | timed_out)
    new_rv_value = jnp.where(start_rec, best_value, rv_value)
    status = jnp.where(start_rec, S_RECOVER, status)
    new_rv_p2a = jnp.where(
        start_rec[None, :, :],
        t + jnp.broadcast_to(rv_lat[None], vote_value.shape),
        rv_p2a_arrival,
    )

    # ---- 3. Classic round at acceptors + choose.
    rv_now = new_rv_p2a == t
    new_rv_voted = rv_voted | rv_now
    new_rv_p2b = jnp.where(rv_now, t + rv_lat[None], rv_p2b_arrival)
    new_rv_p2a = jnp.where(rv_now, INF, new_rv_p2a)
    n_rv = jnp.sum(new_rv_voted & (new_rv_p2b <= t), axis=0)
    rec_ok = (status == S_RECOVER) & (n_rv >= f + 1)

    newly_chosen = fast_ok | rec_ok
    # rec_ok slots were recovering before this tick (a freshly started
    # recovery has no classic votes yet), so the PRE-update rv_value is
    # the value their round proposed — exactly what the tick read.
    value_now = jnp.where(fast_ok, best_value, rv_value)
    safety = (
        newly_chosen
        & (fast_committed != NO_VALUE)
        & (value_now != fast_committed)
    )
    new_chosen_value = jnp.where(newly_chosen, value_now, chosen_value)
    status = jnp.where(newly_chosen, S_CHOSEN, status)
    new_replica_arrival = jnp.where(
        newly_chosen, t + reply_lat, replica_arrival
    )
    return (
        status, open_tick, fast_committed, new_rv_value,
        new_rv_p2a, new_rv_p2b, new_rv_voted,
        new_chosen_value, new_replica_arrival,
        newly_chosen, fast_ok, start_rec, safety,
    )


def _fmp_vote_kernel_factory(fq, f, recovery_timeout, A):
    def kernel(
        t_ref,  # SMEM (1,)
        vv_ref, vs_ref,  # [A, BG, W]
        status_ref, ot_ref, fc_ref, rvv_ref,  # [BG, W]
        rp2a_ref, rp2b_ref, rvoted_ref,  # [A, BG, W]
        cv_ref, ra_ref, rvlat_ref, replylat_ref,  # [BG, W]
        out_status, out_ot, out_fc, out_rvv,
        out_rp2a, out_rp2b, out_rvoted,
        out_cv, out_ra,
        out_newly, out_fast, out_rec, out_safety,
    ):
        t = t_ref[0]
        status = status_ref[:]
        rv_lat = rvlat_ref[:]
        vv = [vv_ref[a] for a in range(A)]
        visible = [vs_ref[a] <= t for a in range(A)]

        n_visible = jnp.zeros(status.shape, jnp.int32)
        for a in range(A):
            n_visible = n_visible + visible[a].astype(jnp.int32)
        open_tick = jnp.where(
            (ot_ref[:] == INF_I) & (n_visible > 0) & (status == S_OPEN),
            t,
            ot_ref[:],
        )

        # Pairwise same-value counts + first-max scans (the reference's
        # argmax picks the FIRST max; strict > replicates it exactly).
        best_count = None
        best_value = None
        full_count = None
        full_value = None
        for a in range(A):
            cnt = jnp.zeros(status.shape, jnp.int32)
            cnt_all = jnp.zeros(status.shape, jnp.int32)
            # The != NO_VALUE test is on vv[b] — the reference's
            # `vote_value[None, :] != NO_VALUE` broadcasts over b.
            for b in range(A):
                pair = (vv[a] == vv[b]) & (vv[b] != NO_VALUE)
                cnt_all = cnt_all + pair.astype(jnp.int32)
                cnt = cnt + (pair & visible[a] & visible[b]).astype(
                    jnp.int32
                )
            if a == 0:
                best_count, best_value = cnt, vv[0]
                full_count, full_value = cnt_all, vv[0]
            else:
                upd = cnt > best_count
                best_count = jnp.where(upd, cnt, best_count)
                best_value = jnp.where(upd, vv[a], best_value)
                upd_f = cnt_all > full_count
                full_count = jnp.where(upd_f, cnt_all, full_count)
                full_value = jnp.where(upd_f, vv[a], full_value)
        fast_committed = jnp.where(
            (fc_ref[:] == NO_VALUE) & (full_count >= fq),
            full_value,
            fc_ref[:],
        )

        fast_ok = (status == S_OPEN) & (best_count >= fq)
        census_full = n_visible >= A
        timed_out = (
            (open_tick < INF_I)
            & (t - open_tick >= recovery_timeout)
            & (n_visible >= A - f)
        )
        start_rec = (status == S_OPEN) & ~fast_ok & (census_full | timed_out)
        out_rvv[:] = jnp.where(start_rec, best_value, rvv_ref[:])
        status = jnp.where(start_rec, S_RECOVER, status)

        n_rv = jnp.zeros(status.shape, jnp.int32)
        for a in range(A):
            rp2a = jnp.where(start_rec, t + rv_lat, rp2a_ref[a])
            rv_now = rp2a == t
            rvoted = (rvoted_ref[a] != 0) | rv_now
            rp2b = jnp.where(rv_now, t + rv_lat, rp2b_ref[a])
            out_rp2a[a] = jnp.where(rv_now, INF_I, rp2a)
            out_rp2b[a] = rp2b
            out_rvoted[a] = rvoted.astype(jnp.int8)
            n_rv = n_rv + (rvoted & (rp2b <= t)).astype(jnp.int32)
        rec_ok = (status == S_RECOVER) & (n_rv >= f + 1)

        newly_chosen = fast_ok | rec_ok
        value_now = jnp.where(fast_ok, best_value, rvv_ref[:])
        out_safety[:] = (
            newly_chosen
            & (fast_committed != NO_VALUE)
            & (value_now != fast_committed)
        ).astype(jnp.int8)
        out_cv[:] = jnp.where(newly_chosen, value_now, cv_ref[:])
        out_status[:] = jnp.where(newly_chosen, S_CHOSEN, status)
        out_ra[:] = jnp.where(newly_chosen, t + replylat_ref[:], ra_ref[:])
        out_ot[:] = open_tick
        out_fc[:] = fast_committed
        out_newly[:] = newly_chosen.astype(jnp.int8)
        out_fast[:] = fast_ok.astype(jnp.int8)
        out_rec[:] = start_rec.astype(jnp.int8)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("block", "interpret", "fq", "f", "recovery_timeout"),
)
def fused_fmp_vote(
    vote_value,
    vote_seen,
    status,
    open_tick,
    fast_committed,
    rv_value,
    rv_p2a_arrival,
    rv_p2b_arrival,
    rv_voted,
    chosen_value,
    replica_arrival,
    rv_lat,
    reply_lat,
    t,
    block: int = 256,
    interpret: bool = False,
    fq: int = 2,
    f: int = 1,
    recovery_timeout: int = 10,
):
    """Fused :func:`reference_fmp_vote`, gridded over group blocks."""
    from jax.experimental import pallas as pl

    A, G, W = vote_value.shape
    bg, pad = balanced_block(G, block)
    agw = [vote_value, vote_seen, rv_p2a_arrival, rv_p2b_arrival, rv_voted]
    gw = [
        status, open_tick, fast_committed, rv_value, chosen_value,
        replica_arrival, rv_lat, reply_lat,
    ]
    if pad:
        agw = [pad_axis(x, 1, pad) for x in agw]
        gw = [pad_axis(x, 0, pad) for x in gw]
    vote_value, vote_seen, rv_p2a_arrival, rv_p2b_arrival, rv_voted = agw
    (status, open_tick, fast_committed, rv_value, chosen_value,
     replica_arrival, rv_lat, reply_lat) = gw
    Gp = G + pad

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=(
            [pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret))]
            + [spec3] * 2  # vote_value, vote_seen
            + [spec_gw] * 4  # status, open_tick, fast_committed, rv_value
            + [spec3] * 3  # rv_p2a, rv_p2b, rv_voted
            + [spec_gw] * 4  # chosen_value, replica_arrival, rv_lat, reply
        ),
        out_specs=(
            [spec_gw] * 4  # status, open_tick, fast_committed, rv_value
            + [spec3] * 3  # rv_p2a, rv_p2b, rv_voted
            + [spec_gw] * 2  # chosen_value, replica_arrival
            + [spec_gw] * 4  # newly, fast_ok, start_rec, safety
        ),
    )
    i8 = jnp.int8
    out_shape = (
        [
            jax.ShapeDtypeStruct((Gp, W), status.dtype),
            jax.ShapeDtypeStruct((Gp, W), open_tick.dtype),
            jax.ShapeDtypeStruct((Gp, W), fast_committed.dtype),
            jax.ShapeDtypeStruct((Gp, W), rv_value.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), rv_p2a_arrival.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), rv_p2b_arrival.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), i8),  # rv_voted
            jax.ShapeDtypeStruct((Gp, W), chosen_value.dtype),
            jax.ShapeDtypeStruct((Gp, W), replica_arrival.dtype),
        ]
        + [jax.ShapeDtypeStruct((Gp, W), i8)] * 4
    )
    kernel = _fmp_vote_kernel_factory(fq, f, recovery_timeout, A)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        vote_value, vote_seen,
        status, open_tick, fast_committed, rv_value,
        rv_p2a_arrival, rv_p2b_arrival, rv_voted.astype(i8),
        chosen_value, replica_arrival, rv_lat, reply_lat,
    )
    if pad:
        axis1 = {4, 5, 6}  # the [A, G, W] outputs pad axis 1
        outs = [
            x[:, :G] if i in axis1 else x[:G] for i, x in enumerate(outs)
        ]
    (status, open_tick, fast_committed, rv_value, rv_p2a, rv_p2b,
     rv_voted, chosen_value, replica_arrival, newly, fast_ok, start_rec,
     safety) = outs
    return (
        status, open_tick, fast_committed, rv_value,
        rv_p2a, rv_p2b, rv_voted.astype(bool),
        chosen_value, replica_arrival,
        newly.astype(bool), fast_ok.astype(bool), start_rec.astype(bool),
        safety.astype(bool),
    )


registry.register(
    registry.Plane(
        name="fastmultipaxos_vote",
        backend="fastmultipaxos",
        reference=reference_fmp_vote,
        kernel=fused_fmp_vote,
        key_of=lambda args: args[0].shape,  # vote_value: (A, G, W)
        batch_axis=1,  # grids over G
        default_block=256,
    )
)
