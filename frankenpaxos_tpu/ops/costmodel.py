"""Analytical roofline cost model for every registered kernel plane.

The observability gap this closes: the stack measures itself everywhere
(telemetry rings, span samplers, microbench captures) but has no notion
of how fast anything SHOULD be — every perf headline is a raw timing
with no expected-performance anchor. Following the SCALE-Sim line of
work (simple analytical systolic/VMEM/HBM models predict real TPU
kernel time well), each plane gets STATED byte and FLOP terms — the
exact input/output array shapes and dtypes as closed-form functions of
the plane's autotune key, plus a per-cell op-count estimate — and a
roofline evaluator

    seconds = max(bytes / mem_bw, flops / compute) + call_overhead
              + grid_steps * grid_step_overhead

under a named :class:`MachineParams` set (CPU-jit, CPU-interpret, TPU).
Consumers:

  * **efficiency telemetry** — ``harness/microbench.py`` and
    ``bench.py`` record measured/predicted ratios; the serve loops
    export ``fpx_efficiency_*`` gauges (``monitoring/scrape.py``); the
    dashboard's roofline panel plots predicted envelope vs measured
    points;
  * **drift gates** — the ``costmodel-coverage`` / ``costmodel-drift``
    analysis rules: every plane (and every ``common.PACKED_PLANES``
    entry) must carry model terms, and every recorded microbench
    capture must sit inside the model envelope — a perf-regression CI
    gate that needs zero hardware;
  * **model-predicted autotune** — ``registry.block_for`` ranks
    candidate blocks by predicted time for UNSEEN (plane, shape) keys
    instead of guessing by nearest batch extent (recorded table
    entries still win);
  * **saturation prediction** — :func:`predict_saturation` anchors the
    ``bench.py --workload`` capture and :func:`capacity` gives the
    per-role throughput ceilings (batcher / proxy leader / acceptor
    grid / replica — the Compartmentalized MultiPaxos decomposition)
    the ROADMAP elastic-capacity item needs as its feedforward term.

Constants are FIT ONCE against the committed capture pair
``results/kernel_microbench_r10.json`` / ``_r11.json`` (CPU-jit set)
and committed here; the envelope is wide (the captures themselves vary
up to ~4x between rounds on the shared CPU box) but tight enough that
a grossly corrupted timing — or a pre-kernel-layer capture like the
BENCH_r05 headline — trips the gate. Refit procedure: README
"Performance observatory".

Layering: this module imports ONLY the registry (for plane metadata)
and jax (for dtype sizes / eval_shape in tests) — never the harness.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Machine parameter sets
# ---------------------------------------------------------------------------

CONSTANTS_VERSION = 1  # bump on any refit; costmodel-drift cross-checks
# the committed envelope artifact (results/costmodel_envelope.json)
# against this so a stale envelope file is itself a finding.


@dataclasses.dataclass(frozen=True)
class MachineParams:
    """One roofline parameter set. ``mem_bw`` is the EFFECTIVE
    bytes/sec a fused elementwise sweep sustains (far below STREAM
    peak on CPU: the planes are int16/int32 select/compare chains, so
    the fit folds ALU pressure into the single bandwidth term),
    ``flop_rate`` the scalar-equivalent int ops/sec, ``call_overhead_s``
    the per-dispatch fixed cost (jit call + argument plumbing — what
    dominates the tiny scalog plane), ``grid_step_s`` the per-grid-step
    cost of a blocked Pallas launch (large under the interpreter, small
    compiled), ``vmem_bytes`` the per-core working-set budget a block
    must fit in (TPU; None = unconstrained), ``clock_hz`` converts
    seconds to cycles for reporting."""

    name: str
    mem_bw: float  # bytes / second
    flop_rate: float  # scalar int ops / second
    call_overhead_s: float
    grid_step_s: float = 0.0
    vmem_bytes: Optional[int] = None
    clock_hz: float = 1.0e9


# CPU-jit: FIT ONCE against results/kernel_microbench_r10.json +
# _r11.json. The machine constants are fixed at plausible single-core
# figures (20 GB/s effective stream, 2 Gop/s scalar-equivalent, 50 us
# dispatch) and each plane's ``flops_per_cell`` is then the free term
# solved so the plane's geomean measured time across both captures
# lands on the model (the byte terms are exact, so per-plane op count
# is the only honest knob). Worst committed ratio after the fit:
# mencius r11 at 2.16x (the captures themselves vary up to 3.9x
# between rounds on the shared box).
CPU_JIT = MachineParams(
    name="cpu_jit",
    mem_bw=2.0e10,
    flop_rate=2.0e9,
    call_overhead_s=5.0e-5,
    grid_step_s=0.0,
    vmem_bytes=None,
    clock_hz=3.0e9,
)

# CPU-interpret: the Pallas interpreter pays a large per-grid-step
# Python/callback cost, so bigger blocks (fewer steps) always win —
# exactly the behavior the CPU-seeded autotune table records. STATED,
# not capture-fit (interpret timings are not captured; timing the
# interpreter is meaningless for perf, only its SHAPE matters for
# block ranking).
CPU_INTERPRET = MachineParams(
    name="cpu_interpret",
    mem_bw=2.0e8,
    flop_rate=2.0e8,
    call_overhead_s=5.0e-3,
    grid_step_s=2.0e-3,
    vmem_bytes=None,
    clock_hz=3.0e9,
)

# TPU v5e-class: ~819 GB/s HBM, ~16 MB VMEM/core (pallas guide), VPU
# int32 throughput O(1e12) scalar ops/s, ~1 GHz clock. PENDING
# HARDWARE VALIDATION — no committed capture carries real-TPU plane
# timings yet (the autotune table itself is CPU-seeded); when one
# lands, refit and bump CONSTANTS_VERSION.
TPU_V5E = MachineParams(
    name="tpu_v5e",
    mem_bw=8.19e11,
    flop_rate=2.0e12,
    call_overhead_s=5.0e-6,
    grid_step_s=1.0e-6,
    vmem_bytes=16 * 1024 * 1024,
    clock_hz=9.4e8,
)

PARAM_SETS = {p.name: p for p in (CPU_JIT, CPU_INTERPRET, TPU_V5E)}

# measured/predicted ratio bounds: a capture outside [LO, HI] is a
# costmodel-drift finding. Wide enough for the committed capture pair
# (per-plane ratios span [0.55, 2.16] after the fit; plane rates vary
# up to ~3.9x between r10 and r11 on the shared box), tight enough
# that a corrupted timing (or a 10x regression) trips.
ENVELOPE = (0.25, 4.0)
# round-over-round ratio regression bound: consecutive captures of the
# same plane whose measured/predicted ratio moved more than this
# factor are a finding even inside the absolute envelope.
REGRESSION_FACTOR = 5.0

# dtype sizes without importing numpy at module scope (jax is already
# a hard dependency of the package).
_ITEMSIZE = {"bool": 1, "int8": 1, "int16": 2, "int32": 4, "uint32": 4}

Spec = Tuple[Tuple[int, ...], str]  # (shape, dtype name)


def _nbytes(specs: Sequence[Spec]) -> int:
    total = 0
    for shape, dtype in specs:
        total += math.prod(shape) * _ITEMSIZE[dtype]
    return total


# ---------------------------------------------------------------------------
# Per-plane byte / FLOP terms
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlaneModel:
    """Stated cost terms for one plane, all closed-form in the plane's
    autotune key (``registry.Plane.key_of`` order). ``inputs`` /
    ``outputs`` list every array the dispatch reads / writes —
    EXACTLY the shapes and dtypes the reference twin sees (pinned by
    tests/test_costmodel.py against live arrays + ``jax.eval_shape``).
    ``flops_per_cell`` is the per-cell scalar-op estimate (compares,
    selects, adds of the plane's hot loop) over ``cells(key)``."""

    name: str
    inputs: Callable[[Tuple[int, ...]], List[Spec]]
    outputs: Callable[[Tuple[int, ...]], List[Spec]]
    cells: Callable[[Tuple[int, ...]], int]
    flops_per_cell: int
    # which key index the autotune grid tiles (mirrors Plane.batch_axis)
    batch_axis: int = 0
    note: str = ""


def _mp_vote_quorum(key):
    A, G, W = key
    return [
        ((A, G, W), "int16"),
        ((A, G), "int16"),
        ((G,), "int16"),
        ((G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((A, G, W), "bool"),
        ((G,), "int32"),
    ]


def _mp_vote_quorum_out(key):
    A, G, W = key
    return [
        ((A, G, W), "int16"),
        ((A, G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G), "int16"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((A, G), "int32"),
    ]


def _mp_p1_promise(key):
    A, G, W = key
    return [
        ((G, W), "int8"),
        ((A, G, W), "int16"),
        ((A, G, W), "int32"),
        ((G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int32"),
        ((G,), "bool"),
        ((A, G), "bool"),
        ((A, G, W), "int16"),
        ((), "int32"),
    ]


def _mp_p1_promise_out(key):
    A, G, W = key
    return [
        ((G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int32"),
    ]


def _mp_dispatch(key):
    A, G, W = key
    return [
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int16"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((A, G, W), "int32"),
        ((G, W), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G,), "int16"),
        ((G,), "int32"),
        ((G,), "bool"),
        ((A, G, W), "bool"),
        ((A, G, W), "bool"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int32"),
        ((G,), "int32"),
        ((), "int32"),
    ]


def _mp_dispatch_out(key):
    A, G, W = key
    return [
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int16"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((A, G, W), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "int32"),
    ]


def _mp_fused_tick(key):
    A, G, W = key
    # vote_quorum inputs + the dispatch-only inputs (the fused plane
    # consumes both stages' state in one pass; promise state rides the
    # same arrays).
    return _mp_vote_quorum(key) + [
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int16"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G,), "bool"),
        ((A, G, W), "bool"),
        ((A, G, W), "bool"),
        ((A, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int32"),
        ((G,), "int32"),
        ((), "int32"),
    ]


def _mp_fused_tick_out(key):
    A, G, W = key
    return _mp_dispatch_out(key) + [
        ((A, G), "int16"),
        ((G, W), "int32"),
        ((A, G), "int32"),
    ]


def _fast_vote(key):
    A, G, W = key
    return [
        ((A, G, W), "int32"),
        ((A, G, W), "int32"),
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((A, G, W), "int32"),
        ((A, G, W), "int32"),
        ((A, G, W), "bool"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((), "int32"),
    ]


def _fast_vote_out(key):
    A, G, W = key
    return [
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((A, G, W), "int32"),
        ((A, G, W), "int32"),
        ((A, G, W), "bool"),
        ((G, W), "int32"),
        ((G, W), "int32"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "bool"),
    ]


def _horizontal_vote(key):
    P, G, W = key
    return [
        ((G, W), "int16"),
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((P, G, W), "int32"),
        ((P, G, W), "int32"),
        ((P, G, W), "bool"),
        ((P, G, W), "int16"),
        ((P, G, W), "int32"),
        ((P, G, W), "bool"),
        ((), "int32"),
    ]


def _horizontal_vote_out(key):
    P, G, W = key
    return [
        ((G, W), "int8"),
        ((P, G, W), "int32"),
        ((P, G, W), "int32"),
        ((P, G, W), "bool"),
        ((P, G, W), "int16"),
        ((G, W), "bool"),
        ((G, W), "int32"),
        ((G, W), "int32"),
    ]


def _scalog_cut(key):
    P, S = key
    return [
        ((P, S), "int32"),
        ((P,), "int32"),
        ((P,), "int32"),
        ((P,), "int32"),
        ((S,), "int32"),
        ((), "int32"),
        ((), "int32"),
        ((), "int32"),
    ]


def _scalog_cut_out(key):
    P, S = key
    return [
        ((S,), "int32"),
        ((P,), "bool"),
        ((P,), "int32"),
        ((P,), "int32"),
        ((P,), "bool"),
        ((P,), "int32"),
        ((P,), "int32"),
    ]


def _mencius_vote(key):
    L, W, A = key
    return [
        ((L, W, A), "int32"),
        ((L, W, A), "bool"),
        ((L, W, A), "int32"),
        ((L, W, A), "int32"),
        ((L, W, A), "bool"),
        ((), "int32"),
    ]


def _mencius_vote_out(key):
    L, W, A = key
    return [
        ((L, W, A), "bool"),
        ((L, W, A), "int32"),
        ((L, W), "int32"),
    ]


def _craq_chain(key):
    # key = (N, L*KV, CW): N chains, chain-length x keyspace log
    # columns, CW-wide write ring. The chain length itself is not in
    # the key (L=3 at every recorded shape).
    N, LK, CW = key
    return [
        ((N, CW), "int8"),
        ((N, CW), "int32"),
        ((N, CW), "int32"),
        ((N, CW), "int32"),
        ((N, CW), "int32"),
        ((N, CW), "int32"),
        ((N, LK), "int32"),
        ((N, LK), "int32"),
        ((N, CW), "int32"),
        ((), "int32"),
    ]


def _craq_chain_out(key):
    N, LK, CW = key
    return [
        ((N, CW), "int8"),
        ((N, CW), "int32"),
        ((N, CW), "int32"),
        ((N, LK), "int32"),
        ((N, LK), "int32"),
        ((N, CW), "bool"),
        ((N, CW), "int32"),
    ]


def _grid_vote(key):
    R, C, G, W = key
    A = R + C - 1  # transversal acceptors touched per command
    return [
        ((R, C, G, W), "int16"),
        ((R, C, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((A, G), "int32"),
        ((G,), "int32"),
        ((G,), "int32"),
        ((G, W), "bool"),
        ((R, C, G, W), "bool"),
        ((R, C, G, W), "bool"),
        ((R, C, G, W), "int32"),
        ((R, C, G, W), "int32"),
        ((A, G, W), "int32"),
        ((), "int32"),
    ]


def _grid_vote_out(key):
    R, C, G, W = key
    A = R + C - 1
    return [
        ((R, C, G, W), "int16"),
        ((R, C, G, W), "int16"),
        ((A, G, W), "int16"),
        ((G, W), "int8"),
        ((G, W), "int32"),
        ((A, G), "int32"),
        ((G, W), "bool"),
        ((G, W), "bool"),
        ((G, W), "int32"),
        ((G, W), "int32"),
    ]


def _depgraph_execute(key):
    B, V, VW = key
    return [
        ((B, V, VW), "uint32"),
        ((B, V), "bool"),
        ((B, V), "bool"),
    ]


def _depgraph_execute_out(key):
    B, V, VW = key
    return [
        ((B, V), "bool"),
        ((B, V), "int32"),
        ((B, V), "int32"),
    ]


MODELS: Dict[str, PlaneModel] = {}


def _model(m: PlaneModel) -> PlaneModel:
    assert m.name not in MODELS, f"duplicate cost model {m.name}"
    MODELS[m.name] = m
    return m


_model(PlaneModel(
    "multipaxos_vote_quorum", _mp_vote_quorum, _mp_vote_quorum_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=27, batch_axis=1,
    note="clock aging + phase2b vote compare + quorum count per "
         "[A, G, W] cell (~8 selects, ~12 compares, quorum add tree)",
))
_model(PlaneModel(
    "multipaxos_p1_promise", _mp_p1_promise, _mp_p1_promise_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=15, batch_axis=1,
    note="phase1b promise merge: per-cell max-ballot compare/select "
         "chain over the acceptor axis",
))
_model(PlaneModel(
    "multipaxos_dispatch", _mp_dispatch, _mp_dispatch_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=21, batch_axis=1,
    note="chosen-watermark scan, retry clocks, window roll: the widest "
         "per-cell select chain of the three multipaxos planes",
))
_model(PlaneModel(
    "multipaxos_fused_tick", _mp_fused_tick, _mp_fused_tick_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=51, batch_axis=1,
    note="vote_quorum + p1_promise + dispatch + aging in one pass "
         "(the flops add; the bytes DON'T — that's the fusion win)",
))
_model(PlaneModel(
    "fastmultipaxos_vote", _fast_vote, _fast_vote_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=169, batch_axis=1,
    note="fast/classic dual-quorum count + conflict detection + "
         "recovery clocks per [A, G, W] cell",
))
_model(PlaneModel(
    "horizontal_vote", _horizontal_vote, _horizontal_vote_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=17, batch_axis=1,
    note="per-chunk vote + reconfiguration-epoch filter over the "
         "[P=2n, G, W] acceptor-page axis",
))
_model(PlaneModel(
    "scalog_cut_commit", _scalog_cut, _scalog_cut_out,
    cells=lambda k: k[0] * k[1], flops_per_cell=5, batch_axis=1,
    note="in-order cut commit scan: cumulative max over the [P] ring "
         "+ per-[P, S] newest-cut projection; call overhead dominates "
         "at the flagship shape (the arrays are ~100 KB)",
))
_model(PlaneModel(
    "mencius_vote", _mencius_vote, _mencius_vote_out,
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=5, batch_axis=0,
    note="striped-log quorum count + skip resolution per [L, W, A]",
))
_model(PlaneModel(
    "craq_chain", _craq_chain, _craq_chain_out,
    cells=lambda k: k[0] * (k[1] + k[2]), flops_per_cell=310,
    batch_axis=0,
    note="chain propagation + version-vector apply over the write "
         "ring [N, CW] and kv log [N, L*KV] columns",
))
_model(PlaneModel(
    "depgraph_execute", _depgraph_execute, _depgraph_execute_out,
    # The closure dominates: ceil(log2(Vp)) boolean matmul squarings
    # over the [Vp, Vp] reachability matrix (Vp = 32*VW padded
    # vertices) per batch row — a cell here is one multiply-add LANE of
    # one squaring (Vp^3 lanes per matmul), so flops_per_cell is the
    # mul+add pair. The SCC/order epilogue is O(Vp^2) — inside the
    # matmul term's margin.
    cells=lambda k: (
        k[0] * (32 * k[2]) ** 3 * max(1, (32 * k[2] - 1).bit_length())
    ),
    flops_per_cell=2,
    batch_axis=0,
    note="log-depth bitmask transitive closure: ceil(log2(Vp)) f32 "
         "matmul squarings of the [Vp, Vp] reachability seed + SCC "
         "root/order epilogue, batched over graph views",
))
_model(PlaneModel(
    "compartmentalized_grid_vote", _grid_vote, _grid_vote_out,
    cells=lambda k: k[0] * k[1] * k[2] * k[3], flops_per_cell=15,
    batch_axis=2,
    note="acceptor-grid transversal: column write votes + every-row "
         "read quorum per [R, C, G, W] cell",
))

# The UNFUSED reference tick: the pre-kernel-layer multipaxos tick ran
# vote_quorum, p1_promise, and dispatch as three separate sweeps, each
# spilling its state round trip to memory — same flops as the fused
# plane, ~2.4x the bytes. Key = (A, G, W). This entry is what the
# fused-vs-multiplane microbench rows validate and what
# predict_saturation prices.
_UNFUSED_PARTS = (
    "multipaxos_vote_quorum", "multipaxos_p1_promise",
    "multipaxos_dispatch",
)
_model(PlaneModel(
    "multipaxos_unfused_tick",
    inputs=lambda k: [
        s for p in _UNFUSED_PARTS for s in MODELS[p].inputs(k)
    ],
    outputs=lambda k: [
        s for p in _UNFUSED_PARTS for s in MODELS[p].outputs(k)
    ],
    cells=lambda k: k[0] * k[1] * k[2], flops_per_cell=63, batch_axis=1,
    note="the three multipaxos planes as separate sweeps (every "
         "inter-plane intermediate makes a memory round trip)",
))


# ---------------------------------------------------------------------------
# Packed-plane terms (tpu/common.PACKED_PLANES, PR 16)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PackedPlaneModel:
    """Byte/FLOP terms for one bit-packed state plane: ``bits`` values
    pack ``32 // bits`` per int32 word (tpu/packing.py little-endian
    layout), so a plane of ``n`` logical values stores
    ``ceil(n / (32 // bits)) * 4`` bytes and pays ~4 scalar ops per
    value per unpack-at-entry/pack-at-exit crossing (shift + mask each
    way)."""

    name: str
    bits: int
    flops_per_value: int = 4

    def packed_bytes(self, n_values: int) -> int:
        per_word = 32 // self.bits
        return ((n_values + per_word - 1) // per_word) * 4

    def unpacked_bytes(self, n_values: int, itemsize: int = 1) -> int:
        return n_values * itemsize

    def crossing_flops(self, n_values: int) -> int:
        return self.flops_per_value * n_values


PACKED_MODELS: Dict[str, PackedPlaneModel] = {
    "status": PackedPlaneModel("status", bits=2),
    "rb_status": PackedPlaneModel("rb_status", bits=2),
    "sess_occ": PackedPlaneModel("sess_occ", bits=1),
}


# ---------------------------------------------------------------------------
# Roofline evaluation
# ---------------------------------------------------------------------------


def input_bytes(name: str, key: Tuple[int, ...]) -> int:
    return _nbytes(MODELS[name].inputs(tuple(key)))


def output_bytes(name: str, key: Tuple[int, ...]) -> int:
    return _nbytes(MODELS[name].outputs(tuple(key)))


def bytes_moved(name: str, key: Tuple[int, ...]) -> int:
    """Total memory traffic of one dispatch: every input read once +
    every output written once (the VMEM-resident fusion model —
    intermediates stay on chip)."""
    return input_bytes(name, key) + output_bytes(name, key)


def flops(name: str, key: Tuple[int, ...]) -> int:
    m = MODELS[name]
    return m.flops_per_cell * m.cells(tuple(key))


def _grid_steps(name: str, key: Tuple[int, ...], block: int) -> int:
    m = MODELS[name]
    extent = tuple(key)[m.batch_axis]
    return max(1, -(-extent // max(1, block)))


def block_bytes(name: str, key: Tuple[int, ...], block: int) -> int:
    """Working-set bytes of one grid step: the per-batch-row byte
    density times the block extent (what must fit in VMEM)."""
    m = MODELS[name]
    extent = max(1, tuple(key)[m.batch_axis])
    per_row = bytes_moved(name, key) / extent
    return int(per_row * min(block, extent))


def predict_seconds(
    name: str,
    key: Tuple[int, ...],
    params: MachineParams = CPU_JIT,
    block: Optional[int] = None,
) -> float:
    """Roofline time of one dispatch of ``name`` at ``key`` under
    ``params``: max(memory time, compute time) + fixed call overhead +
    per-grid-step launch cost (0 steps-dependent cost when ``block``
    is None — the unblocked jit path)."""
    b = bytes_moved(name, key)
    f = flops(name, key)
    t = max(b / params.mem_bw, f / params.flop_rate)
    t += params.call_overhead_s
    if block is not None and params.grid_step_s:
        t += _grid_steps(name, key, block) * params.grid_step_s
    return t


def predict_per_sec(
    name: str,
    key: Tuple[int, ...],
    params: MachineParams = CPU_JIT,
    block: Optional[int] = None,
) -> float:
    return 1.0 / predict_seconds(name, key, params, block)


def predict_cycles(
    name: str,
    key: Tuple[int, ...],
    params: MachineParams = CPU_JIT,
    block: Optional[int] = None,
) -> int:
    return int(predict_seconds(name, key, params, block) * params.clock_hz)


# ---------------------------------------------------------------------------
# Model-ranked block selection (the registry's autotune fallback)
# ---------------------------------------------------------------------------

# Mirrors harness/microbench.AUTOTUNE_BLOCKS (stated here so the ops
# layer never imports the harness; tests/test_costmodel.py pins the
# two tuples equal).
CANDIDATE_BLOCKS = (128, 256, 512, 1024)


def rank_blocks(
    name: str,
    key: Tuple[int, ...],
    params: Optional[MachineParams] = None,
    candidates: Sequence[int] = CANDIDATE_BLOCKS,
) -> List[Tuple[int, float]]:
    """Candidate blocks sorted by predicted time (best first), VMEM-
    infeasible blocks excluded (unless that excludes everything, in
    which case the smallest block survives — better a spilling guess
    than a crash)."""
    if params is None:
        params = params_for_backend()
    scored = []
    for blk in candidates:
        if (
            params.vmem_bytes is not None
            and block_bytes(name, key, blk) > params.vmem_bytes
        ):
            continue
        scored.append((blk, predict_seconds(name, key, params, blk)))
    if not scored:
        blk = min(candidates)
        scored = [(blk, predict_seconds(name, key, params, blk))]
    return sorted(scored, key=lambda t: (t[1], t[0]))


def model_block(
    name: str,
    key: Tuple[int, ...],
    params: Optional[MachineParams] = None,
) -> Optional[int]:
    """Best predicted block for an UNSEEN (plane, shape) key, or None
    when the plane has no model (the registry then falls back to its
    legacy nearest-batch-extent guess)."""
    if name not in MODELS:
        return None
    return rank_blocks(name, key, params)[0][0]


def params_for_backend(backend: Optional[str] = None) -> MachineParams:
    """The parameter set matching the active jax backend: TPU backends
    get the TPU set (the Pallas kernel runs), everything else the
    CPU-interpret set for block ranking is WRONG — off-TPU the
    registry only engages kernels under interpret mode, but block
    choice there only affects CI speed, so the interpret set is
    exactly right for it."""
    if backend is None:
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # pragma: no cover - jaxless callers
            backend = "cpu"
    from frankenpaxos_tpu.ops import registry

    if backend in registry.TPU_BACKENDS:
        return TPU_V5E
    return CPU_INTERPRET


# ---------------------------------------------------------------------------
# Capture validation (the costmodel-drift engine)
# ---------------------------------------------------------------------------

# The shapes every kernel_microbench capture measures at
# (harness/microbench._kernel_cases defaults) — the captures record
# only rates, so the model re-derives the keys from this table.
CAPTURE_KEYS: Dict[str, Tuple[int, ...]] = {
    "multipaxos_vote_quorum": (3, 3334, 64),
    "multipaxos_p1_promise": (3, 3334, 64),
    "multipaxos_dispatch": (3, 3334, 64),
    "multipaxos_fused_tick": (3, 3334, 64),
    "fastmultipaxos_vote": (3, 3334, 64),
    "horizontal_vote": (6, 3334, 64),
    "scalog_cut_commit": (8, 3334),
    "mencius_vote": (3334, 64, 3),
    "craq_chain": (3334, 48, 16),
    "compartmentalized_grid_vote": (2, 2, 3334, 64),
    "depgraph_execute": (208, 64, 2),
}


def validate_capture(
    capture: dict,
    params: MachineParams = CPU_JIT,
    envelope: Tuple[float, float] = ENVELOPE,
) -> List[dict]:
    """Measured/predicted verdicts for one kernel_microbench capture
    payload (the ``kernels`` block, or a whole capture dict carrying
    one). Rows: ``{plane, measured_per_sec, predicted_per_sec, ratio,
    ok}``; planes without a recorded rate or a capture key are
    skipped (coverage is the costmodel-coverage rule's job)."""
    kernels = capture.get("kernels", capture)
    planes = kernels.get("planes", {})
    rows: List[dict] = []
    for plane, entry in sorted(planes.items()):
        measured = entry.get("reference_per_sec")
        key = CAPTURE_KEYS.get(plane)
        if not measured or key is None or plane not in MODELS:
            continue
        predicted = predict_per_sec(plane, key, params)
        ratio = measured / predicted
        rows.append(
            {
                "plane": plane,
                "key": list(key),
                "measured_per_sec": round(float(measured), 2),
                "predicted_per_sec": round(predicted, 2),
                "ratio": round(ratio, 4),
                "ok": envelope[0] <= ratio <= envelope[1],
            }
        )
    return rows


def drift_findings(
    captures: Sequence[Tuple[str, dict]],
    params: MachineParams = CPU_JIT,
    envelope: Tuple[float, float] = ENVELOPE,
    regression_factor: float = REGRESSION_FACTOR,
) -> List[dict]:
    """The costmodel-drift engine over an ORDERED capture sequence
    (oldest first): a row per violation — a plane outside the
    absolute envelope, or a plane whose measured/predicted ratio
    moved more than ``regression_factor`` between consecutive
    captures. Pure data-in/data-out so the analysis rule and its
    teeth test share one engine."""
    out: List[dict] = []
    prev: Dict[str, Tuple[str, float]] = {}
    for label, capture in captures:
        for row in validate_capture(capture, params, envelope):
            plane, ratio = row["plane"], row["ratio"]
            if not row["ok"]:
                out.append(
                    {
                        "kind": "envelope",
                        "capture": label,
                        "plane": plane,
                        "ratio": ratio,
                        "message": (
                            f"{label}: {plane} measured/predicted "
                            f"ratio {ratio} outside the model "
                            f"envelope [{envelope[0]}, {envelope[1]}]"
                        ),
                    }
                )
            if plane in prev:
                prev_label, prev_ratio = prev[plane]
                move = ratio / prev_ratio if prev_ratio else float("inf")
                if move > regression_factor or move < 1 / regression_factor:
                    out.append(
                        {
                            "kind": "regression",
                            "capture": label,
                            "plane": plane,
                            "ratio": ratio,
                            "message": (
                                f"{label}: {plane} ratio {ratio} moved "
                                f"{round(move, 2)}x vs {prev_label} "
                                f"({prev_ratio}) — past the "
                                f"{regression_factor}x drift bound"
                            ),
                        }
                    )
            prev[plane] = (label, ratio)
    return out


def envelope_confidence(payload: Optional[dict] = None) -> dict:
    """Confidence in the model's capacity feedforward, derived from
    the ENVELOPE SPREAD of the committed capture verdicts: the ratio
    between the widest and tightest measured/predicted ratio across
    every capture row in ``results/costmodel_envelope.json`` (or a
    payload passed in directly). A model whose predictions track the
    measurements inside a narrow band earns confidence ~1.0; a wide
    spread decays it as ``1/spread``; no capture evidence at all is
    0.0 — consumers (``monitoring/autoscaler.py`` weighting its
    scale-up stride) then fall back to their conservative
    one-increment behaviour. Returns ``{samples, spread, confidence,
    source}``."""
    import json
    import pathlib

    source = "payload"
    if payload is None:
        path = (
            pathlib.Path(__file__).resolve().parents[2]
            / "results"
            / "costmodel_envelope.json"
        )
        source = path.name
        try:
            payload = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            payload = {}
    ratios = [
        row["ratio"]
        for rows in payload.get("captures", {}).values()
        for row in rows
        if row.get("ratio")
    ]
    if not ratios:
        return {
            "samples": 0,
            "spread": None,
            "confidence": 0.0,
            "source": source,
        }
    spread = max(ratios) / min(ratios)
    return {
        "samples": len(ratios),
        "spread": round(spread, 4),
        "confidence": round(min(1.0, 1.0 / spread), 4),
        "source": source,
    }


# ---------------------------------------------------------------------------
# Whole-protocol prediction: saturation + per-role capacity
# ---------------------------------------------------------------------------


def commit_round_trip_ticks(lat_min: int, lat_max: int) -> float:
    """Expected phase-2 round trip in ticks: two one-way hops at the
    mean simulated latency, plus the commit-visibility tick."""
    return 2.0 * (lat_min + lat_max) / 2.0 + 1.0


def predict_saturation(
    num_groups: int,
    window: int,
    slots_per_tick: int,
    lat_min: int = 1,
    lat_max: int = 3,
    params: MachineParams = CPU_JIT,
    key: Tuple[int, int, int] = None,
) -> dict:
    """Pre-run saturation prediction for the multipaxos flagship
    (``bench.py --workload``): per-tick commits are issue-bound
    (``slots_per_tick``) unless the in-flight window stalls the
    pipeline (``window / round_trip``); ticks/sec comes from the
    fused-tick roofline plus a tick-machinery factor for the steps
    outside the plane (workload engine, stats, RNG — measured at
    ~2-3x the plane alone on CPU, folded into one constant)."""
    rt = commit_round_trip_ticks(lat_min, lat_max)
    per_lane = min(float(slots_per_tick), window / rt)
    per_tick = per_lane * num_groups
    if key is None:
        key = (3, num_groups, window)
    tick_s = predict_seconds("multipaxos_fused_tick", key, params)
    # Everything the tick runs AROUND the fused plane (workload
    # engine, faults, telemetry, invariant inputs): fit against
    # WORKLOAD_r01 (16.4 ticks/s) vs the r10/r11 fused-tick reference
    # rate (51-73/s) — the machinery roughly triples the plane time.
    TICK_MACHINERY_FACTOR = 3.0
    ticks_per_sec = 1.0 / (tick_s * TICK_MACHINERY_FACTOR)
    return {
        "committed_per_tick": round(per_tick, 2),
        "rate_per_lane_per_tick": round(per_lane, 4),
        "round_trip_ticks": rt,
        "ticks_per_sec": round(ticks_per_sec, 2),
        "committed_per_sec": round(per_tick * ticks_per_sec, 1),
        "params": params.name,
    }


# Per-command work of each Compartmentalized MultiPaxos role (arxiv
# 2012.15762 decomposition), stated as bytes touched + scalar ops per
# command through that role. The absolute scale is the CPU_JIT /
# TPU_V5E roofline; the RELATIVE ratios encode the paper's
# bottleneck ordering (proxy leaders do the wide fan-out, batchers
# amortize, acceptor rows touch one grid transversal, replicas
# execute + broadcast).
ROLE_COSTS: Dict[str, Tuple[int, int]] = {
    # role: (bytes_per_command, flops_per_command)
    "batcher": (64, 20),
    "leader": (96, 40),
    "proxy_leader": (256, 120),
    "acceptor": (128, 60),
    "replica": (192, 100),
    # Unbatchers split replica result batches back into per-client
    # replies — pure dissemination, the cheapest role on the path
    # (HT-Paxos arxiv 1407.1237 puts the batch/unbatch pair on
    # opposite ends of the amortization).
    "unbatcher": (48, 16),
}


def role_rate(role: str, params: MachineParams = CPU_JIT) -> float:
    """Commands/sec ONE instance of ``role`` sustains under the
    roofline (amortized: no per-command call overhead — roles batch)."""
    b, f = ROLE_COSTS[role]
    return 1.0 / max(b / params.mem_bw, f / params.flop_rate)


def capacity(
    role_counts: Dict[str, int],
    params: MachineParams = CPU_JIT,
) -> dict:
    """Feedforward capacity of a compartmentalized deployment: each
    role's aggregate commands/sec ceiling (count x per-instance rate)
    and the system bottleneck — the min. Unknown roles raise (a
    mis-spelled role silently predicting infinity would defeat the
    elastic-capacity consumer)."""
    for role in role_counts:
        if role not in ROLE_COSTS:
            raise KeyError(
                f"unknown role {role!r}; known: {sorted(ROLE_COSTS)}"
            )
    ceilings = {
        role: count * role_rate(role, params)
        for role, count in role_counts.items()
    }
    bottleneck = min(ceilings, key=ceilings.get) if ceilings else None
    return {
        "per_role_commands_per_sec": {
            r: round(v, 1) for r, v in sorted(ceilings.items())
        },
        "bottleneck_role": bottleneck,
        "commands_per_sec": (
            round(ceilings[bottleneck], 1) if bottleneck else 0.0
        ),
        "params": params.name,
    }


# ---------------------------------------------------------------------------
# Serve-loop anchors + capture plausibility
# ---------------------------------------------------------------------------


def expected_commit_rate_per_tick(cfg) -> float:
    """The model's expected commits/tick/instance for a backend config
    — the fleet_summary straggler anchor (previously a hand-fed
    constant). Covers configs with the multipaxos-family shape
    (num_groups / window / slots_per_tick); an offered-load plan caps
    the protocol ceiling at what the workload admits. Returns 0.0
    (anchor off) for shapes the model does not cover — a wrong anchor
    flags healthy instances, so unknown stays OFF."""
    G = getattr(cfg, "num_groups", None)
    W = getattr(cfg, "window", None)
    K = getattr(cfg, "slots_per_tick", None)
    if not (G and W and K):
        return 0.0
    rt = commit_round_trip_ticks(
        getattr(cfg, "lat_min", 1), getattr(cfg, "lat_max", 3)
    )
    per_lane = min(float(K), W / rt)
    plan = getattr(cfg, "workload", None)
    if plan is not None and getattr(plan, "shaped", False):
        rate = float(getattr(plan, "rate", 0.0))
        if rate > 0.0:
            per_lane = min(per_lane, rate)
    return per_lane * G


# Plausibility band for whole-capture headlines (committed entries/sec
# vs the model's saturation throughput on the capture's device class).
# Much wider than ENVELOPE: a headline this far off isn't noise, it's
# a capture measuring different code than the tree (the BENCH_r05
# case: a pre-kernel-layer TPU capture ~80x under the model's
# hardware ceiling).
PLAUSIBLE_RATIO = (1 / 40.0, 40.0)


def flag_capture(result: dict) -> dict:
    """Stale-capture honesty: annotate a bench headline with its
    measured/predicted ratio and an explicit ``model_flagged`` field
    when the ratio is outside :data:`PLAUSIBLE_RATIO` — the capture
    still surfaces (it is the honest last-known-good), but never
    silently. Mutates and returns ``result``."""
    value = result.get("value")
    if not value:
        return result
    device = str(result.get("device", ""))
    params = TPU_V5E if ("TPU" in device or "tpu" in device) else CPU_JIT
    pred = predict_saturation(3334, 64, 8, params=params)
    predicted = pred["committed_per_sec"]
    ratio = float(value) / predicted if predicted else 0.0
    result["model_check"] = {
        "predicted_entries_per_sec": predicted,
        "ratio": round(ratio, 5),
        "plausible_band": list(PLAUSIBLE_RATIO),
        "params": params.name,
        "constants_version": CONSTANTS_VERSION,
    }
    flagged = not (PLAUSIBLE_RATIO[0] <= ratio <= PLAUSIBLE_RATIO[1])
    result["model_flagged"] = flagged
    if flagged:
        result["model_flag_reason"] = (
            f"measured {value} entries/sec is {round(ratio, 5)}x the "
            f"model's predicted saturation ({predicted}) on "
            f"{params.name} — outside the plausible band "
            f"{list(PLAUSIBLE_RATIO)}; the capture predates the "
            "current kernel layer and must be re-measured"
        )
    return result
