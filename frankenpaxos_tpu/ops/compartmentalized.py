"""Fused Pallas kernel for the Compartmentalized MultiPaxos acceptor
grid — the backend's hot path (``tpu/compartmentalized_batched.py``).

One plane, ``compartmentalized_grid_vote``, covers the whole per-tick
sweep over the wide ``[R, C, G, W]`` grid arrays plus the ``[NR, G, W]``
replica commit plane:

  * offset-clock AGING of the Phase2a / Phase2b / commit-broadcast
    clocks (tpu/common.py delta encoding: 0 = arrives now),
  * column-transversal WRITE VOTES: acceptors with a Phase2a arriving
    now send Phase2b to the slot's proxy leader (idempotent min-write),
  * EVERY-ROW-VOTED CHOSEN detection: a slot is chosen when every grid
    row has a vote in (quorums/Grid.scala — any write transversal
    intersects any read row), gated on the slot's proxy being alive,
  * the commit broadcast arming (proxy -> every replica) and each
    replica's PER-REPLICA WATERMARK advance (masked min over the
    contiguous arrived prefix — no gather),
  * RETRY RE-SEND: timed-out PROPOSED slots re-broadcast Phase2a to the
    FULL grid (overwrite, not min-write — see the backend).

In the unfused tick these steps are ~10 separate XLA sweeps that each
re-read the two largest state arrays from HBM; here every ``[R, C, G,
W]`` cell is read once and the vote/quorum intermediates never leave
VMEM. The reference twin is EXACTLY the tick composition the backend
executed before the plane was fused (the retry step commutes with the
retire/sequencing steps between them — their write masks are disjoint
by construction: retries touch only slots that stay PROPOSED, retires
only CHOSEN ones, fresh sends only newly-allocated ones), pinned bit
for bit by tests/test_ops.py and tests/test_kernel_registry.py.

The grid cells R x C and the replica count NR are tiny static leading
axes (static in-kernel loops, like the multipaxos acceptor axis); the
group axis G grids over blocks and W rides the VPU lanes. Every array
keeps its state dtype (int16 offset clocks, int8 statuses) — no
boundary casts. The plane is group-local (no cross-group dataflow), so
it declares a :class:`registry.ShardSpec` and lowers per-device under
``jax.shard_map`` on a mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import balanced_block, pad_axis, t_arr, t_space
from frankenpaxos_tpu.tpu.common import age_clock

# Mirrors of the backend's batch-slot codes (ops must not import the
# backend). Cross-checked by tests/test_kernel_registry.py.
EMPTY = 0
PROPOSED = 1
CHOSEN = 2

# Group-axis position per positional argument / output of the plane
# (None = scalar). ONE table drives the wrappers' padding, the output
# slicing, AND the registration's ShardSpec, so the three can never
# drift apart. Argument order: p2a, p2b, rep_arrival, status,
# last_send, rep_exec, head, next_slot, alive_of_pos, p2b_del,
# retry_del, p2b_lat, retry_lat, rep_lat, t. Output order: p2a, p2b,
# rep_arrival, status, last_send, rep_exec, newly_chosen, timed_out,
# votes_cast, votes_dropped.
ARG_G_AXES = (2, 2, 1, 0, 0, 1, 0, 0, 0, 2, 2, 2, 2, 1, None)
OUT_G_AXES = (2, 2, 1, 0, 0, 1, 0, 0, 0, 0)


def _pad_args(args, pad):
    """Pad every array argument's group axis up to a block multiple
    (scalars pass through)."""
    if not pad:
        return args
    return tuple(
        x if ax is None else pad_axis(x, ax, pad)
        for x, ax in zip(args, ARG_G_AXES)
    )


def _slice_outs(outs, G, pad):
    """Slice the group-axis padding back off every output."""
    if not pad:
        return list(outs)
    return [
        x[(slice(None),) * ax + (slice(0, G),)]
        for x, ax in zip(outs, OUT_G_AXES)
    ]


def _specs(pl, R, C, NR, bg, W, interpret):
    """The shared BlockSpec vocabulary of the fused and unfused
    wrappers: t (SMEM scalar), 4-D grid cells, replica planes, replica
    watermarks, [G] vectors, [G, W] slot planes."""
    return dict(
        t=pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret)),
        rcgw=pl.BlockSpec((R, C, bg, W), lambda i: (0, 0, i, 0)),
        ngw=pl.BlockSpec((NR, bg, W), lambda i: (0, i, 0)),
        ng=pl.BlockSpec((NR, bg), lambda i: (0, i)),
        g=pl.BlockSpec((bg,), lambda i: (i,)),
        gw=pl.BlockSpec((bg, W), lambda i: (i, 0)),
    )


def reference_grid_vote(
    p2a,  # [R, C, G, W] Phase2a offset clocks (RAW: aged in-plane)
    p2b,  # [R, C, G, W] Phase2b offset clocks (RAW: aged in-plane)
    rep_arrival,  # [NR, G, W] commit-broadcast clocks (RAW: aged in-plane)
    status,  # [G, W] int8 EMPTY | PROPOSED | CHOSEN
    last_send,  # [G, W] absolute ticks
    rep_exec,  # [NR, G] per-replica executed watermarks
    head,  # [G] ring heads
    next_slot,  # [G] allocation frontiers
    alive_of_pos,  # [G, W] bool: the slot's proxy leader is alive
    p2b_del,  # [R, C, G, W] bool Phase2b fault-delivery mask
    retry_del,  # [R, C, G, W] bool retry fault-delivery mask
    p2b_lat,  # [R, C, G, W] int32 sampled latencies
    retry_lat,  # [R, C, G, W] int32
    rep_lat,  # [NR, G, W] int32
    t,  # [] current tick
    *,
    retry_timeout: int,
):
    """The pure-jnp specification: exactly the backend's in-tick
    composition of aging + votes + quorum/chosen + replica watermark +
    retry (module docstring). Returns ``(p2a, p2b, rep_arrival, status,
    last_send, rep_exec, newly_chosen, timed_out, votes_cast,
    votes_dropped)`` — the two ``[G, W]`` vote counts feed the tick's
    proxy-load and telemetry reductions without re-materializing the
    ``[R, C, G, W]`` vote mask outside the plane."""
    W = status.shape[1]
    w_iota = jnp.arange(W, dtype=jnp.int32)
    p2a = age_clock(p2a)
    p2b = age_clock(p2b)
    rep_arrival = age_clock(rep_arrival)

    # Acceptors vote on Phase2a arrivals; votes fly back to the slot's
    # proxy leader (idempotent min-write dedups duplicates).
    voted_now = p2a == 0
    p2b = jnp.where(
        voted_now & p2b_del,
        jnp.minimum(p2b, p2b_lat.astype(p2b.dtype)),
        p2b,
    )

    # Chosen when EVERY row has a vote in (column-transversal quorum),
    # collected by a live proxy.
    votes_in = p2b <= 0
    quorum = jnp.all(jnp.any(votes_in, axis=1), axis=0)  # [G, W]
    newly_chosen = (status == PROPOSED) & quorum & alive_of_pos
    status = jnp.where(newly_chosen, CHOSEN, status)
    rep_arrival = jnp.where(
        newly_chosen[None, :, :],
        rep_lat.astype(rep_arrival.dtype),
        rep_arrival,
    )

    # Per-replica watermark: each replica executes its contiguous
    # arrived prefix (masked min-reduction, no gather).
    ord_of_pos = (w_iota[None, :] - head[:, None]) % W  # [G, W]
    live_ord = w_iota[None, :] < (next_slot - head)[:, None]
    exec_ready = (status == CHOSEN)[None] & (rep_arrival <= 0)
    ord_ready = exec_ready & live_ord[None]
    first_gap = jnp.min(
        jnp.where(ord_ready, W, ord_of_pos[None]), axis=2
    )  # [NR, G]
    rep_exec = jnp.maximum(rep_exec, head[None, :] + first_gap)

    # Proxy retries: a timed-out PROPOSED slot re-broadcasts to the
    # FULL grid. OVERWRITE (not min-write): an acceptor whose Phase2b
    # was dropped has an already-arrived (saturated) p2a clock — only a
    # fresh arrival makes it re-vote.
    timed_out = (
        (status == PROPOSED)
        & (t - last_send >= retry_timeout)
        & alive_of_pos
    )
    resend = timed_out[None, None] & retry_del
    p2a = jnp.where(resend, retry_lat.astype(p2a.dtype), p2a)
    last_send = jnp.where(timed_out, t, last_send)

    votes_cast = jnp.sum(voted_now.astype(jnp.int32), axis=(0, 1))
    votes_dropped = jnp.sum(
        (voted_now & ~p2b_del).astype(jnp.int32), axis=(0, 1)
    )
    return (
        p2a, p2b, rep_arrival, status, last_send, rep_exec,
        newly_chosen, timed_out, votes_cast, votes_dropped,
    )


def _grid_vote_kernel_factory(retry_timeout, R, C, NR, bg, W):
    def kernel(
        t_ref,  # SMEM (1,)
        p2a_ref,  # [R, C, BG, W]
        p2b_ref,  # [R, C, BG, W]
        rep_ref,  # [NR, BG, W]
        status_ref,  # [BG, W] int8
        ls_ref,  # [BG, W]
        repexec_ref,  # [NR, BG]
        head_ref,  # [BG]
        next_ref,  # [BG]
        alive_ref,  # [BG, W] int8
        p2bdel_ref,  # [R, C, BG, W] int8
        retrydel_ref,  # [R, C, BG, W] int8
        p2blat_ref,  # [R, C, BG, W] int32
        retrylat_ref,  # [R, C, BG, W] int32
        replat_ref,  # [NR, BG, W] int32
        out_p2a, out_p2b, out_rep, out_status, out_ls, out_repexec,
        out_newly, out_timed, out_votes, out_dropped,
    ):
        import jax.lax as lax

        t = t_ref[0]
        head = head_ref[:]
        alive = alive_ref[:] != 0
        w_iota = lax.broadcasted_iota(jnp.int32, (bg, W), 1)
        ord_of_pos = (w_iota - head[:, None]) % W

        # The R x C grid cells are tiny static loops: every [BG, W]
        # cell slice is aged, voted, and quorum-accumulated while
        # resident in VMEM — the HBM round trips of the ~10 unfused
        # sweeps collapse into this one pass. The Phase2b result is
        # final after the min-write, so it stores immediately; the
        # aged p2a cells stay live across the choose section for the
        # retry loop (aging happens exactly once per cell).
        votes = jnp.zeros((bg, W), jnp.int32)
        dropped = jnp.zeros((bg, W), jnp.int32)
        quorum = None
        p2a_aged = [[None] * C for _ in range(R)]
        for r in range(R):
            row_any = None
            for c in range(C):
                p2a = age_clock(p2a_ref[r, c])
                p2b = age_clock(p2b_ref[r, c])
                voted = p2a == 0
                deliv = p2bdel_ref[r, c] != 0
                p2b = jnp.where(
                    voted & deliv,
                    jnp.minimum(p2b, p2blat_ref[r, c].astype(p2b.dtype)),
                    p2b,
                )
                votes = votes + voted.astype(jnp.int32)
                dropped = dropped + (voted & ~deliv).astype(jnp.int32)
                vin = p2b <= 0
                row_any = vin if row_any is None else (row_any | vin)
                out_p2b[r, c] = p2b
                p2a_aged[r][c] = p2a
            quorum = row_any if quorum is None else (quorum & row_any)

        status = status_ref[:]
        newly = (status == PROPOSED) & quorum & alive
        status = jnp.where(newly, CHOSEN, status)

        live_ord = w_iota < (next_ref[:] - head)[:, None]
        chosen = status == CHOSEN
        for n in range(NR):
            rep = age_clock(rep_ref[n])
            rep = jnp.where(newly, replat_ref[n].astype(rep.dtype), rep)
            ready = chosen & (rep <= 0) & live_ord
            first_gap = jnp.min(jnp.where(ready, W, ord_of_pos), axis=1)
            out_repexec[n] = jnp.maximum(repexec_ref[n], head + first_gap)
            out_rep[n] = rep

        timed = (
            (status == PROPOSED)
            & (t - ls_ref[:] >= retry_timeout)
            & alive
        )
        for r in range(R):
            for c in range(C):
                resend = timed & (retrydel_ref[r, c] != 0)
                p2a = p2a_aged[r][c]
                out_p2a[r, c] = jnp.where(
                    resend, retrylat_ref[r, c].astype(p2a.dtype), p2a
                )
        out_status[:] = status
        out_ls[:] = jnp.where(timed, t, ls_ref[:])
        out_newly[:] = newly.astype(jnp.int8)
        out_timed[:] = timed.astype(jnp.int8)
        out_votes[:] = votes
        out_dropped[:] = dropped

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block", "interpret", "retry_timeout")
)
def fused_grid_vote(
    p2a, p2b, rep_arrival, status, last_send, rep_exec, head, next_slot,
    alive_of_pos, p2b_del, retry_del, p2b_lat, retry_lat, rep_lat, t,
    block: int = 256,
    interpret: bool = False,
    retry_timeout: int = 8,
):
    """Fused :func:`reference_grid_vote`: aging + votes + quorum/chosen
    + per-replica watermark + retry in ONE VMEM-resident pass per group
    block."""
    from jax.experimental import pallas as pl

    R, C, G, W = p2a.shape
    NR = rep_arrival.shape[0]
    bg, pad = balanced_block(G, block)
    (p2a, p2b, rep_arrival, status, last_send, rep_exec, head, next_slot,
     alive_of_pos, p2b_del, retry_del, p2b_lat, retry_lat, rep_lat,
     t) = _pad_args(
        (p2a, p2b, rep_arrival, status, last_send, rep_exec, head,
         next_slot, alive_of_pos, p2b_del, retry_del, p2b_lat,
         retry_lat, rep_lat, t),
        pad,
    )
    Gp = G + pad

    i8 = jnp.int8
    sp = _specs(pl, R, C, NR, bg, W, interpret)
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=(
            [sp["t"]]
            + [sp["rcgw"], sp["rcgw"], sp["ngw"]]  # p2a, p2b, rep_arrival
            + [sp["gw"], sp["gw"], sp["ng"]]  # status, last_send, rep_exec
            + [sp["g"], sp["g"], sp["gw"]]  # head, next_slot, alive
            + [sp["rcgw"]] * 4  # p2b_del, retry_del, p2b_lat, retry_lat
            + [sp["ngw"]]  # rep_lat
        ),
        out_specs=(
            [sp["rcgw"], sp["rcgw"], sp["ngw"]]  # p2a, p2b, rep_arrival
            + [sp["gw"], sp["gw"], sp["ng"]]  # status, last_send, rep_exec
            + [sp["gw"]] * 4  # newly, timed, votes, dropped
        ),
    )
    out_shape = [
        jax.ShapeDtypeStruct((R, C, Gp, W), p2a.dtype),
        jax.ShapeDtypeStruct((R, C, Gp, W), p2b.dtype),
        jax.ShapeDtypeStruct((NR, Gp, W), rep_arrival.dtype),
        jax.ShapeDtypeStruct((Gp, W), status.dtype),
        jax.ShapeDtypeStruct((Gp, W), last_send.dtype),
        jax.ShapeDtypeStruct((NR, Gp), rep_exec.dtype),
        jax.ShapeDtypeStruct((Gp, W), i8),  # newly_chosen
        jax.ShapeDtypeStruct((Gp, W), i8),  # timed_out
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # votes_cast
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # votes_dropped
    ]
    kernel = _grid_vote_kernel_factory(retry_timeout, R, C, NR, bg, W)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        p2a, p2b, rep_arrival,
        status, last_send, rep_exec,
        head, next_slot, alive_of_pos.astype(i8),
        p2b_del.astype(i8), retry_del.astype(i8), p2b_lat, retry_lat,
        rep_lat,
    )
    (p2a, p2b, rep_arrival, status, last_send, rep_exec,
     newly, timed, votes_cast, votes_dropped) = _slice_outs(outs, G, pad)
    return (
        p2a, p2b, rep_arrival, status, last_send, rep_exec,
        newly.astype(bool), timed.astype(bool), votes_cast, votes_dropped,
    )


# ---------------------------------------------------------------------------
# The UNFUSED kernel-path twin — the race baseline for the microbench
# (harness/microbench.py `grid_vote`). Same semantics as
# :func:`fused_grid_vote`, but split into the SIX passes the
# reference tick's dataflow implies — clock aging, vote, the vote-count
# re-read (the tick's proxy-load/telemetry reductions), quorum/choose,
# replica watermark, retry — each its own ``pallas_call``, so the
# [R, C, G, W] arrays round-trip HBM between passes exactly where the
# unfused tick re-reads them. Racing fused against this through the
# SAME execution vehicle (interpret mode on CPU, compiled on TPU)
# prices the fusion itself — the discipline the whole-tick megakernel
# race established (results/kernel_microbench_r10.json). Not a
# registered plane: nothing dispatches it; it exists to be beaten.
# ---------------------------------------------------------------------------


def _uf_age_kernel_factory(R, C, NR):
    def kernel(p2a_ref, p2b_ref, rep_ref, out_p2a, out_p2b, out_rep):
        for r in range(R):
            for c in range(C):
                out_p2a[r, c] = age_clock(p2a_ref[r, c])
                out_p2b[r, c] = age_clock(p2b_ref[r, c])
        for n in range(NR):
            out_rep[n] = age_clock(rep_ref[n])

    return kernel


def _uf_vote_kernel_factory(R, C):
    def kernel(p2a_ref, p2b_ref, p2bdel_ref, p2blat_ref, out_p2b):
        for r in range(R):
            for c in range(C):
                voted = p2a_ref[r, c] == 0
                deliv = p2bdel_ref[r, c] != 0
                p2b = p2b_ref[r, c]
                out_p2b[r, c] = jnp.where(
                    voted & deliv,
                    jnp.minimum(p2b, p2blat_ref[r, c].astype(p2b.dtype)),
                    p2b,
                )

    return kernel


def _uf_counts_kernel_factory(R, C):
    # The unfused tick re-derives the vote mask for its proxy-load and
    # telemetry reductions (the fused plane exports votes_cast/
    # votes_dropped precisely to delete this re-read — the max_ord
    # argument of the megakernel): a full second sweep over the p2a
    # plane.
    def kernel(p2a_ref, p2bdel_ref, out_votes, out_dropped):
        votes = None
        dropped = None
        for r in range(R):
            for c in range(C):
                voted = p2a_ref[r, c] == 0
                deliv = p2bdel_ref[r, c] != 0
                v = voted.astype(jnp.int32)
                d = (voted & ~deliv).astype(jnp.int32)
                votes = v if votes is None else votes + v
                dropped = d if dropped is None else dropped + d
        out_votes[:] = votes
        out_dropped[:] = dropped

    return kernel


def _uf_choose_kernel_factory(R, C):
    def kernel(p2b_ref, status_ref, alive_ref, out_status, out_newly):
        quorum = None
        for r in range(R):
            row_any = None
            for c in range(C):
                vin = p2b_ref[r, c] <= 0
                row_any = vin if row_any is None else (row_any | vin)
            quorum = row_any if quorum is None else (quorum & row_any)
        status = status_ref[:]
        newly = (status == PROPOSED) & quorum & (alive_ref[:] != 0)
        out_status[:] = jnp.where(newly, CHOSEN, status)
        out_newly[:] = newly.astype(jnp.int8)

    return kernel


def _uf_replica_kernel_factory(NR, bg, W):
    def kernel(rep_ref, status_ref, newly_ref, repexec_ref,
               head_ref, next_ref, replat_ref, out_rep, out_repexec):
        import jax.lax as lax

        head = head_ref[:]
        w_iota = lax.broadcasted_iota(jnp.int32, (bg, W), 1)
        ord_of_pos = (w_iota - head[:, None]) % W
        live_ord = w_iota < (next_ref[:] - head)[:, None]
        newly = newly_ref[:] != 0
        chosen = status_ref[:] == CHOSEN
        for n in range(NR):
            rep = rep_ref[n]
            rep = jnp.where(newly, replat_ref[n].astype(rep.dtype), rep)
            ready = chosen & (rep <= 0) & live_ord
            first_gap = jnp.min(jnp.where(ready, W, ord_of_pos), axis=1)
            out_repexec[n] = jnp.maximum(repexec_ref[n], head + first_gap)
            out_rep[n] = rep

    return kernel


def _uf_retry_kernel_factory(retry_timeout, R, C):
    def kernel(t_ref, p2a_ref, status_ref, ls_ref, alive_ref,
               retrydel_ref, retrylat_ref, out_p2a, out_ls, out_timed):
        t = t_ref[0]
        timed = (
            (status_ref[:] == PROPOSED)
            & (t - ls_ref[:] >= retry_timeout)
            & (alive_ref[:] != 0)
        )
        for r in range(R):
            for c in range(C):
                resend = timed & (retrydel_ref[r, c] != 0)
                p2a = p2a_ref[r, c]
                out_p2a[r, c] = jnp.where(
                    resend, retrylat_ref[r, c].astype(p2a.dtype), p2a
                )
        out_ls[:] = jnp.where(timed, t, ls_ref[:])
        out_timed[:] = timed.astype(jnp.int8)

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block", "interpret", "retry_timeout")
)
def unfused_grid_vote(
    p2a, p2b, rep_arrival, status, last_send, rep_exec, head, next_slot,
    alive_of_pos, p2b_del, retry_del, p2b_lat, retry_lat, rep_lat, t,
    block: int = 256,
    interpret: bool = False,
    retry_timeout: int = 8,
):
    """Six-pass kernel-path twin of :func:`fused_grid_vote` (race
    baseline; module comment above). Identical signature and outputs."""
    from jax.experimental import pallas as pl

    R, C, G, W = p2a.shape
    NR = rep_arrival.shape[0]
    bg, pad = balanced_block(G, block)
    (p2a, p2b, rep_arrival, status, last_send, rep_exec, head, next_slot,
     alive_of_pos, p2b_del, retry_del, p2b_lat, retry_lat, rep_lat,
     t) = _pad_args(
        (p2a, p2b, rep_arrival, status, last_send, rep_exec, head,
         next_slot, alive_of_pos, p2b_del, retry_del, p2b_lat,
         retry_lat, rep_lat, t),
        pad,
    )
    Gp = G + pad

    i8 = jnp.int8
    sp = _specs(pl, R, C, NR, bg, W, interpret)
    spec4, spec3, spec2 = sp["rcgw"], sp["ngw"], sp["ng"]
    spec_g, spec_gw, spec_t = sp["g"], sp["gw"], sp["t"]
    grid = (Gp // bg,)

    # Pass 1: clock aging (the tick's step-0 sweep).
    p2a_aged, p2b_aged, rep_aged = pl.pallas_call(
        _uf_age_kernel_factory(R, C, NR),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec4, spec4, spec3],
            out_specs=[spec4, spec4, spec3],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((R, C, Gp, W), p2a.dtype),
            jax.ShapeDtypeStruct((R, C, Gp, W), p2b.dtype),
            jax.ShapeDtypeStruct((NR, Gp, W), rep_arrival.dtype),
        ],
        interpret=interpret,
    )(p2a, p2b, rep_arrival)

    # Pass 2: acceptor votes (Phase2b min-write).
    i8_p2b_del = p2b_del.astype(i8)
    p2b_new = pl.pallas_call(
        _uf_vote_kernel_factory(R, C),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec4, spec4, spec4, spec4],
            out_specs=spec4,
        ),
        out_shape=jax.ShapeDtypeStruct((R, C, Gp, W), p2b.dtype),
        interpret=interpret,
    )(p2a_aged, p2b_aged, i8_p2b_del, p2b_lat)

    # Pass 2b: the vote-mask re-read the unfused tick pays for its
    # proxy-load/telemetry reductions.
    votes, dropped = pl.pallas_call(
        _uf_counts_kernel_factory(R, C),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec4, spec4],
            out_specs=[spec_gw, spec_gw],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((Gp, W), jnp.int32),
            jax.ShapeDtypeStruct((Gp, W), jnp.int32),
        ],
        interpret=interpret,
    )(p2a_aged, i8_p2b_del)

    # Pass 3: quorum count -> Chosen (re-reads the whole p2b plane).
    alive8 = alive_of_pos.astype(i8)
    status_new, newly = pl.pallas_call(
        _uf_choose_kernel_factory(R, C),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec4, spec_gw, spec_gw],
            out_specs=[spec_gw, spec_gw],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((Gp, W), status.dtype),
            jax.ShapeDtypeStruct((Gp, W), i8),
        ],
        interpret=interpret,
    )(p2b_new, status, alive8)

    # Pass 4: commit-broadcast arming + per-replica watermark.
    rep_new, rep_exec_new = pl.pallas_call(
        _uf_replica_kernel_factory(NR, bg, W),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec3, spec_gw, spec_gw, spec2,
                      spec_g, spec_g, spec3],
            out_specs=[spec3, spec2],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((NR, Gp, W), rep_arrival.dtype),
            jax.ShapeDtypeStruct((NR, Gp), rep_exec.dtype),
        ],
        interpret=interpret,
    )(rep_aged, status_new, newly, rep_exec, head, next_slot, rep_lat)

    # Pass 5: retry re-send (re-reads the whole p2a plane).
    p2a_final, ls_new, timed = pl.pallas_call(
        _uf_retry_kernel_factory(retry_timeout, R, C),
        grid_spec=pl.GridSpec(
            grid=grid,
            in_specs=[spec_t, spec4, spec_gw, spec_gw, spec_gw,
                      spec4, spec4],
            out_specs=[spec4, spec_gw, spec_gw],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((R, C, Gp, W), p2a.dtype),
            jax.ShapeDtypeStruct((Gp, W), last_send.dtype),
            jax.ShapeDtypeStruct((Gp, W), i8),
        ],
        interpret=interpret,
    )(t_arr(t), p2a_aged, status_new, last_send, alive8,
      retry_del.astype(i8), retry_lat)

    outs = [p2a_final, p2b_new, rep_new, status_new, ls_new,
            rep_exec_new, newly, timed, votes, dropped]
    (p2a_final, p2b_new, rep_new, status_new, ls_new, rep_exec_new,
     newly, timed, votes, dropped) = _slice_outs(outs, G, pad)
    return (
        p2a_final, p2b_new, rep_new, status_new, ls_new, rep_exec_new,
        newly.astype(bool), timed.astype(bool), votes, dropped,
    )


registry.register(
    registry.Plane(
        name="compartmentalized_grid_vote",
        backend="compartmentalized",
        reference=reference_grid_vote,
        kernel=fused_grid_vote,
        key_of=lambda args: args[0].shape,  # p2a: (R, C, G, W)
        batch_axis=2,  # grids over G
        default_block=256,
        # Group-local end to end: grid cells, replica planes, and every
        # [G, W] mask shard along G — per-device lowering is exact. The
        # axes are the same tables the wrappers pad/slice with.
        shard=registry.ShardSpec(
            arg_axes=ARG_G_AXES, out_axes=OUT_G_AXES
        ),
    )
)
