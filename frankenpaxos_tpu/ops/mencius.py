"""Fused Pallas kernel for the batched Mencius vote plane.

``mencius_vote`` covers tick steps 1-2 of ``tpu/mencius_batched.py``:
acceptors of every stripe process Phase2a arrivals (no competing rounds
in the steady-state Mencius write path — each leader owns its stripe),
schedule Phase2b replies, and the per-slot quorum count sums the
acceptor axis. Skips (noop range fills) flow through this same plane as
ordinary proposals, so fusing it accelerates both the loaded and the
catch-up paths. Four elementwise [L, W, A] passes plus a reduction in
XLA; one VMEM-resident pass here.

Layout note: mencius state is leader-major ``[L, W, A]`` with the tiny
acceptor axis MINOR (the backend predates the acceptor-major layout
rework). The kernel therefore blocks over L with full [BL, W, A] blocks
and reduces over the minor axis — on real TPU the (W, A) tile pads A up
to the lane width, so this plane's win is fusion (one HBM read per
array), not layout; the autotune table picks the block accordingly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import balanced_block, pad_axis, t_arr, t_space


def reference_mencius_vote(
    p2a_arrival: jnp.ndarray,  # [L, W, A] absolute arrival ticks
    voted: jnp.ndarray,  # [L, W, A] bool
    p2b_arrival: jnp.ndarray,  # [L, W, A] absolute arrival ticks
    p2b_lat: jnp.ndarray,  # [L, W, A] sampled latencies
    p2b_delivered: jnp.ndarray,  # [L, W, A] bool
    t: jnp.ndarray,  # [] current tick
):
    """The pure-jnp specification (tick steps 1-2 of mencius_batched).
    Returns ``(voted', p2b_arrival', nvotes [L, W])``."""
    arrived = p2a_arrival == t
    new_voted = voted | arrived
    new_p2b = jnp.where(
        arrived & p2b_delivered,
        jnp.minimum(p2b_arrival, t + p2b_lat),
        p2b_arrival,
    )
    nvotes = jnp.sum(
        ((new_p2b <= t) & new_voted).astype(jnp.int32), axis=2
    )
    return new_voted, new_p2b, nvotes


def _mencius_vote_kernel(
    t_ref,  # SMEM (1,)
    p2a_ref,  # [BL, W, A]
    voted_ref,  # [BL, W, A] int8
    p2b_ref,  # [BL, W, A]
    lat_ref,  # [BL, W, A]
    deliv_ref,  # [BL, W, A] int8
    out_voted_ref,
    out_p2b_ref,
    out_nv_ref,  # [BL, W]
):
    t = t_ref[0]
    arrived = p2a_ref[:] == t
    new_voted = (voted_ref[:] != 0) | arrived
    new_p2b = jnp.where(
        arrived & (deliv_ref[:] != 0),
        jnp.minimum(p2b_ref[:], t + lat_ref[:]),
        p2b_ref[:],
    )
    out_voted_ref[:] = new_voted.astype(jnp.int8)
    out_p2b_ref[:] = new_p2b
    out_nv_ref[:] = jnp.sum(
        ((new_p2b <= t) & new_voted).astype(jnp.int32), axis=2
    )


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def fused_mencius_vote(
    p2a_arrival,
    voted,
    p2b_arrival,
    p2b_lat,
    p2b_delivered,
    t,
    block: int = 256,
    interpret: bool = False,
):
    """Fused :func:`reference_mencius_vote`, gridded over leader-stripe
    blocks."""
    from jax.experimental import pallas as pl

    L, W, A = p2a_arrival.shape
    bl, pad = balanced_block(L, block)
    if pad:
        p2a_arrival = pad_axis(p2a_arrival, 0, pad)
        voted = pad_axis(voted, 0, pad)
        p2b_arrival = pad_axis(p2b_arrival, 0, pad)
        p2b_lat = pad_axis(p2b_lat, 0, pad)
        p2b_delivered = pad_axis(p2b_delivered, 0, pad)
    Lp = L + pad

    spec3 = pl.BlockSpec((bl, W, A), lambda i: (i, 0, 0))
    spec_lw = pl.BlockSpec((bl, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Lp // bl,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret)),
            spec3,  # p2a
            spec3,  # voted
            spec3,  # p2b
            spec3,  # lat
            spec3,  # delivered
        ],
        out_specs=[spec3, spec3, spec_lw],
    )
    out_shape = [
        jax.ShapeDtypeStruct((Lp, W, A), jnp.int8),
        jax.ShapeDtypeStruct((Lp, W, A), p2b_arrival.dtype),
        jax.ShapeDtypeStruct((Lp, W), jnp.int32),
    ]
    voted_out, p2b, nv = pl.pallas_call(
        _mencius_vote_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        p2a_arrival,
        voted.astype(jnp.int8),
        p2b_arrival,
        p2b_lat,
        p2b_delivered.astype(jnp.int8),
    )
    if pad:
        voted_out, p2b, nv = voted_out[:L], p2b[:L], nv[:L]
    return voted_out.astype(bool), p2b, nv


registry.register(
    registry.Plane(
        name="mencius_vote",
        backend="mencius",
        reference=reference_mencius_vote,
        kernel=fused_mencius_vote,
        key_of=lambda args: args[0].shape,  # (L, W, A)
        default_block=256,
    )
)
