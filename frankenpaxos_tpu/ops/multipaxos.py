"""Fused Pallas kernels for the batched MultiPaxos hot planes.

Four planes of ``tpu/multipaxos_batched.py`` dispatch here (see
``ops/registry.py`` for the policy machinery):

  * ``multipaxos_fused_tick`` — the WHOLE-TICK MEGAKERNEL: offset-clock
    aging, the vote/quorum plane, and the dispatch plane (quorum ->
    Chosen -> commit-watermark -> propose -> retry) as ONE Pallas grid
    program. Between the per-plane kernels below, State still
    round-trips HBM (the vote plane's [A, G, W] outputs are written,
    then re-read by the dispatch kernel, with a separate aging pass in
    front); here every array is read from HBM exactly once per tick and
    the intermediate vote state never leaves VMEM. The tick routes to
    this plane whenever the policy resolves it off the reference path
    (``disable=("multipaxos_fused_tick",)`` restores the per-plane
    kernels); elections/reconfiguration repairs compose by aging
    outside (``age=False``) and feeding the repaired arrays in.
  * ``multipaxos_vote_quorum`` — tick steps 1-2: acceptors process
    Phase2a arrivals, record votes, schedule Phase2b arrivals, count
    per-slot quorums (Acceptor.scala:184-220 + ProxyLeader.scala:
    217-258). Six elementwise passes plus a reduction over [A, G, W]
    arrays in the XLA version, ONE VMEM-resident pass here. Also folds
    the per-acceptor max-voted-slot bookkeeping (``max_ord``, the
    Acceptor.scala:222-237 ``maxVotedSlot`` the read path serves) into
    the same pass, so ``use_pallas + reads`` is single-pass again.
  * ``multipaxos_p1_promise`` — phase-1 promise/max-vote aggregation
    (startPhase1 / safeValue, Leader.scala:314-329, 409-459): per slot,
    the max-round visible vote across the acceptor axis decides the
    safe value; in-flight slots re-propose it to the full group. The
    argmax + gather + three [A, G, W] re-send writes fuse into one
    pass.
  * ``multipaxos_dispatch`` — tick steps 2-5: quorum -> Chosen, the
    commit-watermark advance (contiguous-prefix retire), the
    retire-clears of the four [A, G, W] vote/message arrays, leader
    Phase2a dispatch of fresh slots, and timeout resends. The
    [G]-space control decisions (proposal caps under elections /
    reconfiguration / closed workloads, retry gates) stay in XLA and
    enter as tiny per-group vectors.

All kernels are DTYPE-POLYMORPHIC: they compute in whatever dtypes the
state carries (int16 rounds, int8 statuses, int16 offset clocks under
the dtype policy of ``tpu/common.py``; int32 everything on the
``widen_state()`` reference path), so there are no widen/narrow casts
at the kernel boundary — ROADMAP PR 1 follow-up (b). Message arrival
clocks are the DELTA-ENCODED offsets of ``tpu/common.py``: "arrives
now" is ``offset == 0``, "already arrived" is ``offset <= 0``, and the
tick counter never enters the arrival math (only absolute bookkeeping
ticks — propose/chosen/last-send stamps — read the SMEM ``t``).

Layout: acceptor-major ``[A, G, W]`` (see the backend's module
docstring); the group axis grids, W rides the 128-lane VPU, and the
tiny acceptor axis A = 2f+1 is a static in-kernel loop. Each kernel's
``reference_*`` twin is the pure-jnp specification it is verified
against bit for bit (tests/test_ops.py, tests/test_kernel_registry.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import (
    INF_I,
    balanced_block,
    pad_axis,
    t_arr,
    t_space,
)
from frankenpaxos_tpu.tpu.common import (
    INF,
    INF16,
    age_clock,
    ring_retire_pos,
)

# Mirrors of the backend's slot codes (ops must not import the backend:
# the backend imports ops). Cross-checked by tests/test_kernel_registry.
EMPTY = 0
PROPOSED = 1
CHOSEN = 2
NO_VALUE = -1
NOOP_VALUE = -2
# Saturation floor of the head-relative acc_max_slot delta (the
# backend's AMS_FLOOR): max_ord entries of acceptors with no vote this
# tick saturate here so the outside maximum() leaves them untouched.
AMS_FLOOR = -(2**14)


# ---------------------------------------------------------------------------
# Plane: multipaxos_vote_quorum (tick steps 1-2)
# ---------------------------------------------------------------------------


def reference_vote_quorum(
    p2a_off: jnp.ndarray,  # [A, G, W] offset clocks (0 = arrives now)
    acc_round: jnp.ndarray,  # [A, G] promised rounds
    leader_round: jnp.ndarray,  # [G]
    slot_value: jnp.ndarray,  # [G, W]
    vote_round: jnp.ndarray,  # [A, G, W] (-1 = no vote)
    vote_value: jnp.ndarray,  # [A, G, W]
    p2b_off: jnp.ndarray,  # [A, G, W] offset clocks (INF16 = none pending)
    p2b_lat: jnp.ndarray,  # [A, G, W] sampled latencies (clock dtype)
    p2b_delivered: jnp.ndarray,  # [A, G, W] bool
    head: jnp.ndarray,  # [G] ring heads (max_ord's ordinal origin)
):
    """The pure-jnp specification (tick steps 1-2 of multipaxos_batched,
    Acceptor.scala:184-220 + ProxyLeader.scala:217-258), acceptor-major.

    The sixth output ``nsends`` [G, W] counts the Phase2b messages the
    acceptors SENT this tick (votes cast whose reply was delivered) —
    the vote predicate is otherwise plane-internal, and the telemetry
    phase-2 message accounting needs it to be exact on every path. The
    seventh, ``max_ord`` [A, G], is each acceptor's max voted ring
    ordinal this tick (AMS_FLOOR when it cast none) — the read path's
    ``acc_max_slot`` feed (Acceptor.scala:222-237 maxVotedSlot), folded
    in so reads don't recompute the vote predicate in a second pass."""
    W = p2a_off.shape[2]
    lr = leader_round[None, :, None]  # [1, G, 1]
    arrived = p2a_off == 0
    may_vote = arrived & (lr >= acc_round[:, :, None])
    new_vote_round = jnp.where(may_vote, lr, vote_round)
    new_vote_value = jnp.where(may_vote, slot_value[None, :, :], vote_value)
    sends = may_vote & p2b_delivered
    new_p2b = jnp.where(sends, jnp.minimum(p2b_off, p2b_lat), p2b_off)
    new_acc_round = jnp.maximum(
        acc_round, jnp.max(jnp.where(may_vote, lr, -1), axis=2)
    )
    votes_in = (new_p2b <= 0) & (new_vote_round == lr)
    nvotes = jnp.sum(votes_in.astype(jnp.int32), axis=0)  # [G, W]
    nsends = jnp.sum(sends.astype(jnp.int32), axis=0)  # [G, W]
    w_iota = jnp.arange(W, dtype=jnp.int32)
    ord_of_pos = (w_iota[None, :] - head[:, None]) % W  # [G, W]
    max_ord = jnp.max(
        jnp.where(may_vote, ord_of_pos[None, :, :], AMS_FLOOR), axis=2
    )  # [A, G]
    return (
        new_vote_round, new_vote_value, new_p2b, new_acc_round, nvotes,
        nsends, max_ord,
    )


def _vote_step(lr, sv, acc_r, p2a, vr, vv, p2b, lat, deliv, ord_of_pos):
    """ONE acceptor's vote step on [BG, W] values — the shared in-kernel
    body of the vote plane and the megakernel (a fix to the vote
    semantics lands in both paths by construction). ``lr`` is [BG, 1],
    ``acc_r`` [BG], ``deliv`` an int8 mask. Returns ``(vote_round',
    vote_value', p2b', acc_round', max_ord, votes_in, sends)``."""
    arrived = p2a == 0
    may_vote = arrived & (lr >= acc_r[:, None])
    new_vr = jnp.where(may_vote, lr, vr)
    new_vv = jnp.where(may_vote, sv, vv)
    sends = may_vote & (deliv != 0)
    new_p2b = jnp.where(sends, jnp.minimum(p2b, lat), p2b)
    new_accr = jnp.maximum(
        acc_r, jnp.max(jnp.where(may_vote, lr, -1), axis=1)
    )
    max_ord = jnp.max(jnp.where(may_vote, ord_of_pos, AMS_FLOOR), axis=1)
    votes_in = ((new_p2b <= 0) & (new_vr == lr)).astype(jnp.int32)
    return new_vr, new_vv, new_p2b, new_accr, max_ord, votes_in, sends


def _vote_quorum_kernel(
    p2a_ref,  # [A, BG, W]
    accr_ref,  # [A, BG]
    lr_ref,  # [BG]
    sv_ref,  # [BG, W]
    vr_ref,  # [A, BG, W]
    vv_ref,  # [A, BG, W]
    p2b_ref,  # [A, BG, W]
    lat_ref,  # [A, BG, W]
    deliv_ref,  # [A, BG, W] int8 (0/1)
    head_ref,  # [BG]
    out_vr_ref,
    out_vv_ref,
    out_p2b_ref,
    out_accr_ref,
    out_nv_ref,  # [BG, W]
    out_ns_ref,  # [BG, W] Phase2b sends this tick
    out_maxord_ref,  # [A, BG] max voted ordinal (AMS_FLOOR = none)
):
    import jax.lax as lax

    A = p2a_ref.shape[0]
    W = p2a_ref.shape[2]
    lr = lr_ref[:][:, None]  # [BG, 1]
    sv = sv_ref[:]  # [BG, W]
    w_iota = lax.broadcasted_iota(jnp.int32, sv.shape, 1)
    ord_of_pos = (w_iota - head_ref[:][:, None]) % W
    nvotes = jnp.zeros(sv.shape, jnp.int32)
    nsends = jnp.zeros(sv.shape, jnp.int32)
    # The acceptor axis is tiny (2f+1): a static loop keeps every slice a
    # well-tiled [BG, W] block, with values resident in VMEM across the
    # vote update AND the quorum count.
    for a in range(A):
        new_vr, new_vv, new_p2b, new_accr, max_ord, votes, sends = (
            _vote_step(
                lr, sv, accr_ref[a], p2a_ref[a], vr_ref[a], vv_ref[a],
                p2b_ref[a], lat_ref[a], deliv_ref[a], ord_of_pos,
            )
        )
        out_vr_ref[a] = new_vr
        out_vv_ref[a] = new_vv
        out_p2b_ref[a] = new_p2b
        out_accr_ref[a] = new_accr
        out_maxord_ref[a] = max_ord
        nvotes = nvotes + votes
        nsends = nsends + sends.astype(jnp.int32)
    out_nv_ref[:] = nvotes
    out_ns_ref[:] = nsends


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def fused_vote_quorum(
    p2a_off,
    acc_round,
    leader_round,
    slot_value,
    vote_round,
    vote_value,
    p2b_off,
    p2b_lat,
    p2b_delivered,
    head,
    block: int = 256,
    interpret: bool = False,
):
    """One fused VMEM-resident pass over the acceptor step. Same
    semantics (and dtypes) as :func:`reference_vote_quorum`; gridded
    over blocks of the group axis."""
    from jax.experimental import pallas as pl

    A, G, W = p2a_off.shape
    bg, pad = balanced_block(G, block)
    args3 = [p2a_off, vote_round, vote_value, p2b_off, p2b_lat]
    if pad:
        args3 = [pad_axis(x, 1, pad) for x in args3]
        acc_round = pad_axis(acc_round, 1, pad)
        leader_round = pad_axis(leader_round, 0, pad)
        slot_value = pad_axis(slot_value, 0, pad)
        p2b_delivered = pad_axis(p2b_delivered, 1, pad)
        head = pad_axis(head, 0, pad)
    p2a_off, vote_round, vote_value, p2b_off, p2b_lat = args3
    Gp = G + pad

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec2 = pl.BlockSpec((A, bg), lambda i: (0, i))
    spec_g = pl.BlockSpec((bg,), lambda i: (i,))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))

    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=[
            spec3,  # p2a
            spec2,  # acc_round
            spec_g,  # leader_round
            spec_gw,  # slot_value
            spec3,  # vote_round
            spec3,  # vote_value
            spec3,  # p2b
            spec3,  # p2b_lat
            spec3,  # delivered
            spec_g,  # head
        ],
        out_specs=[spec3, spec3, spec3, spec2, spec_gw, spec_gw, spec2],
    )
    out_shape = [
        jax.ShapeDtypeStruct((A, Gp, W), vote_round.dtype),
        jax.ShapeDtypeStruct((A, Gp, W), vote_value.dtype),
        jax.ShapeDtypeStruct((A, Gp, W), p2b_off.dtype),
        jax.ShapeDtypeStruct((A, Gp), acc_round.dtype),
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # nvotes
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # Phase2b sends
        jax.ShapeDtypeStruct((A, Gp), jnp.int32),  # max voted ordinal
    ]
    vr, vv, p2b, accr, nv, ns, maxord = pl.pallas_call(
        _vote_quorum_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        p2a_off,
        acc_round,
        leader_round,
        slot_value,
        vote_round,
        vote_value,
        p2b_off,
        p2b_lat,
        p2b_delivered.astype(jnp.int8),
        head,
    )
    if pad:
        vr, vv, p2b = vr[:, :G], vv[:, :G], p2b[:, :G]
        accr, nv, ns = accr[:, :G], nv[:G], ns[:G]
        maxord = maxord[:, :G]
    return vr, vv, p2b, accr, nv, ns, maxord


# ---------------------------------------------------------------------------
# Plane: multipaxos_p1_promise (phase-1 safe-value aggregation + re-send)
# ---------------------------------------------------------------------------


def reference_p1_promise(
    status: jnp.ndarray,  # [G, W] int8
    vote_round: jnp.ndarray,  # [A, G, W]
    vote_value: jnp.ndarray,  # [A, G, W]
    slot_value: jnp.ndarray,  # [G, W]
    p2a_off: jnp.ndarray,  # [A, G, W] offset clocks
    p2b_off: jnp.ndarray,  # [A, G, W] offset clocks
    last_send: jnp.ndarray,  # [G, W] absolute ticks
    mask: jnp.ndarray,  # [G] bool: groups repairing now
    learned: jnp.ndarray,  # [A, G] bool: acceptors whose Phase1b arrived
    lat: jnp.ndarray,  # [A, G, W] re-send latencies (clock dtype)
    t: jnp.ndarray,  # [] current tick
):
    """Masked phase-1 log repair (startPhase1, Leader.scala:409-459):
    for every in-flight slot of a masked group, adopt the safe value —
    the value of the max-round vote among LEARNED acceptors (safeValue,
    Leader.scala:314-329; callers guarantee ``learned`` covers an f+1
    read quorum) — and re-propose it to the full group. Slots with no
    visible votes repair to noops (Leader.scala:541-575). Stale pending
    Phase2bs clear so old-round votes can't piggyback on past arrivals.

    Returns ``(slot_value, p2a_off, p2b_off, last_send)``."""
    in_flight = (status == PROPOSED) & mask[:, None]  # [G, W]
    vr = jnp.where(learned[:, :, None], vote_round, -1)
    # safeValue: per slot, the value of the max-round visible vote (all
    # votes in one round carry the same value, so any argmax tie-break
    # is safe).
    best = jnp.argmax(vr, axis=0)
    voted_value = jnp.take_along_axis(vote_value, best[None, :, :], axis=0)[0]
    any_vote = jnp.any(vr >= 0, axis=0)  # [G, W]
    safe_value = jnp.where(any_vote, voted_value, NOOP_VALUE)
    new_slot_value = jnp.where(in_flight, safe_value, slot_value)
    new_p2a = jnp.where(in_flight[None, :, :], lat, p2a_off)
    new_p2b = jnp.where(in_flight[None, :, :], INF16, p2b_off)
    new_last_send = jnp.where(in_flight, t, last_send)
    return new_slot_value, new_p2a, new_p2b, new_last_send


def _p1_promise_kernel(
    t_ref,  # SMEM (1,)
    status_ref,  # [BG, W] int8
    vr_ref,  # [A, BG, W]
    vv_ref,  # [A, BG, W]
    sv_ref,  # [BG, W]
    p2a_ref,  # [A, BG, W]
    p2b_ref,  # [A, BG, W]
    ls_ref,  # [BG, W]
    mask_ref,  # [BG] int8
    learned_ref,  # [A, BG] int8
    lat_ref,  # [A, BG, W]
    out_sv_ref,
    out_p2a_ref,
    out_p2b_ref,
    out_ls_ref,
):
    t = t_ref[0]
    A = vr_ref.shape[0]
    in_flight = (status_ref[:] == PROPOSED) & (mask_ref[:][:, None] != 0)
    # First-max scan over the tiny acceptor axis: strict > keeps the
    # FIRST max, matching the reference's argmax tie-break exactly.
    best_r = jnp.where(
        learned_ref[0][:, None] != 0, vr_ref[0], -1
    )
    best_v = vv_ref[0]
    for a in range(1, A):
        vr_a = jnp.where(learned_ref[a][:, None] != 0, vr_ref[a], -1)
        upd = vr_a > best_r
        best_r = jnp.where(upd, vr_a, best_r)
        best_v = jnp.where(upd, vv_ref[a], best_v)
    safe_value = jnp.where(best_r >= 0, best_v, NOOP_VALUE)
    out_sv_ref[:] = jnp.where(in_flight, safe_value, sv_ref[:])
    out_ls_ref[:] = jnp.where(in_flight, t, ls_ref[:])
    for a in range(A):
        out_p2a_ref[a] = jnp.where(in_flight, lat_ref[a], p2a_ref[a])
        out_p2b_ref[a] = jnp.where(in_flight, INF16, p2b_ref[a])


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def fused_p1_promise(
    status,
    vote_round,
    vote_value,
    slot_value,
    p2a_off,
    p2b_off,
    last_send,
    mask,
    learned,
    lat,
    t,
    block: int = 256,
    interpret: bool = False,
):
    """Fused :func:`reference_p1_promise`: the safe-value argmax, the
    noop fallback, and all three [A, G, W] re-send writes in one
    VMEM-resident pass."""
    from jax.experimental import pallas as pl

    A, G, W = vote_round.shape
    bg, pad = balanced_block(G, block)
    if pad:
        status = pad_axis(status, 0, pad)
        vote_round = pad_axis(vote_round, 1, pad)
        vote_value = pad_axis(vote_value, 1, pad)
        slot_value = pad_axis(slot_value, 0, pad)
        p2a_off = pad_axis(p2a_off, 1, pad)
        p2b_off = pad_axis(p2b_off, 1, pad)
        last_send = pad_axis(last_send, 0, pad)
        mask = pad_axis(mask, 0, pad)
        learned = pad_axis(learned, 1, pad)
        lat = pad_axis(lat, 1, pad)
    Gp = G + pad

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec2 = pl.BlockSpec((A, bg), lambda i: (0, i))
    spec_g = pl.BlockSpec((bg,), lambda i: (i,))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret)),
            spec_gw,  # status
            spec3,  # vote_round
            spec3,  # vote_value
            spec_gw,  # slot_value
            spec3,  # p2a
            spec3,  # p2b
            spec_gw,  # last_send
            spec_g,  # mask
            spec2,  # learned
            spec3,  # lat
        ],
        out_specs=[spec_gw, spec3, spec3, spec_gw],
    )
    out_shape = [
        jax.ShapeDtypeStruct((Gp, W), slot_value.dtype),
        jax.ShapeDtypeStruct((A, Gp, W), p2a_off.dtype),
        jax.ShapeDtypeStruct((A, Gp, W), p2b_off.dtype),
        jax.ShapeDtypeStruct((Gp, W), last_send.dtype),
    ]
    sv, p2a, p2b, ls = pl.pallas_call(
        _p1_promise_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        status,
        vote_round,
        vote_value,
        slot_value,
        p2a_off,
        p2b_off,
        last_send,
        mask.astype(jnp.int8),
        learned.astype(jnp.int8),
        lat,
    )
    if pad:
        sv, p2a, p2b, ls = sv[:G], p2a[:, :G], p2b[:, :G], ls[:G]
    return sv, p2a, p2b, ls


# ---------------------------------------------------------------------------
# Plane: multipaxos_dispatch (tick steps 2-5: choose, watermark, propose,
# retry)
# ---------------------------------------------------------------------------


def reference_mp_dispatch(
    status,  # [G, W] int8
    slot_value,  # [G, W]
    propose_tick,  # [G, W] absolute ticks
    last_send,  # [G, W] absolute ticks
    chosen_tick,  # [G, W] absolute ticks
    chosen_round,  # [G, W] round dtype
    chosen_value,  # [G, W]
    replica_arrival,  # [G, W] absolute ticks
    p2a_off,  # [A, G, W] offset clocks
    p2b_off,  # [A, G, W] offset clocks
    vote_round,  # [A, G, W]
    vote_value,  # [A, G, W]
    nvotes,  # [G, W] int32 (vote-plane output)
    head,  # [G]
    next_slot,  # [G]
    leader_round,  # [G]
    cap,  # [G] int32: proposal budget (all gates except window space)
    retry_ok,  # [G] bool: retries allowed (owner alive, not reconfiguring)
    send_ok,  # [A, G, W] bool: thrifty quorum member AND delivered
    retry_deliv,  # [A, G, W] bool: retry fault-delivery mask
    p2a_lat,  # [A, G, W] clock dtype
    retry_lat,  # [A, G, W] clock dtype
    rep_lat,  # [G, W] int32
    group_ids,  # [G] int32 GLOBAL group ids (fresh proposal values)
    t,  # [] current tick
    *,
    f: int,
    retry_timeout: int,
    num_groups: int,
):
    """Tick steps 2-5 of multipaxos_batched as one plane: quorum ->
    Chosen (ProxyLeader.handlePhase2b), commit-latency capture, the
    contiguous-prefix commit-watermark advance (Replica.executeLog,
    Replica.scala:394-453) with all retire-clears, leader proposals
    into the freed window (Leader.scala:331-407) with their Phase2a
    fan-out, and timeout resends. [G]-space control (proposal caps,
    retry gates) is decided OUTSIDE and enters via ``cap``/``retry_ok``.
    ``group_ids`` carries each row's GLOBAL group id (the tick passes
    ``arange(G)``): fresh proposals encode ``slot * num_groups + g``,
    and under ``jax.shard_map`` a device sees only its slice of the
    arange — deriving ids from local positions would re-number every
    shard from zero.

    Returns a 21-tuple; see the wrapper for the order."""
    G, W = num_groups, status.shape[1]
    w_iota = jnp.arange(W, dtype=jnp.int32)
    newly_chosen = (status == PROPOSED) & (nvotes >= f + 1)
    chosen_tick = jnp.where(newly_chosen, t, chosen_tick)
    chosen_round = jnp.where(newly_chosen, leader_round[:, None], chosen_round)
    chosen_value = jnp.where(newly_chosen, slot_value, chosen_value)
    replica_arrival = jnp.where(newly_chosen, t + rep_lat, replica_arrival)
    status = jnp.where(newly_chosen, CHOSEN, status)
    latency = jnp.where(newly_chosen, t - propose_tick, 0)

    ord_of_pos = (w_iota[None, :] - head[:, None]) % W  # [G, W]
    executable = (
        (status == CHOSEN)
        & (replica_arrival <= t)
        & (ord_of_pos < (next_slot - head)[:, None])
    )
    n_retire, retire_mask = ring_retire_pos(executable, ord_of_pos)
    new_head = head + n_retire

    status = jnp.where(retire_mask, EMPTY, status)
    slot_value = jnp.where(retire_mask, NO_VALUE, slot_value)
    chosen_tick = jnp.where(retire_mask, INF, chosen_tick)
    chosen_round = jnp.where(retire_mask, -1, chosen_round)
    chosen_value = jnp.where(retire_mask, NO_VALUE, chosen_value)
    replica_arrival = jnp.where(retire_mask, INF, replica_arrival)
    propose_tick = jnp.where(retire_mask, INF, propose_tick)
    last_send = jnp.where(retire_mask, INF, last_send)
    p2a_off = jnp.where(retire_mask[None, :, :], INF16, p2a_off)
    p2b_off = jnp.where(retire_mask[None, :, :], INF16, p2b_off)
    vote_round = jnp.where(retire_mask[None, :, :], -1, vote_round)
    vote_value = jnp.where(retire_mask[None, :, :], NO_VALUE, vote_value)

    space = W - (next_slot - new_head)  # [G]
    count = jnp.minimum(cap, space)
    delta = (w_iota[None, :] - next_slot[:, None]) % W
    is_new = delta < count[:, None]
    new_next = next_slot + count
    status = jnp.where(is_new, PROPOSED, status)
    g_ids = group_ids[:, None]
    new_value = ((next_slot[:, None] + delta) * G + g_ids) & 0x7FFFFFFF
    slot_value = jnp.where(is_new, new_value, slot_value)
    propose_tick = jnp.where(is_new, t, propose_tick)
    last_send = jnp.where(is_new, t, last_send)
    p2a_off = jnp.where(is_new[None, :, :] & send_ok, p2a_lat, p2a_off)

    timed_out = (
        (status == PROPOSED)
        & (t - last_send >= retry_timeout)
        & retry_ok[:, None]
    )
    p2a_off = jnp.where(timed_out[None, :, :] & retry_deliv, retry_lat, p2a_off)
    last_send = jnp.where(timed_out, t, last_send)
    return (
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a_off, p2b_off, vote_round, vote_value,
        new_head, new_next, count, n_retire,
        newly_chosen, retire_mask, is_new, timed_out, latency,
    )


def _dispatch_slots(
    t, gids, status, sv_in, pt, ls, ct, cr, cv, ra, rep_lat,
    nvotes, head, next_slot, lr, cap, rok,
    *, f, retry_timeout, num_groups, bg, W,
):
    """The dispatch plane's slot-space body on [BG, W] values — the
    shared in-kernel program of the dispatch kernel and the megakernel.
    ``lr`` is [BG, 1]; ``rok`` an int8 [BG] mask; ``gids`` the block's
    [BG] GLOBAL group ids (the wrapper's ``group_ids`` input sliced by
    the grid — under shard_map these are the device's slice of the
    global arange, which block-local iotas could not reconstruct).
    Returns the updated slot arrays plus the masks the per-acceptor
    writes and the tick's stat reductions need."""
    import jax.lax as lax

    newly_chosen = (status == PROPOSED) & (nvotes >= f + 1)
    ct = jnp.where(newly_chosen, t, ct)
    cr = jnp.where(newly_chosen, lr, cr)
    cv = jnp.where(newly_chosen, sv_in, cv)
    ra = jnp.where(newly_chosen, t + rep_lat, ra)
    status = jnp.where(newly_chosen, CHOSEN, status)
    latency = jnp.where(newly_chosen, t - pt, 0)

    w_iota = lax.broadcasted_iota(jnp.int32, (bg, W), 1)
    ord_of_pos = (w_iota - head[:, None]) % W
    executable = (
        (status == CHOSEN)
        & (ra <= t)
        & (ord_of_pos < (next_slot - head)[:, None])
    )
    blocked = jnp.where(executable, W, ord_of_pos)
    n_retire = jnp.min(blocked, axis=1)  # [BG]
    retire_mask = ord_of_pos < n_retire[:, None]
    new_head = head + n_retire

    status = jnp.where(retire_mask, EMPTY, status)
    sv = jnp.where(retire_mask, NO_VALUE, sv_in)
    ct = jnp.where(retire_mask, INF_I, ct)
    cr = jnp.where(retire_mask, -1, cr)
    cv = jnp.where(retire_mask, NO_VALUE, cv)
    ra = jnp.where(retire_mask, INF_I, ra)
    pt = jnp.where(retire_mask, INF_I, pt)
    ls = jnp.where(retire_mask, INF_I, ls)

    space = W - (next_slot - new_head)
    count = jnp.minimum(cap, space)
    delta = (w_iota - next_slot[:, None]) % W
    is_new = delta < count[:, None]
    new_next = next_slot + count
    status = jnp.where(is_new, PROPOSED, status)
    g_ids = gids[:, None]
    new_value = (
        (next_slot[:, None] + delta) * num_groups + g_ids
    ) & 0x7FFFFFFF
    sv = jnp.where(is_new, new_value, sv)
    pt = jnp.where(is_new, t, pt)
    ls = jnp.where(is_new, t, ls)

    timed_out = (
        (status == PROPOSED)
        & (t - ls >= retry_timeout)
        & (rok[:, None] != 0)
    )
    ls = jnp.where(timed_out, t, ls)
    return (
        status, sv, pt, ls, ct, cr, cv, ra,
        new_head, new_next, count, n_retire,
        newly_chosen, retire_mask, is_new, timed_out, latency,
    )


def _dispatch_acceptor(
    retire_mask, is_new, timed_out, p2a, p2b, vr, vv, sok, rdel,
    p2a_lat, retry_lat,
):
    """One acceptor's dispatch-plane writes on [BG, W] values (shared
    by the dispatch kernel and the megakernel): retire-clears plus the
    Phase2a fan-out of fresh proposals and timeout resends. ``sok`` /
    ``rdel`` are int8 masks."""
    p2a = jnp.where(retire_mask, INF16, p2a)
    p2a = jnp.where(is_new & (sok != 0), p2a_lat, p2a)
    p2a = jnp.where(timed_out & (rdel != 0), retry_lat, p2a)
    p2b = jnp.where(retire_mask, INF16, p2b)
    vr = jnp.where(retire_mask, -1, vr)
    vv = jnp.where(retire_mask, NO_VALUE, vv)
    return p2a, p2b, vr, vv


def _mp_dispatch_kernel_factory(f, retry_timeout, num_groups, bg, W):
    def kernel(
        t_ref,  # SMEM (1,)
        status_ref, sv_ref, pt_ref, ls_ref,  # [BG, W]
        ct_ref, cr_ref, cv_ref, ra_ref,  # [BG, W]
        p2a_ref, p2b_ref, vr_ref, vv_ref,  # [A, BG, W]
        nv_ref, rep_lat_ref,  # [BG, W]
        head_ref, next_ref, lr_ref, cap_ref, rok_ref, gid_ref,  # [BG]
        sok_ref, rdel_ref, p2a_lat_ref, retry_lat_ref,  # [A, BG, W]
        out_status, out_sv, out_pt, out_ls,
        out_ct, out_cr, out_cv, out_ra,
        out_p2a, out_p2b, out_vr, out_vv,
        out_head, out_next, out_count, out_nret,
        out_newly, out_retire, out_isnew, out_timed, out_lat,
    ):
        t = t_ref[0]
        A = p2a_ref.shape[0]
        (
            status, sv, pt, ls, ct, cr, cv, ra,
            new_head, new_next, count, n_retire,
            newly_chosen, retire_mask, is_new, timed_out, latency,
        ) = _dispatch_slots(
            t, gid_ref[:],
            status_ref[:], sv_ref[:], pt_ref[:], ls_ref[:],
            ct_ref[:], cr_ref[:], cv_ref[:], ra_ref[:], rep_lat_ref[:],
            nv_ref[:], head_ref[:], next_ref[:], lr_ref[:][:, None],
            cap_ref[:], rok_ref[:],
            f=f, retry_timeout=retry_timeout, num_groups=num_groups,
            bg=bg, W=W,
        )
        out_status[:] = status
        out_sv[:] = sv
        out_pt[:] = pt
        out_ls[:] = ls
        out_ct[:] = ct
        out_cr[:] = cr
        out_cv[:] = cv
        out_ra[:] = ra
        out_head[:] = new_head
        out_next[:] = new_next
        out_count[:] = count
        out_nret[:] = n_retire
        out_newly[:] = newly_chosen.astype(jnp.int8)
        out_retire[:] = retire_mask.astype(jnp.int8)
        out_isnew[:] = is_new.astype(jnp.int8)
        out_timed[:] = timed_out.astype(jnp.int8)
        out_lat[:] = latency

        for a in range(A):
            p2a, p2b, vr, vv = _dispatch_acceptor(
                retire_mask, is_new, timed_out,
                p2a_ref[a], p2b_ref[a], vr_ref[a], vv_ref[a],
                sok_ref[a], rdel_ref[a], p2a_lat_ref[a], retry_lat_ref[a],
            )
            out_p2a[a] = p2a
            out_p2b[a] = p2b
            out_vr[a] = vr
            out_vv[a] = vv

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "block", "interpret", "f", "retry_timeout", "num_groups",
    ),
)
def fused_mp_dispatch(
    status, slot_value, propose_tick, last_send,
    chosen_tick, chosen_round, chosen_value, replica_arrival,
    p2a_off, p2b_off, vote_round, vote_value,
    nvotes, head, next_slot, leader_round, cap, retry_ok,
    send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
    block: int = 256,
    interpret: bool = False,
    f: int = 1,
    retry_timeout: int = 16,
    num_groups: int = 1,
):
    """Fused :func:`reference_mp_dispatch`: choose + watermark + clears
    + propose + retry in one VMEM-resident pass per group block."""
    from jax.experimental import pallas as pl

    A, G, W = p2a_off.shape
    bg, pad = balanced_block(G, block)
    gw = [
        status, slot_value, propose_tick, last_send, chosen_tick,
        chosen_round, chosen_value, replica_arrival, nvotes, rep_lat,
    ]
    agw = [
        p2a_off, p2b_off, vote_round, vote_value, send_ok, retry_deliv,
        p2a_lat, retry_lat,
    ]
    gv = [head, next_slot, leader_round, cap, retry_ok, group_ids]
    if pad:
        gw = [pad_axis(x, 0, pad) for x in gw]
        agw = [pad_axis(x, 1, pad) for x in agw]
        gv = [pad_axis(x, 0, pad) for x in gv]
    (status, slot_value, propose_tick, last_send, chosen_tick,
     chosen_round, chosen_value, replica_arrival, nvotes, rep_lat) = gw
    (p2a_off, p2b_off, vote_round, vote_value, send_ok, retry_deliv,
     p2a_lat, retry_lat) = agw
    head, next_slot, leader_round, cap, retry_ok, group_ids = gv
    Gp = G + pad

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec_g = pl.BlockSpec((bg,), lambda i: (i,))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=(
            [pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret))]
            + [spec_gw] * 8  # status..replica_arrival
            + [spec3] * 4  # p2a, p2b, vote_round, vote_value
            + [spec_gw] * 2  # nvotes, rep_lat
            + [spec_g] * 6  # head, next_slot, lr, cap, retry_ok, gids
            + [spec3] * 4  # send_ok, retry_deliv, p2a_lat, retry_lat
        ),
        out_specs=(
            [spec_gw] * 8
            + [spec3] * 4
            + [spec_g] * 4  # head, next, count, n_retire
            + [spec_gw] * 5  # newly, retire, is_new, timed_out, latency
        ),
    )
    i8 = jnp.int8
    out_shape = (
        [
            jax.ShapeDtypeStruct((Gp, W), status.dtype),
            jax.ShapeDtypeStruct((Gp, W), slot_value.dtype),
            jax.ShapeDtypeStruct((Gp, W), propose_tick.dtype),
            jax.ShapeDtypeStruct((Gp, W), last_send.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_tick.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_round.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_value.dtype),
            jax.ShapeDtypeStruct((Gp, W), replica_arrival.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), p2a_off.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), p2b_off.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), vote_round.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), vote_value.dtype),
            jax.ShapeDtypeStruct((Gp,), head.dtype),
            jax.ShapeDtypeStruct((Gp,), next_slot.dtype),
            jax.ShapeDtypeStruct((Gp,), jnp.int32),  # count
            jax.ShapeDtypeStruct((Gp,), jnp.int32),  # n_retire
        ]
        + [jax.ShapeDtypeStruct((Gp, W), i8)] * 4
        + [jax.ShapeDtypeStruct((Gp, W), jnp.int32)]  # latency
    )
    kernel = _mp_dispatch_kernel_factory(f, retry_timeout, num_groups, bg, W)
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a_off, p2b_off, vote_round, vote_value,
        nvotes, rep_lat,
        head, next_slot, leader_round, cap, retry_ok.astype(i8),
        group_ids,
        send_ok.astype(i8), retry_deliv.astype(i8), p2a_lat, retry_lat,
    )
    if pad:
        outs = [
            x[:, :G] if x.ndim == 3 else x[:G] for x in outs
        ]
    (status, slot_value, propose_tick, last_send,
     chosen_tick, chosen_round, chosen_value, replica_arrival,
     p2a_off, p2b_off, vote_round, vote_value,
     new_head, new_next, count, n_retire,
     newly, retire, is_new, timed, latency) = outs
    return (
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a_off, p2b_off, vote_round, vote_value,
        new_head, new_next, count, n_retire,
        newly.astype(bool), retire.astype(bool), is_new.astype(bool),
        timed.astype(bool), latency,
    )


# ---------------------------------------------------------------------------
# Plane: multipaxos_fused_tick (the whole-tick megakernel: clock aging +
# vote/quorum + dispatch in ONE grid program — State never round-trips
# HBM between planes)
# ---------------------------------------------------------------------------


def reference_fused_tick(
    p2a_off,  # [A, G, W] offset clocks (raw when age=True, aged otherwise)
    acc_round,  # [A, G]
    leader_round,  # [G]
    slot_value,  # [G, W]
    vote_round,  # [A, G, W]
    vote_value,  # [A, G, W]
    p2b_off,  # [A, G, W]
    p2b_lat,  # [A, G, W] clock dtype
    p2b_delivered,  # [A, G, W] bool
    head,  # [G]
    status,  # [G, W] int8
    propose_tick,  # [G, W]
    last_send,  # [G, W]
    chosen_tick,  # [G, W]
    chosen_round,  # [G, W]
    chosen_value,  # [G, W]
    replica_arrival,  # [G, W]
    next_slot,  # [G]
    cap,  # [G] int32
    retry_ok,  # [G] bool
    send_ok,  # [A, G, W] bool
    retry_deliv,  # [A, G, W] bool
    p2a_lat,  # [A, G, W] clock dtype
    retry_lat,  # [A, G, W] clock dtype
    rep_lat,  # [G, W] int32
    group_ids,  # [G] int32 GLOBAL group ids (fresh proposal values)
    t,  # []
    *,
    f: int,
    retry_timeout: int,
    num_groups: int,
    age: bool,
):
    """The megakernel's pure-jnp specification: EXACTLY the multi-plane
    path — optional clock aging, then :func:`reference_vote_quorum`,
    then :func:`reference_mp_dispatch` — so kernel-vs-reference
    bit-identity IS megakernel-vs-multi-plane bit-identity. ``age=True``
    folds the per-tick offset-clock aging in (the fast path, where
    nothing between aging and the planes touches the clocks);
    elections/reconfiguration repairs pass ``age=False`` and pre-aged
    arrays. Returns the 21 dispatch outputs plus ``(acc_round, nsends,
    max_ord)`` from the vote plane."""
    if age:
        p2a_off = age_clock(p2a_off)
        p2b_off = age_clock(p2b_off)
    vr, vv, p2b, accr, nvotes, nsends, max_ord = reference_vote_quorum(
        p2a_off, acc_round, leader_round, slot_value, vote_round,
        vote_value, p2b_off, p2b_lat, p2b_delivered, head,
    )
    outs = reference_mp_dispatch(
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a_off, p2b, vr, vv,
        nvotes, head, next_slot, leader_round, cap, retry_ok,
        send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
        f=f, retry_timeout=retry_timeout, num_groups=num_groups,
    )
    return (*outs, accr, nsends, max_ord)


def _fused_tick_kernel_factory(f, retry_timeout, num_groups, age, bg, W):
    def kernel(
        t_ref,  # SMEM (1,)
        p2a_ref, accr_ref, lr_ref, sv_ref,  # vote-plane inputs
        vr_ref, vv_ref, p2b_ref, p2b_lat_ref, deliv_ref, head_ref,
        status_ref, pt_ref, ls_ref, ct_ref,  # dispatch-plane inputs
        cr_ref, cv_ref, ra_ref, next_ref, cap_ref, rok_ref, gid_ref,
        sok_ref, rdel_ref, p2a_lat_ref, retry_lat_ref, rep_lat_ref,
        out_status, out_sv, out_pt, out_ls,
        out_ct, out_cr, out_cv, out_ra,
        out_p2a, out_p2b, out_vr, out_vv,
        out_head, out_next, out_count, out_nret,
        out_newly, out_retire, out_isnew, out_timed, out_lat,
        out_accr, out_ns, out_maxord,
    ):
        import jax.lax as lax

        t = t_ref[0]
        A = p2a_ref.shape[0]
        lr = lr_ref[:][:, None]  # [BG, 1]
        sv_in = sv_ref[:]
        head = head_ref[:]
        w_iota = lax.broadcasted_iota(jnp.int32, (bg, W), 1)
        ord_of_pos = (w_iota - head[:, None]) % W

        # ---- Vote/quorum (the shared _vote_step body, with the
        # per-tick clock aging folded in on the fast path). The
        # per-acceptor vote state lives in VMEM registers across BOTH
        # planes — this is the HBM round trip the megakernel deletes.
        nvotes = jnp.zeros((bg, W), jnp.int32)
        nsends = jnp.zeros((bg, W), jnp.int32)
        p2a_a, p2b_a, vr_a, vv_a = [], [], [], []
        for a in range(A):
            p2a = p2a_ref[a]
            p2b = p2b_ref[a]
            if age:
                p2a = age_clock(p2a)
                p2b = age_clock(p2b)
            new_vr, new_vv, new_p2b, new_accr, max_ord, votes, sends = (
                _vote_step(
                    lr, sv_in, accr_ref[a], p2a, vr_ref[a], vv_ref[a],
                    p2b, p2b_lat_ref[a], deliv_ref[a], ord_of_pos,
                )
            )
            out_accr[a] = new_accr
            out_maxord[a] = max_ord
            nvotes = nvotes + votes
            nsends = nsends + sends.astype(jnp.int32)
            p2a_a.append(p2a)
            p2b_a.append(new_p2b)
            vr_a.append(new_vr)
            vv_a.append(new_vv)
        out_ns[:] = nsends

        # ---- Dispatch (the shared _dispatch_slots body: quorum ->
        # Chosen, watermark + retire-clears, propose, retry), reading
        # the vote step's outputs straight out of VMEM.
        (
            status, sv, pt, ls, ct, cr, cv, ra,
            new_head, new_next, count, n_retire,
            newly_chosen, retire_mask, is_new, timed_out, latency,
        ) = _dispatch_slots(
            t, gid_ref[:],
            status_ref[:], sv_in, pt_ref[:], ls_ref[:],
            ct_ref[:], cr_ref[:], cv_ref[:], ra_ref[:], rep_lat_ref[:],
            nvotes, head, next_ref[:], lr, cap_ref[:], rok_ref[:],
            f=f, retry_timeout=retry_timeout, num_groups=num_groups,
            bg=bg, W=W,
        )
        out_status[:] = status
        out_sv[:] = sv
        out_pt[:] = pt
        out_ls[:] = ls
        out_ct[:] = ct
        out_cr[:] = cr
        out_cv[:] = cv
        out_ra[:] = ra
        out_head[:] = new_head
        out_next[:] = new_next
        out_count[:] = count
        out_nret[:] = n_retire
        out_newly[:] = newly_chosen.astype(jnp.int8)
        out_retire[:] = retire_mask.astype(jnp.int8)
        out_isnew[:] = is_new.astype(jnp.int8)
        out_timed[:] = timed_out.astype(jnp.int8)
        out_lat[:] = latency

        for a in range(A):
            p2a, p2b, vr, vv = _dispatch_acceptor(
                retire_mask, is_new, timed_out,
                p2a_a[a], p2b_a[a], vr_a[a], vv_a[a],
                sok_ref[a], rdel_ref[a], p2a_lat_ref[a], retry_lat_ref[a],
            )
            out_p2a[a] = p2a
            out_p2b[a] = p2b
            out_vr[a] = vr
            out_vv[a] = vv

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "block", "interpret", "f", "retry_timeout", "num_groups", "age",
    ),
)
def fused_tick(
    p2a_off, acc_round, leader_round, slot_value,
    vote_round, vote_value, p2b_off, p2b_lat, p2b_delivered, head,
    status, propose_tick, last_send, chosen_tick,
    chosen_round, chosen_value, replica_arrival, next_slot, cap, retry_ok,
    send_ok, retry_deliv, p2a_lat, retry_lat, rep_lat, group_ids, t,
    block: int = 128,
    interpret: bool = False,
    f: int = 1,
    retry_timeout: int = 16,
    num_groups: int = 1,
    age: bool = True,
):
    """Fused :func:`reference_fused_tick`: the whole MultiPaxos tick hot
    path — aging + vote/quorum + dispatch — as one ``pallas_call`` per
    tick, gridded over group blocks with everything VMEM-resident."""
    from jax.experimental import pallas as pl

    A, G, W = p2a_off.shape
    bg, pad = balanced_block(G, block)
    agw = [
        p2a_off, vote_round, vote_value, p2b_off, p2b_lat, p2b_delivered,
        send_ok, retry_deliv, p2a_lat, retry_lat,
    ]
    gw = [
        slot_value, status, propose_tick, last_send, chosen_tick,
        chosen_round, chosen_value, replica_arrival, rep_lat,
    ]
    gv = [leader_round, head, next_slot, cap, retry_ok, group_ids]
    ag = [acc_round]
    if pad:
        agw = [pad_axis(x, 1, pad) for x in agw]
        gw = [pad_axis(x, 0, pad) for x in gw]
        gv = [pad_axis(x, 0, pad) for x in gv]
        ag = [pad_axis(x, 1, pad) for x in ag]
    (p2a_off, vote_round, vote_value, p2b_off, p2b_lat, p2b_delivered,
     send_ok, retry_deliv, p2a_lat, retry_lat) = agw
    (slot_value, status, propose_tick, last_send, chosen_tick,
     chosen_round, chosen_value, replica_arrival, rep_lat) = gw
    leader_round, head, next_slot, cap, retry_ok, group_ids = gv
    (acc_round,) = ag
    Gp = G + pad

    spec3 = pl.BlockSpec((A, bg, W), lambda i: (0, i, 0))
    spec2 = pl.BlockSpec((A, bg), lambda i: (0, i))
    spec_g = pl.BlockSpec((bg,), lambda i: (i,))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=(
            [pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret))]
            + [spec3, spec2, spec_g, spec_gw]  # p2a, acc_round, lr, sv
            + [spec3] * 4  # vote_round, vote_value, p2b, p2b_lat
            + [spec3, spec_g]  # delivered, head
            + [spec_gw] * 7  # status .. replica_arrival
            + [spec_g] * 4  # next_slot, cap, retry_ok, gids
            + [spec3] * 4  # send_ok, retry_deliv, p2a_lat, retry_lat
            + [spec_gw]  # rep_lat
        ),
        out_specs=(
            [spec_gw] * 8
            + [spec3] * 4
            + [spec_g] * 4  # head, next, count, n_retire
            + [spec_gw] * 5  # newly, retire, is_new, timed_out, latency
            + [spec2, spec_gw, spec2]  # acc_round, nsends, max_ord
        ),
    )
    i8 = jnp.int8
    out_shape = (
        [
            jax.ShapeDtypeStruct((Gp, W), status.dtype),
            jax.ShapeDtypeStruct((Gp, W), slot_value.dtype),
            jax.ShapeDtypeStruct((Gp, W), propose_tick.dtype),
            jax.ShapeDtypeStruct((Gp, W), last_send.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_tick.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_round.dtype),
            jax.ShapeDtypeStruct((Gp, W), chosen_value.dtype),
            jax.ShapeDtypeStruct((Gp, W), replica_arrival.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), p2a_off.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), p2b_off.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), vote_round.dtype),
            jax.ShapeDtypeStruct((A, Gp, W), vote_value.dtype),
            jax.ShapeDtypeStruct((Gp,), head.dtype),
            jax.ShapeDtypeStruct((Gp,), next_slot.dtype),
            jax.ShapeDtypeStruct((Gp,), jnp.int32),  # count
            jax.ShapeDtypeStruct((Gp,), jnp.int32),  # n_retire
        ]
        + [jax.ShapeDtypeStruct((Gp, W), i8)] * 4
        + [
            jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # latency
            jax.ShapeDtypeStruct((A, Gp), acc_round.dtype),
            jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # nsends
            jax.ShapeDtypeStruct((A, Gp), jnp.int32),  # max_ord
        ]
    )
    kernel = _fused_tick_kernel_factory(
        f, retry_timeout, num_groups, age, bg, W
    )
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        p2a_off, acc_round, leader_round, slot_value,
        vote_round, vote_value, p2b_off, p2b_lat,
        p2b_delivered.astype(i8), head,
        status, propose_tick, last_send, chosen_tick,
        chosen_round, chosen_value, replica_arrival,
        next_slot, cap, retry_ok.astype(i8), group_ids,
        send_ok.astype(i8), retry_deliv.astype(i8), p2a_lat, retry_lat,
        rep_lat,
    )
    if pad:
        # Slice the G padding off by position: [A, G, W] and [A, G]
        # arrays pad axis 1; [G, W] and [G] arrays pad axis 0.
        axis1 = {8, 9, 10, 11, 21, 23}  # p2a/p2b/vr/vv, acc_round, max_ord
        outs = [
            x[:, :G] if i in axis1 else x[:G] for i, x in enumerate(outs)
        ]
    (status, slot_value, propose_tick, last_send,
     chosen_tick, chosen_round, chosen_value, replica_arrival,
     p2a_off, p2b_off, vote_round, vote_value,
     new_head, new_next, count, n_retire,
     newly, retire, is_new, timed, latency,
     accr, nsends, max_ord) = outs
    return (
        status, slot_value, propose_tick, last_send,
        chosen_tick, chosen_round, chosen_value, replica_arrival,
        p2a_off, p2b_off, vote_round, vote_value,
        new_head, new_next, count, n_retire,
        newly.astype(bool), retire.astype(bool), is_new.astype(bool),
        timed.astype(bool), latency,
        accr, nsends, max_ord,
    )


# ---------------------------------------------------------------------------
# Registration
# ---------------------------------------------------------------------------

# ShardSpecs (registry.ShardSpec): every MultiPaxos plane is group-local
# — no cross-group dataflow anywhere — so each declares, per positional
# arg/output, where the group axis sits ([A, G, W] -> 1, [G, ...] -> 0,
# scalars -> None) and the sharding layer lowers the kernel per-device
# via jax.shard_map instead of rejecting the policy at mesh > 1.

registry.register(
    registry.Plane(
        name="multipaxos_vote_quorum",
        backend="multipaxos",
        reference=reference_vote_quorum,
        kernel=fused_vote_quorum,
        key_of=lambda args: args[0].shape,  # (A, G, W)
        batch_axis=1,  # grids over G
        default_block=256,
        shard=registry.ShardSpec(
            arg_axes=(1, 1, 0, 0, 1, 1, 1, 1, 1, 0),
            out_axes=(1, 1, 1, 1, 0, 0, 1),
        ),
    )
)

registry.register(
    registry.Plane(
        name="multipaxos_p1_promise",
        backend="multipaxos",
        reference=reference_p1_promise,
        kernel=fused_p1_promise,
        key_of=lambda args: args[1].shape,  # vote_round: (A, G, W)
        batch_axis=1,  # grids over G
        default_block=256,
        shard=registry.ShardSpec(
            arg_axes=(0, 1, 1, 0, 1, 1, 0, 0, 1, 1, None),
            out_axes=(0, 1, 1, 0),
        ),
    )
)

registry.register(
    registry.Plane(
        name="multipaxos_dispatch",
        backend="multipaxos",
        reference=reference_mp_dispatch,
        kernel=fused_mp_dispatch,
        key_of=lambda args: args[8].shape,  # p2a_off: (A, G, W)
        batch_axis=1,  # grids over G
        default_block=256,
        shard=registry.ShardSpec(
            arg_axes=(
                0, 0, 0, 0, 0, 0, 0, 0,  # status..replica_arrival
                1, 1, 1, 1,  # p2a, p2b, vote_round, vote_value
                0, 0, 0, 0, 0, 0,  # nvotes, head, next, lr, cap, retry_ok
                1, 1, 1, 1,  # send_ok, retry_deliv, p2a_lat, retry_lat
                0, 0, None,  # rep_lat, group_ids, t
            ),
            out_axes=(
                0, 0, 0, 0, 0, 0, 0, 0,  # status..replica_arrival
                1, 1, 1, 1,  # p2a, p2b, vote_round, vote_value
                0, 0, 0, 0,  # head, next, count, n_retire
                0, 0, 0, 0, 0,  # newly, retire, is_new, timed, latency
            ),
        ),
    )
)

registry.register(
    registry.Plane(
        name="multipaxos_fused_tick",
        backend="multipaxos",
        reference=reference_fused_tick,
        kernel=fused_tick,
        key_of=lambda args: args[0].shape,  # p2a_off: (A, G, W)
        batch_axis=1,  # grids over G
        # More live VMEM per block than any per-plane kernel (the whole
        # tick's arrays at once): a smaller default block; the autotune
        # table overrides per shape.
        default_block=128,
        shard=registry.ShardSpec(
            arg_axes=(
                1, 1, 0, 0,  # p2a, acc_round, leader_round, slot_value
                1, 1, 1, 1, 1, 0,  # vr, vv, p2b, p2b_lat, deliv, head
                0, 0, 0, 0, 0, 0, 0,  # status..replica_arrival
                0, 0, 0,  # next_slot, cap, retry_ok
                1, 1, 1, 1,  # send_ok, retry_deliv, p2a_lat, retry_lat
                0, 0, None,  # rep_lat, group_ids, t
            ),
            out_axes=(
                0, 0, 0, 0, 0, 0, 0, 0,  # status..replica_arrival
                1, 1, 1, 1,  # p2a, p2b, vote_round, vote_value
                0, 0, 0, 0,  # head, next, count, n_retire
                0, 0, 0, 0, 0,  # newly, retire, is_new, timed, latency
                1, 0, 1,  # acc_round, nsends, max_ord
            ),
        ),
    )
)
