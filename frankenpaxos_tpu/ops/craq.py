"""Fused Pallas kernel for the batched CRAQ chain plane.

``craq_chain`` covers tick steps 1-2 of ``tpu/craq_batched.py``: DOWN
writes arriving at mid-chain nodes join their pending sets and forward;
the tail applies + replies + starts the ack; UP acks apply locally,
leave the pending set, and keep propagating (ChainNode.scala:120-299).

The XLA version's hot ops are four scatters into the flattened
``[N, L*KV]`` node state. Scatters don't vectorize on the VPU, so the
kernel recasts them as ONE-HOT ACCUMULATIONS over the (static, small)
write ring: for each ring slot w, a ``[BN, L*KV]`` equality mask
scatters its contribution as a masked add/max. Addition and max both
commute, so the accumulation is bit-identical to the reference's
scatter order. The whole plane — both scatter families plus the
advance/retire logic — runs in one VMEM-resident pass per chain block.

Partitions buffer hops until the heal tick (``faults.defer_to_heal``):
the plan's side bits, start, and heal tick enter as STATICS (``side``,
``partition_start``, ``partition_heal``) and the kernel rewrites every
hop into a cut-side node to ``max(arrival, heal)`` while the cut is
live — the node-side lookup is a static unrolled loop over the tiny
chain length, so partitioned plans ride the kernel instead of routing
to the reference (the carried PR 4 follow-up (c)). Drop/jitter fault
penalties land in ``hop_lat`` BEFORE dispatch, as before.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import (
    INF_I,
    balanced_block,
    pad_axis,
    t_arr,
    t_space,
)
from frankenpaxos_tpu.tpu.common import INF

# Mirrors of the backend's write-slot codes (ops must not import the
# backend). Cross-checked by tests/test_kernel_registry.
W_EMPTY = 0
W_DOWN = 1
W_UP = 2


def _hop_fn(side, partition_start, partition_heal, t):
    """The partition hop-deferral closure (faults.defer_to_heal
    semantics): arrivals at cut-side nodes while the cut is live wait
    for the heal tick. Identity when no partition sides are given."""
    if not (side and any(side)):
        return lambda arrival, node: arrival
    sides = jnp.array(side, jnp.int32)
    heal = jnp.int32(partition_heal if partition_heal >= 0 else int(INF))
    active = t >= jnp.int32(partition_start)
    if partition_heal >= 0:
        active = active & (t < jnp.int32(partition_heal))

    def hop(arrival, node):
        cut = active & (sides[node] == 1)
        return jnp.where(cut, jnp.maximum(arrival, heal), arrival)

    return hop


def reference_craq_chain(
    w_status: jnp.ndarray,  # [N, W] int8
    w_key: jnp.ndarray,  # [N, W]
    w_version: jnp.ndarray,  # [N, W]
    w_node: jnp.ndarray,  # [N, W]
    w_arrival: jnp.ndarray,  # [N, W] absolute ticks
    w_issue: jnp.ndarray,  # [N, W]
    node_dirty_flat: jnp.ndarray,  # [N, L*KV]
    node_version_flat: jnp.ndarray,  # [N, L*KV]
    hop_lat: jnp.ndarray,  # [N, W]
    t: jnp.ndarray,  # []
    *,
    tail: int,
    num_keys: int,
    side: tuple = (),
    partition_start: int = 0,
    partition_heal: int = -1,
):
    """The pure-jnp specification (tick steps 1-2 of craq_batched).
    Returns ``(w_status', w_node', w_arrival', node_dirty',
    node_version', at_tail, wlat)`` — ``at_tail`` [N, W] marks tail
    applies (client-visible write completions) and ``wlat`` their
    latencies, for the stats the tick keeps outside. With ``side``
    bits, hops INTO cut-side nodes defer to the heal tick
    (``faults.defer_to_heal`` TCP partition semantics)."""
    N, W = w_status.shape
    KV = num_keys
    n_rows = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32)[:, None], (N, W))
    _hop = _hop_fn(side, partition_start, partition_heal, t)

    # ---- DOWN arrivals (ChainNode._process_write_batch).
    arrive_down = (w_status == W_DOWN) & (w_arrival == t)
    at_mid = arrive_down & (w_node < tail)
    at_tail = arrive_down & (w_node == tail)
    wslot = w_node * KV + w_key
    node_dirty_flat = node_dirty_flat.at[n_rows, wslot].add(
        at_mid.astype(jnp.int32)
    )
    node_version_flat = node_version_flat.at[n_rows, wslot].max(
        jnp.where(at_tail, w_version, -1)
    )
    wlat = jnp.where(at_tail, t + hop_lat - w_issue, 0)
    w_node = jnp.where(at_mid, w_node + 1, w_node)
    w_node = jnp.where(at_tail, tail - 1, w_node)
    w_status = jnp.where(at_tail, W_UP, w_status)
    w_arrival = jnp.where(
        arrive_down, _hop(t + hop_lat, w_node), w_arrival
    )

    # ---- UP (ack) arrivals (ChainNode._handle_ack).
    arrive_up = (w_status == W_UP) & (w_arrival == t)
    uslot = w_node * KV + w_key
    node_version_flat = node_version_flat.at[n_rows, uslot].max(
        jnp.where(arrive_up, w_version, -1)
    )
    node_dirty_flat = node_dirty_flat.at[n_rows, uslot].add(
        -arrive_up.astype(jnp.int32)
    )
    retire = arrive_up & (w_node == 0)
    w_status = jnp.where(retire, W_EMPTY, w_status)
    w_arrival = jnp.where(retire, INF, w_arrival)
    keep_up = arrive_up & ~retire
    w_node = jnp.where(keep_up, w_node - 1, w_node)
    w_arrival = jnp.where(keep_up, _hop(t + hop_lat, w_node), w_arrival)
    return (
        w_status, w_node, w_arrival, node_dirty_flat, node_version_flat,
        at_tail, wlat,
    )


def _craq_chain_kernel_factory(
    tail, num_keys, W, LKV, side=(), partition_start=0, partition_heal=-1
):
    KV = num_keys
    partitioned = bool(side and any(side))
    heal_v = partition_heal if partition_heal >= 0 else INF_I

    def kernel(
        t_ref,  # SMEM (1,)
        ws_ref, wk_ref, wv_ref, wn_ref, wa_ref, wi_ref, lat_ref,  # [BN, W]
        dirty_ref, ver_ref,  # [BN, LKV]
        out_ws, out_wn, out_wa,  # [BN, W]
        out_dirty, out_ver,  # [BN, LKV]
        out_at_tail, out_wlat,  # [BN, W]
    ):
        import jax.lax as lax

        t = t_ref[0]
        ws = ws_ref[:]
        wn = wn_ref[:]
        wa = wa_ref[:]
        wk = wk_ref[:]
        wv = wv_ref[:]
        lat = lat_ref[:]

        if partitioned:
            # Hop deferral (faults.defer_to_heal): the side bits are
            # STATIC, so the node-side lookup unrolls over the tiny
            # chain length and the cut-liveness test is two compares
            # against compile-time ticks.
            cut_live = t >= partition_start
            if partition_heal >= 0:
                cut_live = cut_live & (t < partition_heal)

            def _hop(arrival, node):
                is_cut = jnp.zeros(node.shape, bool)
                for l, s in enumerate(side):
                    if s:
                        is_cut = is_cut | (node == l)
                return jnp.where(
                    cut_live & is_cut,
                    jnp.maximum(arrival, heal_v),
                    arrival,
                )
        else:

            def _hop(arrival, node):
                return arrival

        arrive_down = (ws == W_DOWN) & (wa == t)
        at_mid = arrive_down & (wn < tail)
        at_tail = arrive_down & (wn == tail)
        wslot = wn * KV + wk
        out_at_tail[:] = at_tail.astype(jnp.int8)
        out_wlat[:] = jnp.where(at_tail, t + lat - wi_ref[:], 0)

        wn1 = jnp.where(at_mid, wn + 1, wn)
        wn1 = jnp.where(at_tail, tail - 1, wn1)
        ws1 = jnp.where(at_tail, W_UP, ws)
        wa1 = jnp.where(arrive_down, _hop(t + lat, wn1), wa)

        arrive_up = (ws1 == W_UP) & (wa1 == t)
        uslot = wn1 * KV + wk
        retire = arrive_up & (wn1 == 0)
        ws2 = jnp.where(retire, W_EMPTY, ws1)
        wa2 = jnp.where(retire, INF_I, wa1)
        keep_up = arrive_up & ~retire
        wn2 = jnp.where(keep_up, wn1 - 1, wn1)
        wa2 = jnp.where(keep_up, _hop(t + lat, wn2), wa2)
        out_ws[:] = ws2
        out_wn[:] = wn2
        out_wa[:] = wa2

        # The scatter families as one-hot accumulations over the static
        # write ring (adds and maxes commute: bit-identical to the
        # reference's scatters).
        bn = dirty_ref.shape[0]
        j_iota = lax.broadcasted_iota(jnp.int32, (bn, LKV), 1)
        dirty = dirty_ref[:]
        ver = ver_ref[:]
        for w in range(W):
            eq_w = j_iota == wslot[:, w][:, None]  # [BN, LKV]
            eq_u = j_iota == uslot[:, w][:, None]
            dirty = dirty + jnp.where(
                eq_w & at_mid[:, w][:, None], 1, 0
            )
            dirty = dirty - jnp.where(
                eq_u & arrive_up[:, w][:, None], 1, 0
            )
            contrib = jnp.where(
                eq_w & at_tail[:, w][:, None], wv[:, w][:, None], -1
            )
            contrib = jnp.maximum(
                contrib,
                jnp.where(
                    eq_u & arrive_up[:, w][:, None], wv[:, w][:, None], -1
                ),
            )
            ver = jnp.maximum(ver, contrib)
        out_dirty[:] = dirty
        out_ver[:] = ver

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "block", "interpret", "tail", "num_keys", "side",
        "partition_start", "partition_heal",
    ),
)
def fused_craq_chain(
    w_status,
    w_key,
    w_version,
    w_node,
    w_arrival,
    w_issue,
    node_dirty_flat,
    node_version_flat,
    hop_lat,
    t,
    block: int = 256,
    interpret: bool = False,
    tail: int = 1,
    num_keys: int = 1,
    side: tuple = (),
    partition_start: int = 0,
    partition_heal: int = -1,
):
    """Fused :func:`reference_craq_chain`, gridded over chain blocks;
    partition plans ride along via the static side/start/heal knobs."""
    from jax.experimental import pallas as pl

    N, W = w_status.shape
    LKV = node_dirty_flat.shape[1]
    bn, pad = balanced_block(N, block)
    nw = [w_status, w_key, w_version, w_node, w_arrival, w_issue, hop_lat]
    if pad:
        nw = [pad_axis(x, 0, pad) for x in nw]
        node_dirty_flat = pad_axis(node_dirty_flat, 0, pad)
        node_version_flat = pad_axis(node_version_flat, 0, pad)
    w_status, w_key, w_version, w_node, w_arrival, w_issue, hop_lat = nw
    Np = N + pad

    spec_nw = pl.BlockSpec((bn, W), lambda i: (i, 0))
    spec_nk = pl.BlockSpec((bn, LKV), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Np // bn,),
        in_specs=(
            [pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret))]
            + [spec_nw] * 7
            + [spec_nk] * 2
        ),
        out_specs=[spec_nw] * 3 + [spec_nk] * 2 + [spec_nw] * 2,
    )
    out_shape = [
        jax.ShapeDtypeStruct((Np, W), w_status.dtype),
        jax.ShapeDtypeStruct((Np, W), w_node.dtype),
        jax.ShapeDtypeStruct((Np, W), w_arrival.dtype),
        jax.ShapeDtypeStruct((Np, LKV), node_dirty_flat.dtype),
        jax.ShapeDtypeStruct((Np, LKV), node_version_flat.dtype),
        jax.ShapeDtypeStruct((Np, W), jnp.int8),  # at_tail
        jax.ShapeDtypeStruct((Np, W), jnp.int32),  # wlat
    ]
    kernel = _craq_chain_kernel_factory(
        tail, num_keys, W, LKV, side, partition_start, partition_heal
    )
    ws, wn, wa, dirty, ver, at_tail, wlat = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        w_status, w_key, w_version, w_node, w_arrival, w_issue, hop_lat,
        node_dirty_flat, node_version_flat,
    )
    if pad:
        ws, wn, wa = ws[:N], wn[:N], wa[:N]
        dirty, ver = dirty[:N], ver[:N]
        at_tail, wlat = at_tail[:N], wlat[:N]
    return ws, wn, wa, dirty, ver, at_tail.astype(bool), wlat


registry.register(
    registry.Plane(
        name="craq_chain",
        backend="craq",
        reference=reference_craq_chain,
        kernel=fused_craq_chain,
        key_of=lambda args: (
            args[0].shape[0],  # N
            args[6].shape[1],  # L*KV
            args[0].shape[1],  # W
        ),
        default_block=256,
    )
)
