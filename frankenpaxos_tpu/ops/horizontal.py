"""Fused Pallas kernel for the batched Horizontal MultiPaxos vote plane.

``horizontal_vote`` covers tick steps 1-2 of
``tpu/horizontal_batched.py``: acceptors of the slot's BANK process
Phase2a arrivals (the pool is two banks of ``n = 2f+1`` rows; epoch
parity picks the active bank — votes only land where
``bank_of_row == slot_epoch % 2``), schedule Phase2b replies, the
per-slot in-bank quorum count chooses, and the bank-isolation ledger
counts any vote sitting in the WRONG bank (the horizontal analog of
"no value chosen by the wrong configuration"). Five elementwise
[P, G, W] passes plus a reduction in XLA; one VMEM-resident pass here,
with the pool axis as a static unrolled loop (bank membership of each
row is a compile-time constant, so the bank masks cost nothing).

The chunk machinery (watermark walk, phase-1 handover, the
configuration-as-log-value proposal driver) stays in XLA — it is
[G]-space control, exactly the split the flagship planes use.
FaultPlans compose from OUTSIDE: the pool-axis delivery masks
(drops/cuts) enter as the ``p2b_delivered`` input, identical to the
flagship vote plane's contract.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.ops import registry
from frankenpaxos_tpu.ops.blocks import (
    INF_I,
    balanced_block,
    pad_axis,
    t_arr,
    t_space,
)
from frankenpaxos_tpu.tpu.common import INF

# Mirrors of the backend's slot codes (ops must not import the backend).
# Cross-checked by tests/test_kernel_registry.
EMPTY = 0
PROPOSED = 1
CHOSEN = 2
NO_VALUE = -1


def reference_horizontal_vote(
    slot_epoch: jnp.ndarray,  # [G, W] chunk epoch stamped at proposal (-1)
    status: jnp.ndarray,  # [G, W] int8
    propose_tick: jnp.ndarray,  # [G, W]
    p2a_arrival: jnp.ndarray,  # [P, G, W] absolute arrival ticks (INF)
    p2b_arrival: jnp.ndarray,  # [P, G, W]
    voted: jnp.ndarray,  # [P, G, W] bool
    vote_epoch: jnp.ndarray,  # [P, G, W] epoch the vote was cast under
    p2b_lat: jnp.ndarray,  # [P, G, W] sampled latencies
    p2b_delivered: jnp.ndarray,  # [P, G, W] bool (fault delivery mask)
    t: jnp.ndarray,  # []
    *,
    n: int,
    quorum: int,
):
    """The pure-jnp specification (tick steps 1-2 of horizontal_batched).
    Returns the updated vote/arrival arrays plus ``newly_chosen``, the
    per-slot commit latencies, and the per-slot wrong-bank vote counts
    the tick's ledger reduces outside."""
    P = p2a_arrival.shape[0]
    bank_of_row = (jnp.arange(P, dtype=jnp.int32) >= n).astype(jnp.int32)

    # ---- 1. Acceptors vote on arriving Phase2as — but ONLY rows in the
    # bank the slot's chunk owns.
    slot_bank = jnp.mod(slot_epoch, 2)  # [G, W]
    row_matches = bank_of_row[:, None, None] == slot_bank[None, :, :]
    p2a_now = p2a_arrival == t
    may_vote = p2a_now & row_matches & (status == PROPOSED)[None, :, :]
    new_voted = voted | may_vote
    new_vote_epoch = jnp.where(
        may_vote, slot_epoch[None, :, :], vote_epoch
    )
    # Under a fault plan the VOTE lands but the Phase2b reply may be
    # dropped or cut (the retry plane re-solicits it after a heal).
    p2b_send = may_vote & p2b_delivered
    new_p2b = jnp.where(p2b_send, t + p2b_lat, p2b_arrival)
    new_p2a = jnp.where(p2a_now, INF, p2a_arrival)

    # ---- 2. Quorums form: f+1 arrived Phase2bs within the slot's bank.
    arrived = (new_p2b <= t) & new_voted & row_matches
    votes_in_bank = jnp.sum(arrived, axis=0)  # [G, W]
    newly_chosen = (status == PROPOSED) & (votes_in_bank >= quorum)
    new_status = jnp.where(newly_chosen, CHOSEN, status)
    lat = jnp.where(newly_chosen, t - propose_tick, 0)
    # Bank isolation ledger: votes observed OUTSIDE their slot's bank.
    viol = jnp.sum(
        (new_voted & ~row_matches & (slot_epoch >= 0)[None, :, :]).astype(
            jnp.int32
        ),
        axis=0,
    )  # [G, W]
    return (
        new_status, new_p2a, new_p2b, new_voted, new_vote_epoch,
        newly_chosen, lat, viol,
    )


def _horizontal_vote_kernel_factory(n, quorum, P):
    def kernel(
        t_ref,  # SMEM (1,)
        se_ref, status_ref, pt_ref,  # [BG, W]
        p2a_ref, p2b_ref, voted_ref, ve_ref,  # [P, BG, W]
        lat_ref, deliv_ref,  # [P, BG, W]
        out_status, out_p2a, out_p2b, out_voted, out_ve,
        out_newly, out_lat, out_viol,
    ):
        t = t_ref[0]
        slot_epoch = se_ref[:]
        status = status_ref[:]
        slot_bank = jnp.mod(slot_epoch, 2)
        proposed = status == PROPOSED
        epoch_set = slot_epoch >= 0
        votes_in = jnp.zeros(status.shape, jnp.int32)
        viol = jnp.zeros(status.shape, jnp.int32)
        # The pool axis is static (2n rows): bank membership of each row
        # is a compile-time constant, so the bank masks are plain
        # comparisons against a Python int.
        for p in range(P):
            row_matches = slot_bank == (1 if p >= n else 0)
            p2a_now = p2a_ref[p] == t
            may_vote = p2a_now & row_matches & proposed
            new_voted = (voted_ref[p] != 0) | may_vote
            p2b_send = may_vote & (deliv_ref[p] != 0)
            new_p2b = jnp.where(p2b_send, t + lat_ref[p], p2b_ref[p])
            out_voted[p] = new_voted.astype(jnp.int8)
            out_ve[p] = jnp.where(may_vote, slot_epoch, ve_ref[p])
            out_p2b[p] = new_p2b
            out_p2a[p] = jnp.where(p2a_now, INF_I, p2a_ref[p])
            votes_in = votes_in + (
                (new_p2b <= t) & new_voted & row_matches
            ).astype(jnp.int32)
            viol = viol + (
                new_voted & ~row_matches & epoch_set
            ).astype(jnp.int32)
        newly_chosen = proposed & (votes_in >= quorum)
        out_status[:] = jnp.where(newly_chosen, CHOSEN, status)
        out_lat[:] = jnp.where(newly_chosen, t - pt_ref[:], 0)
        out_newly[:] = newly_chosen.astype(jnp.int8)
        out_viol[:] = viol

    return kernel


@functools.partial(
    jax.jit, static_argnames=("block", "interpret", "n", "quorum")
)
def fused_horizontal_vote(
    slot_epoch,
    status,
    propose_tick,
    p2a_arrival,
    p2b_arrival,
    voted,
    vote_epoch,
    p2b_lat,
    p2b_delivered,
    t,
    block: int = 256,
    interpret: bool = False,
    n: int = 3,
    quorum: int = 2,
):
    """Fused :func:`reference_horizontal_vote`, gridded over group
    blocks with the 2n-row pool axis unrolled."""
    from jax.experimental import pallas as pl

    P, G, W = p2a_arrival.shape
    bg, pad = balanced_block(G, block)
    pgw = [p2a_arrival, p2b_arrival, voted, vote_epoch, p2b_lat, p2b_delivered]
    gw = [slot_epoch, status, propose_tick]
    if pad:
        pgw = [pad_axis(x, 1, pad) for x in pgw]
        gw = [pad_axis(x, 0, pad) for x in gw]
    p2a_arrival, p2b_arrival, voted, vote_epoch, p2b_lat, p2b_delivered = pgw
    slot_epoch, status, propose_tick = gw
    Gp = G + pad

    spec3 = pl.BlockSpec((P, bg, W), lambda i: (0, i, 0))
    spec_gw = pl.BlockSpec((bg, W), lambda i: (i, 0))
    grid_spec = pl.GridSpec(
        grid=(Gp // bg,),
        in_specs=(
            [pl.BlockSpec((1,), lambda i: (0,), memory_space=t_space(interpret))]
            + [spec_gw] * 3  # slot_epoch, status, propose_tick
            + [spec3] * 6  # p2a, p2b, voted, vote_epoch, lat, delivered
        ),
        out_specs=(
            [spec_gw]  # status
            + [spec3] * 4  # p2a, p2b, voted, vote_epoch
            + [spec_gw] * 3  # newly_chosen, lat, viol
        ),
    )
    i8 = jnp.int8
    out_shape = [
        jax.ShapeDtypeStruct((Gp, W), status.dtype),
        jax.ShapeDtypeStruct((P, Gp, W), p2a_arrival.dtype),
        jax.ShapeDtypeStruct((P, Gp, W), p2b_arrival.dtype),
        jax.ShapeDtypeStruct((P, Gp, W), i8),  # voted
        jax.ShapeDtypeStruct((P, Gp, W), vote_epoch.dtype),
        jax.ShapeDtypeStruct((Gp, W), i8),  # newly_chosen
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # lat
        jax.ShapeDtypeStruct((Gp, W), jnp.int32),  # viol
    ]
    kernel = _horizontal_vote_kernel_factory(n, quorum, P)
    (st, p2a, p2b, vtd, ve, newly, lat, viol) = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(
        t_arr(t),
        slot_epoch, status, propose_tick,
        p2a_arrival, p2b_arrival, voted.astype(i8), vote_epoch,
        p2b_lat, p2b_delivered.astype(i8),
    )
    if pad:
        st, newly, lat, viol = st[:G], newly[:G], lat[:G], viol[:G]
        p2a, p2b, vtd, ve = (
            p2a[:, :G], p2b[:, :G], vtd[:, :G], ve[:, :G]
        )
    return (
        st, p2a, p2b, vtd.astype(bool), ve,
        newly.astype(bool), lat, viol,
    )


registry.register(
    registry.Plane(
        name="horizontal_vote",
        backend="horizontal",
        reference=reference_horizontal_vote,
        kernel=fused_horizontal_vote,
        key_of=lambda args: args[3].shape,  # p2a_arrival: (P, G, W)
        batch_axis=1,  # grids over G
        default_block=256,
    )
)
