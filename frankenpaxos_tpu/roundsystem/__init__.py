"""Round systems: assignment of integer rounds to leaders, each round
classic or fast.

Capability parity with
``shared/src/main/scala/frankenpaxos/roundsystem/RoundSystem.scala``:
``ClassicRoundRobin`` (:60-87), ``ClassicStutteredRoundRobin`` (:118-167),
``RoundZeroFast`` (:183-212), ``MixedRoundRobin`` (:229-264),
``RenamedRoundSystem``/``RotatedRoundSystem`` and the rotated convenience
classes (:291-424). Every leader owns infinitely many classic rounds;
``next_classic_round(leader, round)`` is the smallest classic round for
``leader`` strictly greater than ``round`` (or the first one if round < 0).
"""

from __future__ import annotations

import enum
from typing import Dict, Optional


class RoundType(enum.Enum):
    CLASSIC = "classic"
    FAST = "fast"


class RoundSystem:
    def num_leaders(self) -> int:
        raise NotImplementedError

    def leader(self, round: int) -> int:
        raise NotImplementedError

    def round_type(self, round: int) -> RoundType:
        raise NotImplementedError

    def next_classic_round(self, leader_index: int, round: int) -> int:
        raise NotImplementedError

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        raise NotImplementedError


class ClassicRoundRobin(RoundSystem):
    """Classic rounds assigned round-robin; no fast rounds."""

    def __init__(self, n: int):
        self.n = n

    def __repr__(self) -> str:
        return f"ClassicRoundRobin({self.n})"

    def num_leaders(self) -> int:
        return self.n

    def leader(self, round: int) -> int:
        return round % self.n

    def round_type(self, round: int) -> RoundType:
        return RoundType.CLASSIC

    def next_classic_round(self, leader_index: int, round: int) -> int:
        if round < 0:
            return leader_index
        base = self.n * (round // self.n)
        offset = leader_index % self.n
        return base + offset if base + offset > round else base + self.n + offset

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        return None


class ClassicStutteredRoundRobin(RoundSystem):
    """Round-robin in stutters of ``stutter_length`` (a leader owns runs of
    consecutive rounds); no fast rounds."""

    def __init__(self, n: int, stutter_length: int):
        if n <= 1:
            raise ValueError("n must be > 1")
        if stutter_length < 1:
            raise ValueError("stutter_length must be >= 1")
        self.n = n
        self.stutter = stutter_length

    def __repr__(self) -> str:
        return f"ClassicStutteredRoundRobin(n={self.n}, stutter={self.stutter})"

    def num_leaders(self) -> int:
        return self.n

    def leader(self, round: int) -> int:
        return (round // self.stutter) % self.n

    def round_type(self, round: int) -> RoundType:
        return RoundType.CLASSIC

    def next_classic_round(self, leader_index: int, round: int) -> int:
        if round < 0:
            return leader_index * self.stutter
        if self.leader(round + 1) == leader_index:
            return round + 1
        chunk = self.n * self.stutter
        start_of_chunk = chunk * (round // chunk)
        start_of_stutter = start_of_chunk + leader_index * self.stutter
        if self.leader(round) < leader_index:
            return start_of_stutter
        return start_of_stutter + chunk

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        return None


class RoundZeroFast(RoundSystem):
    """Round 0 is fast (leader 0); rounds 1, 2, ... are classic round-robin.
    Used by BPaxos and implicitly EPaxos."""

    def __init__(self, n: int):
        self.n = n

    def __repr__(self) -> str:
        return f"RoundZeroFast({self.n})"

    def num_leaders(self) -> int:
        return self.n

    def leader(self, round: int) -> int:
        return 0 if round == 0 else (round - 1) % self.n

    def round_type(self, round: int) -> RoundType:
        return RoundType.FAST if round == 0 else RoundType.CLASSIC

    def next_classic_round(self, leader_index: int, round: int) -> int:
        return 1 + ClassicRoundRobin(self.n).next_classic_round(
            leader_index, round - 1
        )

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        return 0 if leader_index == 0 and round < 0 else None


class MixedRoundRobin(RoundSystem):
    """Contiguous (fast, classic) round pairs assigned round-robin."""

    def __init__(self, n: int):
        self.n = n

    def __repr__(self) -> str:
        return f"MixedRoundRobin({self.n})"

    def num_leaders(self) -> int:
        return self.n

    def leader(self, round: int) -> int:
        return (round // 2) % self.n

    def round_type(self, round: int) -> RoundType:
        return RoundType.FAST if round % 2 == 0 else RoundType.CLASSIC

    def next_classic_round(self, leader_index: int, round: int) -> int:
        if round >= 0 and (round // 2) % self.n == leader_index and round % 2 == 0:
            return round + 1
        return self.next_fast_round(leader_index, round) + 1

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        if round < 0:
            return leader_index * 2
        return ClassicRoundRobin(self.n).next_classic_round(
            leader_index, round // 2
        ) * 2


class RenamedRoundSystem(RoundSystem):
    """Adapts a round system by permuting leader identities."""

    def __init__(self, round_system: RoundSystem, renaming: Dict[int, int]):
        self.rs = round_system
        self.renaming = dict(renaming)
        self.unrenaming = {v: k for k, v in renaming.items()}

    def __repr__(self) -> str:
        return f"Renamed({self.rs!r}, {self.renaming})"

    def num_leaders(self) -> int:
        return self.rs.num_leaders()

    def leader(self, round: int) -> int:
        return self.renaming[self.rs.leader(round)]

    def round_type(self, round: int) -> RoundType:
        return self.rs.round_type(round)

    def next_classic_round(self, leader_index: int, round: int) -> int:
        return self.rs.next_classic_round(self.unrenaming[leader_index], round)

    def next_fast_round(self, leader_index: int, round: int) -> Optional[int]:
        return self.rs.next_fast_round(self.unrenaming[leader_index], round)


class RotatedRoundSystem(RenamedRoundSystem):
    """Renamed round system with leaders rotated by ``rotation``."""

    def __init__(self, round_system: RoundSystem, rotation: int):
        n = round_system.num_leaders()
        super().__init__(round_system, {i: (i + rotation) % n for i in range(n)})
        self.rotation = rotation


class RotatedClassicRoundRobin(RotatedRoundSystem):
    def __init__(self, n: int, first_leader: int):
        super().__init__(ClassicRoundRobin(n), first_leader)

    def __repr__(self) -> str:
        return f"RotatedClassicRoundRobin({self.rs.n}, {self.rotation})"


class RotatedRoundZeroFast(RotatedRoundSystem):
    def __init__(self, n: int, first_leader: int):
        super().__init__(RoundZeroFast(n), first_leader)

    def __repr__(self) -> str:
        return f"RotatedRoundZeroFast({self.rs.n}, {self.rotation})"
