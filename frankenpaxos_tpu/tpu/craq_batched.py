"""Batched CRAQ as a single XLA program.

CRAQ — chain replication with apportioned queries (reference ``craq/
ChainNode.scala:120-299``): writes enter at the head and flow down the
chain, the tail applies and replies, acks flow back up and every node
applies on ack; reads go to ANY node and are served locally iff the key
has no pending writes at that node ("clean"), otherwise forwarded to the
tail ("dirty") — apportioning read load across the whole chain while
staying linearizable.

TPU-first design: ``N`` independent chains of ``L`` nodes are the
replica axis (vectorized elementwise, shardable along ``N`` — a chain
never talks to another chain). "The network" is device memory:

  * In-flight writes live in a per-chain ring of ``W`` slots; a write's
    position in the chain is a (direction, node, arrival-tick) triple,
    and one tick moves every write at most one hop (a masked scatter
    into the per-node state — no per-message objects).
  * Per-node CRAQ state is two ``[N, L, KV]`` arrays: ``node_dirty``
    (pending-write counts per key — the ``pending_writes`` set of
    ChainNode.scala, reduced to what reads need: a count) and
    ``node_version`` (the version each node has applied).
  * Versions are a per-chain monotone sequence; nodes and the tail
    apply by scatter-MAX, so a later write overtaking an earlier one on
    the (non-FIFO) simulated links still resolves last-writer-wins —
    the batched analog of the FIFO-link assumption the reference
    inherits from TCP, made explicit and order-insensitive.
  * Reads ride their own ring: issue -> node (clean check = one gather
    of ``node_dirty``) -> optional tail hop -> reply, with the
    linearizability floor (the tail's committed version at issue)
    checked on completion, exactly like the batched MultiPaxos read
    invariant.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Write slot status.
W_EMPTY = 0
W_DOWN = 1  # propagating head -> tail
W_UP = 2  # ack propagating tail -> head

# Read slot status.
R_EMPTY = 0
R_AT_NODE = 1  # request in flight to the chosen node
R_TAIL = 2  # dirty: version query in flight to the tail
R_REPLY = 3  # reply in flight to the client


@dataclasses.dataclass(frozen=True)
class BatchedCraqConfig:
    """Static parameters: N chains x L nodes, KV keys per chain."""

    num_chains: int = 4
    chain_len: int = 3  # L >= 2 (head + tail at minimum)
    num_keys: int = 16  # KV: key space per chain
    window: int = 16  # W: in-flight writes per chain
    writes_per_tick: int = 2  # K
    reads_per_tick: int = 2  # R
    read_window: int = 16  # RW: outstanding reads per chain
    lat_min: int = 1
    lat_max: int = 3
    # Unified in-graph fault injection (tpu/faults.py), TCP semantics
    # (the chain runs on reliable links): drops become retransmission
    # penalties on hop latencies, and a CHAIN-NODE partition (side bits
    # over the L nodes) buffers hops INTO cut nodes until the heal tick
    # — writes queue behind the cut and drain afterwards, so the
    # pending-set conservation invariants hold throughout.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-chain
    # write admission; a read/write mix routes the read share to the
    # apportioned-read ring (needs reads_per_tick > 0). Completions
    # are tail applies. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the chain
    # propagate/ack plane (tick steps 1-2) routes through
    # ops.registry.dispatch. Partitioned plans ride the kernel too —
    # the plan's side bits enter the plane as statics and hops into cut
    # nodes defer to the heal tick IN-KERNEL (ops/craq.py).
    kernels: KernelPolicy = KernelPolicy()

    def __post_init__(self):
        assert self.num_chains >= 1
        assert self.chain_len >= 2
        assert self.num_keys >= 1
        assert self.window >= 2 * self.writes_per_tick
        if self.reads_per_tick:
            assert self.read_window >= 2 * self.reads_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        self.faults.validate(axis=self.chain_len)
        self.workload.validate(reads_supported=self.reads_per_tick > 0)
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedCraqState:
    """Shapes: [N] chains, [N, W] write ring, [N, L, KV] node state,
    [N, RW] read ring."""

    # Write ring.
    w_status: jnp.ndarray  # [N, W] W_EMPTY | W_DOWN | W_UP
    w_key: jnp.ndarray  # [N, W]
    w_version: jnp.ndarray  # [N, W] per-chain monotone version
    w_node: jnp.ndarray  # [N, W] node the write/ack is heading to
    w_arrival: jnp.ndarray  # [N, W] tick it arrives there (INF = idle)
    w_issue: jnp.ndarray  # [N, W] issue tick (write latency)

    # Per-node CRAQ state.
    node_dirty: jnp.ndarray  # [N, L, KV] pending-write count per key
    node_version: jnp.ndarray  # [N, L, KV] applied version (-1 = none)
    next_version: jnp.ndarray  # [N] per-chain version counter

    # Read ring.
    r_status: jnp.ndarray  # [N, RW]
    r_key: jnp.ndarray  # [N, RW]
    r_node: jnp.ndarray  # [N, RW] chosen node
    r_arrival: jnp.ndarray  # [N, RW] next event tick (INF = idle)
    r_issue: jnp.ndarray  # [N, RW]
    r_floor: jnp.ndarray  # [N, RW] tail version at issue (lin floor)
    r_version: jnp.ndarray  # [N, RW] served version

    # Stats.
    writes_done: jnp.ndarray  # [] writes applied at the tail (replied)
    write_lat_sum: jnp.ndarray  # []
    write_lat_hist: jnp.ndarray  # [LAT_BINS]
    reads_done: jnp.ndarray  # []
    reads_clean: jnp.ndarray  # [] served locally at the chosen node
    reads_dirty: jnp.ndarray  # [] forwarded to the tail
    read_lat_sum: jnp.ndarray  # []
    read_lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    read_lin_violations: jnp.ndarray  # [] reads below their floor
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedCraqConfig) -> BatchedCraqState:
    N, L, KV = cfg.num_chains, cfg.chain_len, cfg.num_keys
    W, RW = cfg.window, cfg.read_window
    return BatchedCraqState(
        w_status=jnp.zeros((N, W), DTYPE_STATUS),
        w_key=jnp.zeros((N, W), jnp.int32),
        w_version=jnp.full((N, W), -1, jnp.int32),
        w_node=jnp.zeros((N, W), jnp.int32),
        w_arrival=jnp.full((N, W), INF, jnp.int32),
        w_issue=jnp.full((N, W), INF, jnp.int32),
        node_dirty=jnp.zeros((N, L, KV), jnp.int32),
        node_version=jnp.full((N, L, KV), -1, jnp.int32),
        next_version=jnp.zeros((N,), jnp.int32),
        r_status=jnp.zeros((N, RW), DTYPE_STATUS),
        r_key=jnp.zeros((N, RW), jnp.int32),
        r_node=jnp.zeros((N, RW), jnp.int32),
        r_arrival=jnp.full((N, RW), INF, jnp.int32),
        r_issue=jnp.full((N, RW), INF, jnp.int32),
        r_floor=jnp.full((N, RW), -1, jnp.int32),
        r_version=jnp.full((N, RW), -1, jnp.int32),
        writes_done=jnp.zeros((), jnp.int32),
        write_lat_sum=jnp.zeros((), jnp.int32),
        write_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        reads_done=jnp.zeros((), jnp.int32),
        reads_clean=jnp.zeros((), jnp.int32),
        reads_dirty=jnp.zeros((), jnp.int32),
        read_lat_sum=jnp.zeros((), jnp.int32),
        read_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_chains, cfg.faults
        ),
        read_lin_violations=jnp.zeros((), jnp.int32),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedCraqConfig,
    state: BatchedCraqState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedCraqState:
    """One tick: writes/acks advance one hop, the tail applies+replies,
    reads route (clean local / dirty via tail) and complete."""
    N, L, KV = cfg.num_chains, cfg.chain_len, cfg.num_keys
    W, RW = cfg.window, cfg.read_window
    tail = L - 1
    kw, kr = jax.random.split(key)
    bits_w = jax.random.bits(kw, (N, W))  # [0:8) hop lat, [8:24) new key
    bits_r = jax.random.bits(kr, (N, RW))  # [0:8) hop lat, [8:20) key,
    #                                        [20:28) node choice
    hop_lat_w = bit_latency(bits_w, 0, cfg.lat_min, cfg.lat_max)
    hop_lat_r = bit_latency(bits_r, 0, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py), TCP semantics: drops are
    # retransmission penalties on hop latencies; `_hop(arr, node)` below
    # buffers hops whose TARGET node sits on the cut side of an active
    # partition until the heal tick. Under a none plan `_hop` is the
    # identity and the latencies are untouched (structural no-op).
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    if fp.active:
        kf = faults_mod.fault_key(key)
        hop_lat_w = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 0), (N, W), hop_lat_w, rates=frates
        )
        hop_lat_r = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 1), (N, RW), hop_lat_r, rates=frates
        )
    if fp.has_partition:
        _side = faults_mod.partition_sides(fp)
        _cut_live = faults_mod.partition_active(fp, t)

        def _hop(arrival, node):
            cut = _cut_live & (_side[node] == 1)
            return faults_mod.defer_to_heal(fp, arrival, cut)
    else:

        def _hop(arrival, node):
            return arrival

    w_status = state.w_status
    w_node = state.w_node
    w_arrival = state.w_arrival
    node_dirty_flat = state.node_dirty.reshape(N, L * KV)
    node_version_flat = state.node_version.reshape(N, L * KV)
    writes_done = state.writes_done
    write_lat_sum = state.write_lat_sum
    write_lat_hist = state.write_lat_hist

    # ---- 1+2. The chain propagate/ack plane (ChainNode._process_write_
    # batch + ChainNode._handle_ack): DOWN writes join pending sets and
    # forward, the tail applies + replies + starts the ack, UP acks
    # apply locally and propagate, the head ack retires the ring slot.
    # One registry plane (ops/craq.py): the kernel recasts the four
    # scatters as one-hot accumulations in one VMEM-resident pass, and
    # partitioned plans ride it too — the plan's side bits enter as
    # statics and hops into cut nodes defer to the heal tick in-kernel
    # (the same `faults.defer_to_heal` rewrite `_hop` applies to the
    # read/issue sites below).
    (
        w_status,
        w_node,
        w_arrival,
        node_dirty_flat,
        node_version_flat,
        at_tail,
        wlat,
    ) = ops_registry.dispatch(
        "craq_chain",
        cfg,
        w_status,
        state.w_key,
        state.w_version,
        w_node,
        w_arrival,
        state.w_issue,
        node_dirty_flat,
        node_version_flat,
        hop_lat_w,
        t,
        tail=tail,
        num_keys=KV,
        side=tuple(fp.partition) if fp.has_partition else (),
        partition_start=fp.partition_start,
        partition_heal=fp.partition_heal,
    )
    writes_done = writes_done + jnp.sum(at_tail)
    write_lat_sum = write_lat_sum + jnp.sum(wlat)
    wbins = jnp.clip(wlat, 0, LAT_BINS - 1)
    write_lat_hist = write_lat_hist + jax.ops.segment_sum(
        at_tail.astype(jnp.int32).ravel(), wbins.ravel(), LAT_BINS
    )

    # ---- 3. Reads (apportioned queries, ChainNode._process_read_batch).
    r_status = state.r_status
    r_key = state.r_key
    r_node = state.r_node
    r_arrival = state.r_arrival
    r_issue = state.r_issue
    r_floor = state.r_floor
    r_version = state.r_version
    reads_done = state.reads_done
    reads_clean = state.reads_clean
    reads_dirty = state.reads_dirty
    read_lat_sum = state.read_lat_sum
    read_lat_hist = state.read_lat_hist
    read_lin_violations = state.read_lin_violations
    # Workload arrivals (tpu/workload.py): drawn before the read block
    # so the read share of the mix feeds the apportioned-read ring.
    if wl.active:
        wl_writes, wl_reads, wls = workload_mod.begin(wl, wls, key, t, N)
    # Gate on the ring EXISTING (not on the issue rate): tests inject
    # reads by hand with reads_per_tick == 0 and still need routing.
    if cfg.read_window:
        # (a) Completions free their slots (and check the lin floor).
        done = (r_status == R_REPLY) & (r_arrival <= t)
        rlat = jnp.where(done, t - r_issue, 0)
        reads_done = reads_done + jnp.sum(done)
        read_lat_sum = read_lat_sum + jnp.sum(rlat)
        rbins = jnp.clip(rlat, 0, LAT_BINS - 1)
        read_lat_hist = read_lat_hist + jax.ops.segment_sum(
            done.astype(jnp.int32).ravel(), rbins.ravel(), LAT_BINS
        )
        read_lin_violations = read_lin_violations + jnp.sum(
            done & (r_version < r_floor)
        )
        r_status = jnp.where(done, R_EMPTY, r_status)
        r_arrival = jnp.where(done, INF, r_arrival)

        # (b) Node arrivals: one gather answers "is the key dirty here".
        at_node = (r_status == R_AT_NODE) & (r_arrival == t)
        rslot = r_node * KV + r_key
        dirty_here = (
            jnp.take_along_axis(node_dirty_flat, rslot, axis=1) > 0
        )
        clean = at_node & ~dirty_here
        dirty = at_node & dirty_here
        local_ver = jnp.take_along_axis(node_version_flat, rslot, axis=1)
        r_version = jnp.where(clean, local_ver, r_version)
        r_status = jnp.where(clean, R_REPLY, r_status)
        r_status = jnp.where(dirty, R_TAIL, r_status)
        # Dirty queries hop to the tail; clean replies hop back over the
        # serving node's client link.
        r_arrival = jnp.where(
            at_node,
            _hop(t + hop_lat_r, jnp.where(dirty, tail, r_node)),
            r_arrival,
        )
        reads_clean = reads_clean + jnp.sum(clean)
        reads_dirty = reads_dirty + jnp.sum(dirty)

        # (c) Tail arrivals (CraqTailRead): serve the tail's version.
        at_tail_r = (r_status == R_TAIL) & (r_arrival == t)
        tslot = tail * KV + r_key
        tail_ver = jnp.take_along_axis(node_version_flat, tslot, axis=1)
        r_version = jnp.where(at_tail_r, tail_ver, r_version)
        r_status = jnp.where(at_tail_r, R_REPLY, r_status)
        r_arrival = jnp.where(
            at_tail_r, _hop(t + hop_lat_r, tail), r_arrival
        )

        # (d) Issue new reads at a PRNG node/key; the floor is the tail's
        # committed version for the key right now.
        empty_r = r_status == R_EMPTY
        rank_r = jnp.cumsum(empty_r.astype(jnp.int32), axis=1)
        if wl.has_reads:
            issue_r = empty_r & (rank_r <= wl_reads[:, None])
        else:
            issue_r = empty_r & (rank_r <= cfg.reads_per_tick)
        new_key_r = (
            ((bits_r >> 8) & jnp.uint32(0xFFF)).astype(jnp.int32) % KV
        )
        new_node = (
            ((bits_r >> 20) & jnp.uint32(0xFF)).astype(jnp.int32) % L
        )
        floor_slot = tail * KV + new_key_r
        floor_now = jnp.take_along_axis(
            node_version_flat, floor_slot, axis=1
        )
        r_key = jnp.where(issue_r, new_key_r, r_key)
        r_node = jnp.where(issue_r, new_node, r_node)
        r_floor = jnp.where(issue_r, floor_now, r_floor)
        r_issue = jnp.where(issue_r, t, r_issue)
        r_version = jnp.where(issue_r, -1, r_version)
        r_status = jnp.where(issue_r, R_AT_NODE, r_status)
        r_arrival = jnp.where(
            issue_r, _hop(t + hop_lat_r, new_node), r_arrival
        )

    # ---- 4. New writes into empty ring slots (CraqClient.write -> head).
    empty_w = w_status == W_EMPTY
    rank_w = jnp.cumsum(empty_w.astype(jnp.int32), axis=1)
    # Workload admission (tpu/workload.py): under a shaping plan the
    # static writes_per_tick knob becomes the per-chain cap.
    if wl.active:
        adm = workload_mod.admission(wl, wls, wl_writes)
        issue_w = empty_w & (rank_w <= adm[:, None])
    else:
        issue_w = empty_w & (rank_w <= cfg.writes_per_tick)
    count_w = jnp.sum(issue_w, axis=1)  # [N]
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count_w, jnp.sum(at_tail, axis=1)
        )
    new_key_w = (
        ((bits_w >> 8) & jnp.uint32(0xFFFF)).astype(jnp.int32) % KV
    )
    new_version = state.next_version[:, None] + rank_w - 1
    w_key = jnp.where(issue_w, new_key_w, state.w_key)
    w_version = jnp.where(issue_w, new_version, state.w_version)
    w_node = jnp.where(issue_w, 0, w_node)
    w_status = jnp.where(issue_w, W_DOWN, w_status)
    w_arrival = jnp.where(issue_w, _hop(t + hop_lat_w, 0), w_arrival)
    w_issue = jnp.where(issue_w, t, state.w_issue)
    next_version = state.next_version + count_w

    # Telemetry: writes entering the head are "proposals", tail applies
    # are "commits", completed reads "executes"; dirty reads forwarded
    # to the tail are the chain's extra message plane.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(issue_w),
        phase2_msgs=reads_dirty - state.reads_dirty,
        commits=writes_done - state.writes_done,
        executes=reads_done - state.reads_done,
        queue_depth=jnp.sum(w_status != W_EMPTY),
        queue_capacity=N * W,
        lat_hist_delta=write_lat_hist - state.write_lat_hist,
    )

    return BatchedCraqState(
        w_status=w_status,
        w_key=w_key,
        w_version=w_version,
        w_node=w_node,
        w_arrival=w_arrival,
        w_issue=w_issue,
        node_dirty=node_dirty_flat.reshape(N, L, KV),
        node_version=node_version_flat.reshape(N, L, KV),
        next_version=next_version,
        r_status=r_status,
        r_key=r_key,
        r_node=r_node,
        r_arrival=r_arrival,
        r_issue=r_issue,
        r_floor=r_floor,
        r_version=r_version,
        writes_done=writes_done,
        write_lat_sum=write_lat_sum,
        write_lat_hist=write_lat_hist,
        reads_done=reads_done,
        reads_clean=reads_clean,
        reads_dirty=reads_dirty,
        read_lat_sum=read_lat_sum,
        read_lat_hist=read_lat_hist,
        workload=wls,
        read_lin_violations=read_lin_violations,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedCraqConfig,
    state: BatchedCraqState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedCraqState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedCraqConfig, state: BatchedCraqState, t
) -> dict:
    """Device-side safety checks."""
    L, KV = cfg.chain_len, cfg.num_keys
    down = state.w_status == W_DOWN
    up = state.w_status == W_UP
    # Pending-set conservation: a DOWN write heading to node m is pending
    # at nodes 0..m-1 (m entries); an UP ack heading to node m has been
    # acked at m+1..L-2, so the write is still pending at 0..m (m+1).
    expected_dirty = jnp.sum(
        jnp.where(down, state.w_node, 0) + jnp.where(up, state.w_node + 1, 0)
    )
    dirty_conserved = jnp.sum(state.node_dirty) == expected_dirty
    dirty_nonneg = jnp.all(state.node_dirty >= 0)
    # A node never applies ahead of the tail (acks follow the tail apply).
    tail_ver = state.node_version[:, L - 1 : L, :]
    node_behind_tail = jnp.all(state.node_version <= tail_ver)
    # Versions applied anywhere were actually issued.
    ver_issued = jnp.all(
        state.node_version < state.next_version[:, None, None]
    )
    # Write accounting: every issued write is in flight or done.
    in_flight = jnp.sum(state.w_status != W_EMPTY)
    # writes_done counts tail applies; UP acks are done-but-in-flight.
    acked_in_flight = jnp.sum(up)
    write_books = (
        jnp.sum(state.next_version) == state.writes_done + in_flight
        - acked_in_flight
    )
    # Apportioned reads stay linearizable.
    read_lin_ok = state.read_lin_violations == 0
    read_books = state.reads_clean + state.reads_dirty >= state.reads_done
    return {
        "dirty_conserved": dirty_conserved,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "dirty_nonneg": dirty_nonneg,
        "node_behind_tail": node_behind_tail,
        "ver_issued": ver_issued,
        "write_books": write_books,
        "read_lin_ok": read_lin_ok,
        "read_books": read_books,
    }


def stats(cfg: BatchedCraqConfig, state: BatchedCraqState, t) -> dict:
    """Host-side summary (mirrors TpuSimTransport.stats)."""
    writes = int(state.writes_done)
    reads = int(state.reads_done)
    whist = jax.device_get(state.write_lat_hist)
    rhist = jax.device_get(state.read_lat_hist)

    def p50(hist, n):
        if not n:
            return -1
        return int((hist.cumsum() >= max(1, (n + 1) // 2)).argmax())

    clean = int(state.reads_clean)
    dirty = int(state.reads_dirty)
    return {
        "ticks": int(t),
        "writes_done": writes,
        "write_latency_p50_ticks": p50(whist, writes),
        "write_latency_mean_ticks": (
            float(state.write_lat_sum) / writes if writes else -1.0
        ),
        "reads_done": reads,
        "read_latency_p50_ticks": p50(rhist, reads),
        "reads_clean": clean,
        "reads_dirty": dirty,
        "clean_fraction": clean / max(1, clean + dirty),
        "read_lin_violations": int(state.read_lin_violations),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedCraqConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedCraqConfig(
        num_chains=4, chain_len=3, num_keys=8, window=8,
        writes_per_tick=2, reads_per_tick=2, read_window=8,
        workload=workload,
        faults=faults,
    )
