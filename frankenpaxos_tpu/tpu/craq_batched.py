"""Batched CRAQ as a single XLA program.

CRAQ — chain replication with apportioned queries (reference ``craq/
ChainNode.scala:120-299``): writes enter at the head and flow down the
chain, the tail applies and replies, acks flow back up and every node
applies on ack; reads go to ANY node and are served locally iff the key
has no pending writes at that node ("clean"), otherwise forwarded to the
tail ("dirty") — apportioning read load across the whole chain while
staying linearizable.

TPU-first design: ``N`` independent chains of ``L`` nodes are the
replica axis (vectorized elementwise, shardable along ``N`` — a chain
never talks to another chain). "The network" is device memory:

  * In-flight writes live in a per-chain ring of ``W`` slots; a write's
    position in the chain is a (direction, node, arrival-tick) triple,
    and one tick moves every write at most one hop (a masked scatter
    into the per-node state — no per-message objects).
  * Per-node CRAQ state is two ``[N, L, KV]`` arrays: ``node_dirty``
    (pending-write counts per key — the ``pending_writes`` set of
    ChainNode.scala, reduced to what reads need: a count) and
    ``node_version`` (the version each node has applied).
  * Versions are a per-chain monotone sequence; nodes and the tail
    apply by scatter-MAX, so a later write overtaking an earlier one on
    the (non-FIFO) simulated links still resolves last-writer-wins —
    the batched analog of the FIFO-link assumption the reference
    inherits from TCP, made explicit and order-insensitive.
  * Reads ride their own ring: issue -> node (clean check = one gather
    of ``node_dirty``) -> optional tail hop -> reply, with the
    linearizability floor (the tail's committed version at issue)
    checked on completion, exactly like the batched MultiPaxos read
    invariant.

Chain-node crash semantics (``FaultPlan.crash_rate``/``revive_rate`` —
the carried PR 3 (b) fault-coverage gap): MIDDLE nodes crash and revive
per tick (head and tail are pinned alive — their replacement is a
chain-membership-service event outside this model, exactly as in the
reference where the coordination service reconfigures the chain).
While a node is dead,

  * the chain RE-STITCHES around it in-tick: a write or read hop whose
    target is dead redirects to the next alive node toward the tail
    (its predecessor links to its successor — ChainNode repair), so
    writes keep flowing and reads keep completing;
  * each write carries a VISITED bitmask of the nodes whose pending
    sets it joined, so acks propagate back only through nodes that
    actually saw the write (pending-set conservation stays EXACT under
    crashes: total dirty == popcount of in-flight visited masks) — an
    ack whose next visited node is currently dead BUFFERS (its arrival
    slides tick by tick) and re-propagates the moment the node
    revives;
  * a dead-then-revived node is SUSPECT until every in-flight write
    that bypassed it has drained; suspect nodes forward all reads to
    the tail (apportioned-query safety: a bypassed write would
    otherwise make a stale key look clean), and on clearing they bulk
    catch up by copying the tail's versions (the buffered
    re-propagation of everything they missed) — after which they serve
    clean reads again, exactly as if they had never crashed.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import (
    DTYPE_STATUS,
    INF,
    LAT_BINS,
    bit_latency,
)
# Submodule import (see multipaxos_batched: package-attr access on
# frankenpaxos_tpu.ops would be circular during tpu package init).
from frankenpaxos_tpu.ops import registry as ops_registry
from frankenpaxos_tpu.ops.registry import KernelPolicy
from frankenpaxos_tpu.tpu import faults as faults_mod
from frankenpaxos_tpu.tpu import workload as workload_mod
from frankenpaxos_tpu.tpu.faults import FaultPlan
from frankenpaxos_tpu.tpu.workload import WorkloadPlan, WorkloadState
from frankenpaxos_tpu.tpu import telemetry as telemetry_mod
from frankenpaxos_tpu.tpu.telemetry import Telemetry, make_telemetry, record

# Write slot status.
W_EMPTY = 0
W_DOWN = 1  # propagating head -> tail
W_UP = 2  # ack propagating tail -> head

# Read slot status.
R_EMPTY = 0
R_AT_NODE = 1  # request in flight to the chosen node
R_TAIL = 2  # dirty: version query in flight to the tail
R_REPLY = 3  # reply in flight to the client


@dataclasses.dataclass(frozen=True)
class BatchedCraqConfig:
    """Static parameters: N chains x L nodes, KV keys per chain."""

    num_chains: int = 4
    chain_len: int = 3  # L >= 2 (head + tail at minimum)
    num_keys: int = 16  # KV: key space per chain
    window: int = 16  # W: in-flight writes per chain
    writes_per_tick: int = 2  # K
    reads_per_tick: int = 2  # R
    read_window: int = 16  # RW: outstanding reads per chain
    lat_min: int = 1
    lat_max: int = 3
    # Unified in-graph fault injection (tpu/faults.py), TCP semantics
    # (the chain runs on reliable links): drops become retransmission
    # penalties on hop latencies, and a CHAIN-NODE partition (side bits
    # over the L nodes) buffers hops INTO cut nodes until the heal tick
    # — writes queue behind the cut and drain afterwards, so the
    # pending-set conservation invariants hold throughout.
    # FaultPlan.none() is a structural no-op.
    faults: FaultPlan = FaultPlan.none()
    # In-graph workload engine (tpu/workload.py): shapes per-chain
    # write admission; a read/write mix routes the read share to the
    # apportioned-read ring (needs reads_per_tick > 0). Completions
    # are tail applies. WorkloadPlan.none() = saturation.
    workload: WorkloadPlan = WorkloadPlan.none()
    # Kernel-layer dispatch policy (ops/registry.py): the chain
    # propagate/ack plane (tick steps 1-2) routes through
    # ops.registry.dispatch. Partitioned plans ride the kernel too —
    # the plan's side bits enter the plane as statics and hops into cut
    # nodes defer to the heal tick IN-KERNEL (ops/craq.py).
    kernels: KernelPolicy = KernelPolicy()

    def __post_init__(self):
        assert self.num_chains >= 1
        assert self.chain_len >= 2
        if self.faults.has_crash:
            # The per-write pending-set bitmask packs node bits into
            # int32; crashes only drive MIDDLE nodes, so L >= 3 is
            # where the axis does anything (L == 2 no-ops harmlessly).
            assert self.chain_len <= 31, (
                "chain crash axis packs the visited set in int32 bits"
            )
        assert self.num_keys >= 1
        assert self.window >= 2 * self.writes_per_tick
        if self.reads_per_tick:
            assert self.read_window >= 2 * self.reads_per_tick
        assert 1 <= self.lat_min <= self.lat_max
        self.faults.validate(axis=self.chain_len)
        self.workload.validate(reads_supported=self.reads_per_tick > 0)
        self.kernels.validate()


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class BatchedCraqState:
    """Shapes: [N] chains, [N, W] write ring, [N, L, KV] node state,
    [N, RW] read ring."""

    # Write ring.
    w_status: jnp.ndarray  # [N, W] W_EMPTY | W_DOWN | W_UP
    w_key: jnp.ndarray  # [N, W]
    w_version: jnp.ndarray  # [N, W] per-chain monotone version
    w_node: jnp.ndarray  # [N, W] node the write/ack is heading to
    w_arrival: jnp.ndarray  # [N, W] tick it arrives there (INF = idle)
    w_issue: jnp.ndarray  # [N, W] issue tick (write latency)

    # Per-node CRAQ state.
    node_dirty: jnp.ndarray  # [N, L, KV] pending-write count per key
    node_version: jnp.ndarray  # [N, L, KV] applied version (-1 = none)
    next_version: jnp.ndarray  # [N] per-chain version counter

    # Read ring.
    r_status: jnp.ndarray  # [N, RW]
    r_key: jnp.ndarray  # [N, RW]
    r_node: jnp.ndarray  # [N, RW] chosen node
    r_arrival: jnp.ndarray  # [N, RW] next event tick (INF = idle)
    r_issue: jnp.ndarray  # [N, RW]
    r_floor: jnp.ndarray  # [N, RW] tail version at issue (lin floor)
    r_version: jnp.ndarray  # [N, RW] served version

    # Chain-node crash axis (all zero-sized unless faults.has_crash).
    node_alive: jnp.ndarray  # [N, L] node liveness (head/tail pinned) | [N, 0]
    node_suspect: jnp.ndarray  # [N, L] revived-but-not-caught-up | [N, 0]
    w_visited: jnp.ndarray  # [N, W] bitmask of nodes in the pending set | [N, 0]
    crashes: jnp.ndarray  # [] node deaths (cumulative) | [0]
    resyncs: jnp.ndarray  # [] suspect nodes caught up (cumulative) | [0]

    # Stats.
    writes_done: jnp.ndarray  # [] writes applied at the tail (replied)
    write_lat_sum: jnp.ndarray  # []
    write_lat_hist: jnp.ndarray  # [LAT_BINS]
    reads_done: jnp.ndarray  # []
    reads_clean: jnp.ndarray  # [] served locally at the chosen node
    reads_dirty: jnp.ndarray  # [] forwarded to the tail
    read_lat_sum: jnp.ndarray  # []
    read_lat_hist: jnp.ndarray  # [LAT_BINS]
    workload: WorkloadState  # shaping state (tpu/workload.py)
    read_lin_violations: jnp.ndarray  # [] reads below their floor
    telemetry: Telemetry  # device-side metric ring (tpu/telemetry.py)


def init_state(cfg: BatchedCraqConfig) -> BatchedCraqState:
    N, L, KV = cfg.num_chains, cfg.chain_len, cfg.num_keys
    W, RW = cfg.window, cfg.read_window
    return BatchedCraqState(
        w_status=jnp.zeros((N, W), DTYPE_STATUS),
        w_key=jnp.zeros((N, W), jnp.int32),
        w_version=jnp.full((N, W), -1, jnp.int32),
        w_node=jnp.zeros((N, W), jnp.int32),
        w_arrival=jnp.full((N, W), INF, jnp.int32),
        w_issue=jnp.full((N, W), INF, jnp.int32),
        node_dirty=jnp.zeros((N, L, KV), jnp.int32),
        node_version=jnp.full((N, L, KV), -1, jnp.int32),
        next_version=jnp.zeros((N,), jnp.int32),
        r_status=jnp.zeros((N, RW), DTYPE_STATUS),
        r_key=jnp.zeros((N, RW), jnp.int32),
        r_node=jnp.zeros((N, RW), jnp.int32),
        r_arrival=jnp.full((N, RW), INF, jnp.int32),
        r_issue=jnp.full((N, RW), INF, jnp.int32),
        r_floor=jnp.full((N, RW), -1, jnp.int32),
        r_version=jnp.full((N, RW), -1, jnp.int32),
        node_alive=jnp.ones(
            (N, L if cfg.faults.has_crash else 0), bool
        ),
        node_suspect=jnp.zeros(
            (N, L if cfg.faults.has_crash else 0), bool
        ),
        w_visited=jnp.zeros(
            (N, W if cfg.faults.has_crash else 0), jnp.int32
        ),
        crashes=jnp.zeros(() if cfg.faults.has_crash else (0,), jnp.int32),
        resyncs=jnp.zeros(() if cfg.faults.has_crash else (0,), jnp.int32),
        writes_done=jnp.zeros((), jnp.int32),
        write_lat_sum=jnp.zeros((), jnp.int32),
        write_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        reads_done=jnp.zeros((), jnp.int32),
        reads_clean=jnp.zeros((), jnp.int32),
        reads_dirty=jnp.zeros((), jnp.int32),
        read_lat_sum=jnp.zeros((), jnp.int32),
        read_lat_hist=jnp.zeros((LAT_BINS,), jnp.int32),
        workload=workload_mod.make_state(
            cfg.workload, cfg.num_chains, cfg.faults
        ),
        read_lin_violations=jnp.zeros((), jnp.int32),
        telemetry=make_telemetry(),
    )


def tick(
    cfg: BatchedCraqConfig,
    state: BatchedCraqState,
    t: jnp.ndarray,
    key: jnp.ndarray,
) -> BatchedCraqState:
    """One tick: writes/acks advance one hop, the tail applies+replies,
    reads route (clean local / dirty via tail) and complete."""
    N, L, KV = cfg.num_chains, cfg.chain_len, cfg.num_keys
    W, RW = cfg.window, cfg.read_window
    tail = L - 1
    kw, kr = jax.random.split(key)
    bits_w = jax.random.bits(kw, (N, W))  # [0:8) hop lat, [8:24) new key
    bits_r = jax.random.bits(kr, (N, RW))  # [0:8) hop lat, [8:20) key,
    #                                        [20:28) node choice
    hop_lat_w = bit_latency(bits_w, 0, cfg.lat_min, cfg.lat_max)
    hop_lat_r = bit_latency(bits_r, 0, cfg.lat_min, cfg.lat_max)

    # Unified fault injection (tpu/faults.py), TCP semantics: drops are
    # retransmission penalties on hop latencies; `_hop(arr, node)` below
    # buffers hops whose TARGET node sits on the cut side of an active
    # partition until the heal tick. Under a none plan `_hop` is the
    # identity and the latencies are untouched (structural no-op).
    fp = cfg.faults
    wl = cfg.workload
    wls = state.workload
    frates = faults_mod.traced_rates(fp, wls)
    if fp.active:
        kf = faults_mod.fault_key(key)
        hop_lat_w = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 0), (N, W), hop_lat_w, rates=frates
        )
        hop_lat_r = faults_mod.tcp_latency(
            fp, jax.random.fold_in(kf, 1), (N, RW), hop_lat_r, rates=frates
        )
    if fp.has_partition:
        _side = faults_mod.partition_sides(fp)
        _cut_live = faults_mod.partition_active(fp, t)

        def _hop(arrival, node):
            cut = _cut_live & (_side[node] == 1)
            return faults_mod.defer_to_heal(fp, arrival, cut)
    else:

        def _hop(arrival, node):
            return arrival

    w_status = state.w_status
    w_node = state.w_node
    w_arrival = state.w_arrival
    node_dirty_flat = state.node_dirty.reshape(N, L * KV)
    node_version_flat = state.node_version.reshape(N, L * KV)
    writes_done = state.writes_done
    write_lat_sum = state.write_lat_sum
    write_lat_hist = state.write_lat_hist

    # ---- 0.5 Chain-node crash axis (module docstring; structurally
    # absent unless faults.has_crash — a none plan draws no keys and
    # adds no ops). Order matters: crash/revive draws land FIRST, then
    # in-flight hops re-stitch/buffer against the updated liveness, and
    # only then does the chain plane process arrivals — so every
    # processing event this tick happens at an alive node, and the
    # visited bookkeeping below mirrors the plane's arrival predicates
    # exactly.
    crash_on = fp.has_crash
    node_alive = state.node_alive
    node_suspect = state.node_suspect
    w_visited = state.w_visited
    crashes = state.crashes
    resyncs = state.resyncs
    if crash_on:
        # (a) Crash/revive (middle nodes only; head + tail pinned —
        # chain-membership replacement is the coordination service's
        # job, outside this model). Newly dead nodes become SUSPECT:
        # they will miss writes until they catch up after reviving.
        kc = faults_mod.fault_key(key, salt=7)
        alive2 = faults_mod.crash_step(fp, kc, node_alive, rates=frates)
        pin = (jnp.arange(L, dtype=jnp.int32) == 0) | (
            jnp.arange(L, dtype=jnp.int32) == tail
        )
        alive2 = alive2 | pin[None, :]
        crashes = crashes + jnp.sum(node_alive & ~alive2)
        node_suspect = node_suspect | (node_alive & ~alive2)
        node_alive = alive2

        def _at_node(arr2d, node):
            return jnp.take_along_axis(
                arr2d, jnp.clip(node, 0, tail), axis=1
            )

        # (b) DOWN re-stitch: a write heading to a dead node redirects
        # to the next alive node toward the tail (tail pinned alive, so
        # the static unrolled scan terminates). Arrival unchanged — the
        # hop is already in flight; the stitch redirects it.
        down = w_status == W_DOWN
        for _ in range(L - 1):
            w_node = jnp.where(
                down
                & ~_at_node(node_alive, w_node)
                & (w_node < tail),
                w_node + 1,
                w_node,
            )
        # (c) UP targeting: acks only visit nodes whose pending set the
        # write actually joined (its visited bit) — bit 0 is always set
        # (every write processes at the alive head), so the scan
        # terminates at the retire point.
        up = w_status == W_UP
        for _ in range(L - 1):
            bit = (
                jnp.right_shift(w_visited, jnp.clip(w_node, 0, tail))
                & 1
            ) == 1
            w_node = jnp.where(up & ~bit & (w_node > 0), w_node - 1, w_node)
        # (d) Buffered re-propagation: an ack whose (visited) target is
        # currently dead waits — its arrival slides one tick at a time
        # and the ack delivers the moment the node revives. Conservation
        # is why acks wait instead of skipping: the dead node's dirty
        # count still holds this write.
        stall = up & (w_arrival == t) & ~_at_node(node_alive, w_node)
        w_arrival = jnp.where(stall, t + 1, w_arrival)
        # (e) Visited bookkeeping, mirroring the plane's arrival
        # predicates exactly (post-redirect, post-stall): DOWN mid-chain
        # processing joins the pending set; UP processing leaves it.
        proc_down_mid = down & (w_arrival == t) & (w_node < tail)
        proc_up = up & (w_arrival == t)
        one_hot = jnp.left_shift(
            jnp.int32(1), jnp.clip(w_node, 0, tail)
        )
        w_visited = jnp.where(
            proc_down_mid, w_visited | one_hot, w_visited
        )
        w_visited = jnp.where(proc_up, w_visited & ~one_hot, w_visited)

    # ---- 1+2. The chain propagate/ack plane (ChainNode._process_write_
    # batch + ChainNode._handle_ack): DOWN writes join pending sets and
    # forward, the tail applies + replies + starts the ack, UP acks
    # apply locally and propagate, the head ack retires the ring slot.
    # One registry plane (ops/craq.py): the kernel recasts the four
    # scatters as one-hot accumulations in one VMEM-resident pass, and
    # partitioned plans ride it too — the plan's side bits enter as
    # statics and hops into cut nodes defer to the heal tick in-kernel
    # (the same `faults.defer_to_heal` rewrite `_hop` applies to the
    # read/issue sites below).
    (
        w_status,
        w_node,
        w_arrival,
        node_dirty_flat,
        node_version_flat,
        at_tail,
        wlat,
    ) = ops_registry.dispatch(
        "craq_chain",
        cfg,
        w_status,
        state.w_key,
        state.w_version,
        w_node,
        w_arrival,
        state.w_issue,
        node_dirty_flat,
        node_version_flat,
        hop_lat_w,
        t,
        tail=tail,
        num_keys=KV,
        side=tuple(fp.partition) if fp.has_partition else (),
        partition_start=fp.partition_start,
        partition_heal=fp.partition_heal,
    )
    writes_done = writes_done + jnp.sum(at_tail)
    write_lat_sum = write_lat_sum + jnp.sum(wlat)
    wbins = jnp.clip(wlat, 0, LAT_BINS - 1)
    write_lat_hist = write_lat_hist + jax.ops.segment_sum(
        at_tail.astype(jnp.int32).ravel(), wbins.ravel(), LAT_BINS
    )

    # ---- 2.5 Suspect resync (crash axis): a revived node stays
    # suspect while ANY in-flight write has bypassed it (passed its
    # position without joining its pending set). Once the last such
    # write drains, the node bulk-catches-up by copying the tail's
    # versions — the buffered re-propagation of everything it missed —
    # and serves clean reads again as if it never crashed.
    if crash_on:
        l_iota = jnp.arange(L, dtype=jnp.int32)[None, None, :]
        bit_l = (
            (w_visited[:, :, None] >> l_iota) & 1
        ) == 1  # [N, W, L]
        up3 = (w_status == W_UP)[:, :, None]
        down3 = (w_status == W_DOWN)[:, :, None]
        passed = up3 | (down3 & (w_node[:, :, None] > l_iota))
        in_flight3 = (w_status != W_EMPTY)[:, :, None]
        missed = jnp.any(in_flight3 & passed & ~bit_l, axis=1)  # [N, L]
        clear = node_suspect & node_alive & ~missed
        nv = node_version_flat.reshape(N, L, KV)
        nv = jnp.where(
            clear[:, :, None],
            jnp.maximum(nv, nv[:, tail : tail + 1, :]),
            nv,
        )
        node_version_flat = nv.reshape(N, L * KV)
        resyncs = resyncs + jnp.sum(clear)
        node_suspect = node_suspect & ~clear

    # ---- 3. Reads (apportioned queries, ChainNode._process_read_batch).
    r_status = state.r_status
    r_key = state.r_key
    r_node = state.r_node
    r_arrival = state.r_arrival
    r_issue = state.r_issue
    r_floor = state.r_floor
    r_version = state.r_version
    reads_done = state.reads_done
    reads_clean = state.reads_clean
    reads_dirty = state.reads_dirty
    read_lat_sum = state.read_lat_sum
    read_lat_hist = state.read_lat_hist
    read_lin_violations = state.read_lin_violations
    # Workload arrivals (tpu/workload.py): drawn before the read block
    # so the read share of the mix feeds the apportioned-read ring.
    if wl.active:
        wl_writes, wl_reads, wls = workload_mod.begin(wl, wls, key, t, N)
    # Gate on the ring EXISTING (not on the issue rate): tests inject
    # reads by hand with reads_per_tick == 0 and still need routing.
    if cfg.read_window:
        if crash_on:
            # Crash re-stitch for reads: an in-flight read heading to a
            # dead node redirects to the next alive node toward the
            # tail (apportioned queries go to ANY node; the chain
            # membership just shrank). Suspect/dead serving is handled
            # at the clean check below.
            pending_at = r_status == R_AT_NODE
            for _ in range(L - 1):
                alive_at = jnp.take_along_axis(
                    node_alive, jnp.clip(r_node, 0, tail), axis=1
                )
                r_node = jnp.where(
                    pending_at & ~alive_at & (r_node < tail),
                    r_node + 1,
                    r_node,
                )
        # (a) Completions free their slots (and check the lin floor).
        done = (r_status == R_REPLY) & (r_arrival <= t)
        rlat = jnp.where(done, t - r_issue, 0)
        reads_done = reads_done + jnp.sum(done)
        read_lat_sum = read_lat_sum + jnp.sum(rlat)
        rbins = jnp.clip(rlat, 0, LAT_BINS - 1)
        read_lat_hist = read_lat_hist + jax.ops.segment_sum(
            done.astype(jnp.int32).ravel(), rbins.ravel(), LAT_BINS
        )
        read_lin_violations = read_lin_violations + jnp.sum(
            done & (r_version < r_floor)
        )
        r_status = jnp.where(done, R_EMPTY, r_status)
        r_arrival = jnp.where(done, INF, r_arrival)

        # (b) Node arrivals: one gather answers "is the key dirty here".
        at_node = (r_status == R_AT_NODE) & (r_arrival == t)
        rslot = r_node * KV + r_key
        dirty_here = (
            jnp.take_along_axis(node_dirty_flat, rslot, axis=1) > 0
        )
        if crash_on:
            # A suspect node may have been bypassed by a write it never
            # saw — a stale key would look clean there. Until the
            # resync clears it, every read it receives takes the dirty
            # path to the tail (always correct).
            unsafe = jnp.take_along_axis(
                node_suspect | ~node_alive,
                jnp.clip(r_node, 0, tail),
                axis=1,
            )
            dirty_here = dirty_here | unsafe
        clean = at_node & ~dirty_here
        dirty = at_node & dirty_here
        local_ver = jnp.take_along_axis(node_version_flat, rslot, axis=1)
        r_version = jnp.where(clean, local_ver, r_version)
        r_status = jnp.where(clean, R_REPLY, r_status)
        r_status = jnp.where(dirty, R_TAIL, r_status)
        # Dirty queries hop to the tail; clean replies hop back over the
        # serving node's client link.
        r_arrival = jnp.where(
            at_node,
            _hop(t + hop_lat_r, jnp.where(dirty, tail, r_node)),
            r_arrival,
        )
        reads_clean = reads_clean + jnp.sum(clean)
        reads_dirty = reads_dirty + jnp.sum(dirty)

        # (c) Tail arrivals (CraqTailRead): serve the tail's version.
        at_tail_r = (r_status == R_TAIL) & (r_arrival == t)
        tslot = tail * KV + r_key
        tail_ver = jnp.take_along_axis(node_version_flat, tslot, axis=1)
        r_version = jnp.where(at_tail_r, tail_ver, r_version)
        r_status = jnp.where(at_tail_r, R_REPLY, r_status)
        r_arrival = jnp.where(
            at_tail_r, _hop(t + hop_lat_r, tail), r_arrival
        )

        # (d) Issue new reads at a PRNG node/key; the floor is the tail's
        # committed version for the key right now.
        empty_r = r_status == R_EMPTY
        rank_r = jnp.cumsum(empty_r.astype(jnp.int32), axis=1)
        if wl.has_reads:
            issue_r = empty_r & (rank_r <= wl_reads[:, None])
        else:
            issue_r = empty_r & (rank_r <= cfg.reads_per_tick)
        new_key_r = (
            ((bits_r >> 8) & jnp.uint32(0xFFF)).astype(jnp.int32) % KV
        )
        new_node = (
            ((bits_r >> 20) & jnp.uint32(0xFF)).astype(jnp.int32) % L
        )
        floor_slot = tail * KV + new_key_r
        floor_now = jnp.take_along_axis(
            node_version_flat, floor_slot, axis=1
        )
        r_key = jnp.where(issue_r, new_key_r, r_key)
        r_node = jnp.where(issue_r, new_node, r_node)
        r_floor = jnp.where(issue_r, floor_now, r_floor)
        r_issue = jnp.where(issue_r, t, r_issue)
        r_version = jnp.where(issue_r, -1, r_version)
        r_status = jnp.where(issue_r, R_AT_NODE, r_status)
        r_arrival = jnp.where(
            issue_r, _hop(t + hop_lat_r, new_node), r_arrival
        )

    # ---- 4. New writes into empty ring slots (CraqClient.write -> head).
    # Ring slots the chain plane retired THIS tick (head ack arrived):
    # captured before new issues overwrite the status — the span
    # sampler's "executed" stage below.
    w_retired = (state.w_status != W_EMPTY) & (w_status == W_EMPTY)
    empty_w = w_status == W_EMPTY
    rank_w = jnp.cumsum(empty_w.astype(jnp.int32), axis=1)
    # Workload admission (tpu/workload.py): under a shaping plan the
    # static writes_per_tick knob becomes the per-chain cap.
    if wl.active:
        adm = workload_mod.admission(wl, wls, wl_writes)
        issue_w = empty_w & (rank_w <= adm[:, None])
    else:
        issue_w = empty_w & (rank_w <= cfg.writes_per_tick)
    count_w = jnp.sum(issue_w, axis=1)  # [N]
    if wl.active:
        wls = workload_mod.finish(
            wl, wls, t, wl_writes, count_w, jnp.sum(at_tail, axis=1)
        )
    new_key_w = (
        ((bits_w >> 8) & jnp.uint32(0xFFFF)).astype(jnp.int32) % KV
    )
    if crash_on:
        # Fresh writes start with an empty pending set (bit 0 joins on
        # arrival at the always-alive head).
        w_visited = jnp.where(issue_w, 0, w_visited)
    new_version = state.next_version[:, None] + rank_w - 1
    w_key = jnp.where(issue_w, new_key_w, state.w_key)
    w_version = jnp.where(issue_w, new_version, state.w_version)
    w_node = jnp.where(issue_w, 0, w_node)
    w_status = jnp.where(issue_w, W_DOWN, w_status)
    w_arrival = jnp.where(issue_w, _hop(t + hop_lat_w, 0), w_arrival)
    w_issue = jnp.where(issue_w, t, state.w_issue)
    next_version = state.next_version + count_w

    # Telemetry: writes entering the head are "proposals", tail applies
    # are "commits", completed reads "executes"; dirty reads forwarded
    # to the tail are the chain's extra message plane.
    tel = record(
        state.telemetry,
        proposals=jnp.sum(issue_w),
        phase2_msgs=reads_dirty - state.reads_dirty,
        commits=writes_done - state.writes_done,
        executes=reads_done - state.reads_done,
        queue_depth=jnp.sum(w_status != W_EMPTY),
        queue_capacity=N * W,
        lat_hist_delta=write_lat_hist - state.write_lat_hist,
    )

    # Span sampler (telemetry.record_spans — the generic plumbing, PR
    # 10): write lifecycles through the chain, recorded from the masks
    # this tick already computed. Mapping: group = chain, ring pos =
    # write slot, slot id = the per-chain monotone VERSION (stable for
    # a write's whole life; a retire + re-issue in one tick carries the
    # new version via new_slot_ids). Stages: proposed = issued at the
    # client, phase2_voted = committed = the tail apply (the chain's
    # commit point), executed = the head ack retiring the slot (>= one
    # hop later, so executed > committed always). No phase-1 plane on a
    # chain. Structurally OFF at spans=0 (the serve loop sizes the
    # reservoir), like every other backend.
    if telemetry_mod.span_slots(tel):
        tel = telemetry_mod.record_spans(
            tel,
            t=t,
            is_new=issue_w,
            slot_ids=state.w_version,
            new_slot_ids=w_version,
            phase1_mark=jnp.zeros((N,), bool),
            voted=at_tail,
            newly_chosen=at_tail,
            retire_mask=w_retired,
        )

    return BatchedCraqState(
        w_status=w_status,
        w_key=w_key,
        w_version=w_version,
        w_node=w_node,
        w_arrival=w_arrival,
        w_issue=w_issue,
        node_dirty=node_dirty_flat.reshape(N, L, KV),
        node_version=node_version_flat.reshape(N, L, KV),
        next_version=next_version,
        r_status=r_status,
        r_key=r_key,
        r_node=r_node,
        r_arrival=r_arrival,
        r_issue=r_issue,
        r_floor=r_floor,
        r_version=r_version,
        node_alive=node_alive,
        node_suspect=node_suspect,
        w_visited=w_visited,
        crashes=crashes,
        resyncs=resyncs,
        writes_done=writes_done,
        write_lat_sum=write_lat_sum,
        write_lat_hist=write_lat_hist,
        reads_done=reads_done,
        reads_clean=reads_clean,
        reads_dirty=reads_dirty,
        read_lat_sum=read_lat_sum,
        read_lat_hist=read_lat_hist,
        workload=wls,
        read_lin_violations=read_lin_violations,
        telemetry=tel,
    )


@functools.partial(jax.jit, static_argnums=(0, 3), donate_argnums=(1,))
def run_ticks(
    cfg: BatchedCraqConfig,
    state: BatchedCraqState,
    t0: jnp.ndarray,
    num_ticks: int,
    key: jnp.ndarray,
) -> Tuple[BatchedCraqState, jnp.ndarray]:
    def step(carry, i):
        st, t = carry
        st = tick(cfg, st, t, jax.random.fold_in(key, i))
        return (st, t + 1), ()

    (state, t), _ = jax.lax.scan(
        step, (state, t0), jnp.arange(num_ticks), unroll=1
    )
    return state, t


def check_invariants(
    cfg: BatchedCraqConfig, state: BatchedCraqState, t
) -> dict:
    """Device-side safety checks."""
    L, KV = cfg.chain_len, cfg.num_keys
    down = state.w_status == W_DOWN
    up = state.w_status == W_UP
    if cfg.faults.has_crash:
        # Under the crash axis the pending set is EXACTLY the write's
        # visited bitmask (bypassed nodes never joined; acked nodes
        # left), so conservation is the popcount over in-flight writes.
        pc = jax.lax.population_count(
            state.w_visited.astype(jnp.uint32)
        ).astype(jnp.int32)
        expected_dirty = jnp.sum(
            jnp.where(state.w_status != W_EMPTY, pc, 0)
        )
    else:
        # Pending-set conservation: a DOWN write heading to node m is
        # pending at nodes 0..m-1 (m entries); an UP ack heading to
        # node m has been acked at m+1..L-2, so the write is still
        # pending at 0..m (m+1).
        expected_dirty = jnp.sum(
            jnp.where(down, state.w_node, 0)
            + jnp.where(up, state.w_node + 1, 0)
        )
    dirty_conserved = jnp.sum(state.node_dirty) == expected_dirty
    dirty_nonneg = jnp.all(state.node_dirty >= 0)
    # Crash-axis books (trivially true when the axis is off — empty
    # arrays): head and tail stay pinned alive, suspicion only ever
    # covers middle nodes, and acks only target pending-set members.
    if cfg.faults.has_crash:
        chain_alive_ok = (
            jnp.all(state.node_alive[:, 0])
            & jnp.all(state.node_alive[:, L - 1])
            & jnp.all(~state.node_suspect[:, 0])
            & jnp.all(~state.node_suspect[:, L - 1])
        )
        # Every in-flight ack still holds its head membership (bit 0
        # joins at the alive head and only the retiring arrival at node
        # 0 clears it). The plane may leave an ack transiently pointed
        # at a bypassed node between ticks — the next tick's pre-plane
        # redirect fixes the target before any processing — so the
        # invariant pins the stable bit, not the in-motion target.
        ack_target_ok = jnp.all(~up | ((state.w_visited & 1) == 1))
    else:
        chain_alive_ok = jnp.asarray(True)
        ack_target_ok = jnp.asarray(True)
    # A node never applies ahead of the tail (acks follow the tail apply).
    tail_ver = state.node_version[:, L - 1 : L, :]
    node_behind_tail = jnp.all(state.node_version <= tail_ver)
    # Versions applied anywhere were actually issued.
    ver_issued = jnp.all(
        state.node_version < state.next_version[:, None, None]
    )
    # Write accounting: every issued write is in flight or done.
    in_flight = jnp.sum(state.w_status != W_EMPTY)
    # writes_done counts tail applies; UP acks are done-but-in-flight.
    acked_in_flight = jnp.sum(up)
    write_books = (
        jnp.sum(state.next_version) == state.writes_done + in_flight
        - acked_in_flight
    )
    # Apportioned reads stay linearizable.
    read_lin_ok = state.read_lin_violations == 0
    read_books = state.reads_clean + state.reads_dirty >= state.reads_done
    return {
        "dirty_conserved": dirty_conserved,
        "workload_ok": workload_mod.invariants_ok(
            cfg.workload, state.workload
        ),
        "dirty_nonneg": dirty_nonneg,
        "node_behind_tail": node_behind_tail,
        "ver_issued": ver_issued,
        "write_books": write_books,
        "read_lin_ok": read_lin_ok,
        "read_books": read_books,
        "chain_alive_ok": chain_alive_ok,
        "ack_target_ok": ack_target_ok,
    }


def stats(cfg: BatchedCraqConfig, state: BatchedCraqState, t) -> dict:
    """Host-side summary (mirrors TpuSimTransport.stats)."""
    writes = int(state.writes_done)
    reads = int(state.reads_done)
    whist = jax.device_get(state.write_lat_hist)
    rhist = jax.device_get(state.read_lat_hist)

    def p50(hist, n):
        if not n:
            return -1
        return int((hist.cumsum() >= max(1, (n + 1) // 2)).argmax())

    clean = int(state.reads_clean)
    dirty = int(state.reads_dirty)
    return {
        "ticks": int(t),
        "writes_done": writes,
        "write_latency_p50_ticks": p50(whist, writes),
        "write_latency_mean_ticks": (
            float(state.write_lat_sum) / writes if writes else -1.0
        ),
        "reads_done": reads,
        "read_latency_p50_ticks": p50(rhist, reads),
        "reads_clean": clean,
        "reads_dirty": dirty,
        "clean_fraction": clean / max(1, clean + dirty),
        "read_lin_violations": int(state.read_lin_violations),
    }


def analysis_config(
    faults: FaultPlan = FaultPlan.none(),
    workload: WorkloadPlan = WorkloadPlan.none(),
) -> BatchedCraqConfig:
    """The backend's canonical SMALL config: shared by the
    static-analysis trace layer (``frankenpaxos_tpu.analysis`` jits and
    inspects ``tick``/``run_ticks`` at exactly this shape) and the
    simulation-testing registry (``harness/simtest.py``). Big enough to
    exercise every protocol plane, small enough to trace and compile in
    well under a second."""
    return BatchedCraqConfig(
        num_chains=4, chain_len=3, num_keys=8, window=8,
        writes_per_tick=2, reads_per_tick=2, read_window=8,
        workload=workload,
        faults=faults,
    )
