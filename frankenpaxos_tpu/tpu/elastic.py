"""Elastic capacity: live resize of pre-allocated role planes.

Compartmentalized MultiPaxos (PAPERS: arxiv 2012.15762) is a thesis
about scaling each bottleneck role INDEPENDENTLY — more proxy leaders
when the broadcast fan-out saturates, more batchers when admission
does. Until now the repo's control plane could only react to duress by
clamping admission (``monitoring/slo.py``): the fleet degraded by
refusing work. This module gives it the other lever: role planes are
allocated at a PADDED static capacity and gated behind traced
active-count scalars, so the SLO engine grows or shrinks the live
role count between serve chunks with ZERO recompiles — the same
plan-static/state-traced split the fault, workload, and lifecycle
engines already prove (``tpu/faults.py``, ``tpu/workload.py``,
``tpu/lifecycle.py`` — the PR 11 membership masks are the direct
ancestor of the masks here).

Design contract (the subsystem trio's, verbatim):

  * :class:`ElasticPlan` is FROZEN + hashable and lives inside the
    static backend config: it fixes the STRUCTURE — which roles are
    elastic, their padded capacities (== the static axis sizes) and
    floors. Changing the plan recompiles; nothing else does.
  * :class:`ElasticState` carries the traced knobs: per-role ``active``
    and ``target`` counts, a resize generation, and cumulative
    scale-up/scale-down event counters. Host verbs set ``target``;
    the tick applies it via :func:`apply`.
  * ``ElasticPlan.none()`` is the STRUCTURAL no-op: every state leaf
    is zero-sized, every helper returns the caller's static default
    (a Python int), and the compiled program is bit-identical to the
    pre-elastic one (the ``elastic-noop`` analysis rule pins this).

Resize semantics (the drain-then-deactivate ladder):

  * SCALE-UP is immediate: ``apply`` raises ``active`` to ``target``
    the tick after the verb lands — the padded plane is already
    allocated, activation is a mask flip.
  * SCALE-DOWN is two-phase. The moment ``target`` drops below
    ``active``, ROUTING of new work switches to the first
    ``min(active, target)`` instances (:func:`routing_count`), so the
    deactivating tail stops receiving; ``active`` itself only drops
    once the backend's per-role drain predicate reports the tail idle
    (:func:`apply`'s ``drained`` argument). No in-flight work is lost:
    the exactly-once session books and ``workload_ok`` conservation
    reconcile across every resize, and a SIGKILL between the verb and
    the switch resumes mid-drain bit-exactly (the counts are ordinary
    checkpointed state leaves).

Role semantics are the BACKEND's: the flagship declares ``groups``
(arrivals re-route over the first N proposer lanes via
:func:`route_lanes`'s traced modulus); compartmentalized declares
``proxies``/``unbatchers`` (slot-ownership moduli — handoff is
immediate, ownership is recomputed per tick), ``batchers`` (admission
split; residual partial fill migrates to batcher 0 at the switch), and
``replicas`` (READ-serving capacity only — every replica keeps
executing writes, so reactivation needs no catch-up transfer).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "ElasticPlan",
    "ElasticState",
    "make_state",
    "apply",
    "set_target",
    "count",
    "target_count",
    "routing_count",
    "route_lanes",
    "counts",
    "invariants_ok",
    "summary",
]


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ElasticPlan:
    """Which role planes resize, and between what bounds. Frozen +
    hashable: lives inside the static backend config (a ``jax.jit``
    static argument). Each entry is ``(role, capacity, floor)`` —
    ``capacity`` is the PADDED static axis size the backend allocates
    (validated to match), ``floor`` the minimum active count the
    control plane may shrink to."""

    roles: Tuple[Tuple[str, int, int], ...] = ()

    # -- structural predicates (trace-time Python values) ----------------

    @property
    def active(self) -> bool:
        """Any role declared (the tick helpers run iff this holds)."""
        return len(self.roles) > 0

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(name for name, _, _ in self.roles)

    def declares(self, name: str) -> bool:
        return any(n == name for n, _, _ in self.roles)

    def index(self, name: str) -> int:
        for i, (n, _, _) in enumerate(self.roles):
            if n == name:
                return i
        raise KeyError(f"role {name!r} not in elastic plan {self.names}")

    def capacity_of(self, name: str) -> int:
        return self.roles[self.index(name)][1]

    def floor_of(self, name: str) -> int:
        return self.roles[self.index(name)][2]

    @classmethod
    def none(cls) -> "ElasticPlan":
        """The structural no-op plan: zero-sized state leaves, every
        helper returns its static default, and XLA emits the exact
        pre-elastic program."""
        return cls()

    def validate(self, capacities: Dict[str, int]) -> None:
        """Config-time validation; the backend passes the static axis
        size of every role it SUPPORTS — a plan naming an unknown role
        or mismatching the allocated capacity is a config bug."""
        seen = set()
        for name, cap, floor in self.roles:
            assert name not in seen, f"duplicate elastic role {name!r}"
            seen.add(name)
            assert name in capacities, (
                f"elastic role {name!r} not supported by this backend "
                f"(supported: {sorted(capacities)})"
            )
            assert cap == capacities[name], (
                f"elastic role {name!r}: plan capacity {cap} != the "
                f"backend's allocated axis {capacities[name]} — the "
                "padded plane IS the static axis"
            )
            assert 1 <= floor <= cap, (
                f"elastic role {name!r}: need 1 <= floor <= capacity, "
                f"got floor={floor} capacity={cap}"
            )

    # -- serialization (autoscaler context / reproducers) ----------------

    def to_dict(self) -> dict:
        return {"roles": [list(r) for r in self.roles]}

    @classmethod
    def from_dict(cls, d: dict) -> "ElasticPlan":
        return cls(
            roles=tuple(tuple(r) for r in d.get("roles", ()))
        )


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ElasticState:
    """Device-resident resize state, carried in the backend's
    ``*State`` dataclass. Every leaf is ZERO-SIZED under
    ``ElasticPlan.none()`` — the none state is structurally empty and
    keeps the scan carry bit-identical to the pre-elastic program.
    All leaves int32 (the dtype policy's accumulator width), so
    ``widen_state`` passes them through untouched."""

    active: jnp.ndarray  # [R] int32 live instance count per role
    target: jnp.ndarray  # [R] int32 verb-set desired count
    gen: jnp.ndarray  # [] int32 applied-resize generation | [0]
    scale_ups: jnp.ndarray  # [] int32 cumulative role grow events | [0]
    scale_downs: jnp.ndarray  # [] int32 cumulative shrink events | [0]


def make_state(
    plan: ElasticPlan, initial: Optional[Dict[str, int]] = None
) -> ElasticState:
    """The per-role count state. Roles start at their padded CAPACITY
    (a resize-free run is bit-identical in OUTPUT to the static
    program — the 3-seed identity tests pin that) unless ``initial``
    names a smaller starting count."""
    R = len(plan.roles)
    scalar = () if plan.active else (0,)
    start = [
        (initial or {}).get(name, cap) for name, cap, _ in plan.roles
    ]
    for (name, cap, floor), s in zip(plan.roles, start):
        assert floor <= s <= cap, (
            f"elastic role {name!r}: initial count {s} outside "
            f"[{floor}, {cap}]"
        )
    # Distinct buffers for active/target (donated carries must never
    # alias two leaves to one buffer).
    return ElasticState(
        active=jnp.asarray(start, jnp.int32).reshape(R),
        target=jnp.asarray(list(start), jnp.int32).reshape(R),
        gen=jnp.zeros(scalar, jnp.int32),
        scale_ups=jnp.zeros(scalar, jnp.int32),
        scale_downs=jnp.zeros(scalar, jnp.int32),
    )


# ---------------------------------------------------------------------------
# Tick-side helpers. Call order inside a backend's tick:
#     es, n_resized = apply(plan, es, {role: drained_bool, ...})
#     n_act = routing_count(plan, es, "proxies", P)   # traced | int P
#     ... route new work by `% n_act` / `iota < n_act` masks ...
# and `n_resized` feeds telemetry.record(resizes=...).
# ---------------------------------------------------------------------------


def count(
    plan: ElasticPlan, es: ElasticState, name: str, default: int
) -> "jnp.ndarray | int":
    """The role's live instance count: a traced [] int32 when the plan
    declares the role, the static Python ``default`` otherwise — so an
    undeclared role compiles to the exact pre-elastic program."""
    if not plan.declares(name):
        return default
    return es.active[plan.index(name)]


def target_count(
    plan: ElasticPlan, es: ElasticState, name: str, default: int
) -> "jnp.ndarray | int":
    """The role's verb-set target count (static default when the role
    is undeclared)."""
    if not plan.declares(name):
        return default
    return es.target[plan.index(name)]


def routing_count(
    plan: ElasticPlan, es: ElasticState, name: str, default: int
) -> "jnp.ndarray | int":
    """The count NEW work routes over: ``min(active, target)``. During
    a drain (target < active) the deactivating tail stops receiving
    immediately while ``active`` holds until the tail is idle — the
    first half of drain-then-deactivate."""
    if not plan.declares(name):
        return default
    i = plan.index(name)
    return jnp.minimum(es.active[i], es.target[i])


def route_lanes(per_lane: jnp.ndarray, n_act) -> jnp.ndarray:
    """Re-route a per-lane count vector onto the first ``n_act``
    lanes: lane ``i``'s entries land on lane ``i % n_act`` (identity
    for live lanes). Conservation is exact — the sum is untouched, so
    workload offered/admitted books reconcile across resizes. Cheap:
    one [L] traced modulus + one segment-sum."""
    L = per_lane.shape[0]
    iota = jnp.arange(L, dtype=jnp.int32)
    dst = iota % jnp.maximum(jnp.asarray(n_act, jnp.int32), 1)
    return jax.ops.segment_sum(per_lane, dst, num_segments=L)


def apply(
    plan: ElasticPlan,
    es: ElasticState,
    drained: Optional[Dict[str, jnp.ndarray]] = None,
):
    """One tick of resize application. ``drained`` maps role name ->
    traced bool: True when every DEACTIVATING instance of that role is
    idle (roles absent from the dict — immediate-handoff roles whose
    ownership is recomputed per tick — default True). Scale-ups apply
    unconditionally; scale-downs wait for the drain predicate.
    Returns ``(es', n_resized)`` where ``n_resized`` counts roles
    whose active count changed this tick (feeds the telemetry ring's
    ``resizes`` column); 0 (a Python int) under the none plan."""
    if not plan.active:
        return es, 0
    dr = jnp.stack(
        [
            jnp.asarray((drained or {}).get(name, True), bool).reshape(())
            for name, _, _ in plan.roles
        ]
    )  # [R]
    grow = es.target > es.active
    shrink = (es.target < es.active) & dr
    new_active = jnp.where(grow | shrink, es.target, es.active)
    changed = new_active != es.active
    n_resized = jnp.sum(changed.astype(jnp.int32))
    return (
        dataclasses.replace(
            es,
            active=new_active,
            gen=es.gen + (n_resized > 0).astype(jnp.int32),
            scale_ups=es.scale_ups
            + jnp.sum((grow & changed).astype(jnp.int32)),
            scale_downs=es.scale_downs
            + jnp.sum((shrink & changed).astype(jnp.int32)),
        ),
        n_resized,
    )


# ---------------------------------------------------------------------------
# Host verbs (serve-loop control plane; dataclasses.replace of traced
# leaves — never a recompile).
# ---------------------------------------------------------------------------


def set_target(
    plan: ElasticPlan, es: ElasticState, name: str, n: int
) -> ElasticState:
    """The resize verb: set the role's target count, clipped to
    ``[floor, capacity]``. The tick applies it (immediately for a
    grow, after the drain for a shrink)."""
    i = plan.index(name)
    _, cap, floor = plan.roles[i]
    n = int(min(max(int(n), floor), cap))
    return dataclasses.replace(
        es, target=es.target.at[i].set(jnp.int32(n))
    )


# ---------------------------------------------------------------------------
# Invariants + host views
# ---------------------------------------------------------------------------


def invariants_ok(plan: ElasticPlan, es: ElasticState) -> jnp.ndarray:
    """Traced bool: every count within its declared bounds and the
    event books non-negative — ANDed into the backend's
    ``check_invariants`` as ``elastic_ok``."""
    if not plan.active:
        return jnp.bool_(True)
    caps = jnp.asarray([c for _, c, _ in plan.roles], jnp.int32)
    floors = jnp.asarray([f for _, _, f in plan.roles], jnp.int32)
    ok = jnp.all(
        (es.active >= floors)
        & (es.active <= caps)
        & (es.target >= floors)
        & (es.target <= caps)
    )
    return (
        ok
        & (es.gen >= 0)
        & (es.scale_ups >= 0)
        & (es.scale_downs >= 0)
    )


def counts(plan: ElasticPlan, es: ElasticState) -> Dict[str, int]:
    """Host view of the live role counts — the shape
    ``ops.costmodel.capacity`` takes as its ``role_counts``
    feedforward term (one device_get of the [R] vector)."""
    if not plan.active:
        return {}
    act = jax.device_get(es.active)
    return {name: int(act[i]) for i, (name, _, _) in enumerate(plan.roles)}


def summary(plan: ElasticPlan, es: ElasticState) -> dict:
    """Host roll-up for reports / capacity-event markers."""
    if not plan.active:
        return {"active": False}
    es = jax.device_get(es)
    return {
        "active": True,
        "roles": {
            name: {
                "active": int(es.active[i]),
                "target": int(es.target[i]),
                "capacity": cap,
                "floor": floor,
            }
            for i, (name, cap, floor) in enumerate(plan.roles)
        },
        "generation": int(es.gen),
        "scale_ups": int(es.scale_ups),
        "scale_downs": int(es.scale_downs),
    }
