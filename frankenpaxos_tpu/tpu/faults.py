"""Unified in-graph fault injection for the batched backends.

The reference framework's core robustness capability is ``FakeTransport``
simulation testing (FakeTransport.scala): deterministic message drops,
duplication, reordering, partitions, and crash schedules driven against
property checks. This module is that capability rebuilt TPU-first: a
single :class:`FaultPlan` accepted by EVERY ``tpu/*_batched.py`` config,
applied INSIDE each compiled tick via the shared helpers below, so
thousands of randomized fault schedules run as vmapped/multi-seed
compiled scans (``harness/simtest.py`` is the driver).

Fault taxonomy (the failure modes Compartmentalized MultiPaxos, arxiv
2012.15762, and Bipartisan Paxos, arxiv 2003.00331, decompose their
protocols around):

  * ``drop_rate`` — extra per-message Bernoulli loss, on top of any
    backend-native ``drop_rate`` knob.
  * ``dup_rate`` — an eager duplicate transmission: with this
    probability a second copy of the message races the first, arriving
    at least one tick later. Receivers in the arrival-tick encoding
    dedup identical copies (``jnp.minimum`` / re-vote idempotence), so
    the observable effects are at-least-once delivery and perturbed
    arrival order — exactly what duplication exercises in FakeTransport.
  * ``jitter`` — extra uniform [0, jitter] per-message delivery delay
    (reordering pressure: messages sent earlier can arrive later).
  * ``crash_rate`` / ``revive_rate`` — per-process per-tick crash and
    revival probabilities. Backends with native liveness machinery
    (multipaxos leader candidates + heartbeat elections, fasterpaxos
    servers, vanillamencius servers, epaxos GC replicas) merge these
    into it via :func:`effective_process_rates`; backends without it
    gate their proposer/aggregator with :func:`crash_step`.
  * ``partition`` / ``partition_start`` / ``partition_heal`` — a static
    side assignment over the backend's replica axis (side 0 holds the
    coordinator — leader / proxy / client / aggregator). While the
    partition is active (``partition_start <= t < partition_heal``),
    messages crossing sides are cut. ``partition_heal = -1`` never
    heals. Two delivery semantics, chosen per message plane:

      - UDP planes (backends with resend timers): crossing messages are
        DROPPED (:func:`message_faults` ``link_up``); the protocol's own
        retries restore liveness after the heal tick.
      - TCP planes (chain/pipeline backends without resend timers):
        crossing messages are BUFFERED until the heal tick
        (:func:`defer_to_heal`) — the transport retransmits until the
        link returns, so conservation invariants survive the cut.

Determinism contract: all fault randomness derives from the tick's own
threefry key via ``jax.random.fold_in`` with the :data:`FAULT_SALT`
stream id and per-plane salts, using the repo's bit-packing idiom
(``common.bit_delivered`` / ``bit_latency``). ``FaultPlan.none()``
(the default on every config) takes the trace-time no-op path in every
helper: no extra PRNG sweeps, no extra ops, so XLA emits the exact
pre-fault program and runs stay bit-identical (pinned by
``tests/test_faults.py`` golden values).

``FaultPlan`` is a frozen, hashable dataclass living inside the static
backend config (a ``jax.jit`` static argument): rates are compile-time
constants (the ``bit_delivered`` 1/256 quantization applies), and a new
plan compiles a new program. The schedule-randomization axis that must
be cheap — the SEED — is free: one compile serves any number of seeds,
vmapped (``harness.simtest.run_many_seeds``).

TRACED rates (``traced=True``): the Bernoulli knobs — drop, dup,
crash, revive — move from compile-time constants to STATE-SIDE float32
scalars (``tpu/workload.py`` ``WorkloadState.fault_rates``, initialized
from this plan's fields by :func:`make_rates`), so a fault-RATE grid
sweeps one compiled program via :func:`frankenpaxos_tpu.tpu.workload
.set_fault_rates` / vmap instead of recompiling per rate (the
``trace-workload-retrace`` analysis rule pins that the jit cache does
not grow across the sweep). A traced plan is structurally ACTIVE on
every Bernoulli plane regardless of its static field values (the
program must be able to realize any swept rate); the structural knobs —
jitter, partition, drop_penalty — stay compile-time static. The
helpers take the traced scalars via their ``rates=`` argument and
assert it is supplied, so a backend that threads a traced plan without
its rate state fails loudly at trace time, never silently at rate 0.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from frankenpaxos_tpu.tpu.common import INF, bit_delivered, bit_latency

# Stream id folded into a tick's key before drawing any fault
# randomness; per-plane keys fold a small plane salt on top. Distinct
# from every fold_in constant the backends use for their own sweeps.
FAULT_SALT = 0x5EED

_RATE_FIELDS = ("drop_rate", "dup_rate", "crash_rate", "revive_rate")

# Slot order of the traced-rate vector (make_rates / workload state).
R_DROP, R_DUP, R_CRASH, R_REVIVE = 0, 1, 2, 3


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """One fault schedule. Frozen + hashable: lives inside the static
    backend config. See the module docstring for field semantics."""

    drop_rate: float = 0.0  # extra per-message Bernoulli loss
    dup_rate: float = 0.0  # P(an eager duplicate copy is also sent)
    jitter: int = 0  # extra uniform [0, jitter] delivery delay (ticks)
    crash_rate: float = 0.0  # per-process per-tick crash probability
    revive_rate: float = 0.0  # per-crashed-process revival probability
    # Side assignment over the backend's replica axis (0 = coordinator
    # side, 1 = the cut side); empty = no partition.
    partition: Tuple[int, ...] = ()
    partition_start: int = 0  # first tick the cut is active
    partition_heal: int = -1  # scheduled heal tick (-1 = never heals)
    # TCP-plane retransmission penalty per dropped transmission (ticks);
    # only read by :func:`tcp_latency`.
    drop_penalty: int = 6
    # Bernoulli rates become TRACED state-side scalars (module
    # docstring): the static rate fields above seed the state vector
    # (:func:`make_rates`) and every Bernoulli plane is structurally
    # active so a rate sweep replays one compiled program.
    traced: bool = False

    # -- structural predicates (all trace-time Python bools) ------------

    @property
    def has_partition(self) -> bool:
        return len(self.partition) > 0 and any(self.partition)

    @property
    def has_crash(self) -> bool:
        return self.traced or self.crash_rate > 0.0

    @property
    def dup_active(self) -> bool:
        return self.traced or self.dup_rate > 0.0

    @property
    def messages_active(self) -> bool:
        """Any message-plane knob engaged (the send-path helpers draw
        PRNG sweeps iff this holds)."""
        return (
            self.traced
            or self.drop_rate > 0.0
            or self.dup_rate > 0.0
            or self.jitter > 0
            or self.has_partition
        )

    @property
    def active(self) -> bool:
        return self.messages_active or self.has_crash

    @classmethod
    def none(cls) -> "FaultPlan":
        """The structural no-op plan: every helper compiles to the
        identity and XLA emits the exact pre-fault program."""
        return cls()

    def validate(self, axis: Optional[int] = None) -> None:
        """Config-time validation; every backend's ``__post_init__``
        calls this with its partition (replica) axis size."""
        for name in _RATE_FIELDS:
            rate = getattr(self, name)
            assert 0.0 <= rate < 1.0, f"faults.{name}={rate} not in [0, 1)"
        assert self.jitter >= 0, f"faults.jitter={self.jitter} < 0"
        assert self.drop_penalty >= 1
        if self.has_crash:
            assert self.revive_rate > 0.0 or self.crash_rate < 1.0
        if self.partition:
            assert all(s in (0, 1) for s in self.partition), (
                f"faults.partition side bits must be 0/1: {self.partition}"
            )
            if axis is not None:
                assert len(self.partition) == axis, (
                    f"faults.partition has {len(self.partition)} side "
                    f"bits; this backend's replica axis is {axis}"
                )
            assert self.partition_start >= 0
            assert (
                self.partition_heal < 0
                or self.partition_heal > self.partition_start
            ), "partition_heal must follow partition_start (or be -1)"

    # -- serialization (the shrinking reproducer format) ----------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["partition"] = list(self.partition)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        d = dict(d)
        d["partition"] = tuple(d.get("partition", ()))
        return cls(**d)


# ---------------------------------------------------------------------------
# PRNG plumbing
# ---------------------------------------------------------------------------


def fault_key(key: jnp.ndarray, salt: int = 0) -> jnp.ndarray:
    """The per-tick fault stream: fold the shared FAULT_SALT plus a
    per-plane salt into the tick key. Callers must only derive this when
    the plan is active so the inactive path touches no keys at all."""
    return jax.random.fold_in(key, FAULT_SALT + salt)


# ---------------------------------------------------------------------------
# Traced rates (the state-side sweep axis of ``traced=True`` plans)
# ---------------------------------------------------------------------------


def make_rates(plan: FaultPlan) -> jnp.ndarray:
    """The plan's Bernoulli rates as the traced state vector
    ``[drop, dup, crash, revive]`` (float32) — zero-sized for untraced
    plans so the default state carries nothing. Lives inside each
    backend's ``WorkloadState`` (``tpu/workload.py make_state``)."""
    if not plan.traced:
        return jnp.zeros((0,), jnp.float32)
    return jnp.asarray(
        [plan.drop_rate, plan.dup_rate, plan.crash_rate,
         plan.revive_rate],
        jnp.float32,
    )


def traced_rates(plan: FaultPlan, workload_state):
    """The ``rates=`` argument every fault helper wants: the workload
    state's traced ``[4]`` rate vector for a traced plan, None
    otherwise (static plans read their compile-time fields)."""
    if not plan.traced:
        return None
    rates = workload_state.fault_rates
    assert rates.shape == (4,), (
        "FaultPlan(traced=True) but the state carries no fault_rates "
        "vector — init_state must build its WorkloadState with "
        "workload.make_state(plan, lanes, cfg.faults)"
    )
    return rates


def _rate(plan: FaultPlan, rates, slot: int, static_value: float):
    """One Bernoulli rate: the traced scalar for traced plans (rates
    is then mandatory), the static field otherwise."""
    if not plan.traced:
        return static_value
    assert rates is not None, (
        "FaultPlan(traced=True) requires the traced rates= argument "
        "(faults.traced_rates(plan, state.workload))"
    )
    return rates[slot]


# ---------------------------------------------------------------------------
# Partition masks
# ---------------------------------------------------------------------------


def partition_active(plan: FaultPlan, t) -> jnp.ndarray:
    """Traced scalar bool: the cut is live at tick ``t``."""
    if not plan.has_partition:
        return jnp.asarray(False)
    active = t >= jnp.int32(plan.partition_start)
    if plan.partition_heal >= 0:
        active = active & (t < jnp.int32(plan.partition_heal))
    return active


def partition_sides(plan: FaultPlan) -> jnp.ndarray:
    """The plan's side-bit vector as a device constant (for backends
    that gather per-message target sides, e.g. chain hops)."""
    return jnp.array(plan.partition, jnp.int32)


def partition_row(plan: FaultPlan, t, n: int) -> jnp.ndarray:
    """[n] bool over the replica axis: True = the link between replica
    ``i`` and the coordinator (side 0) is usable at tick ``t``. All-True
    when no partition is configured or outside the active window."""
    if not plan.has_partition:
        return jnp.ones((n,), bool)
    side = partition_sides(plan)
    assert side.shape == (n,), (side.shape, n)
    return ~partition_active(plan, t) | (side == 0)


def defer_to_heal(plan: FaultPlan, arrival: jnp.ndarray, cut) -> jnp.ndarray:
    """TCP partition semantics: arrivals flagged ``cut`` (sent across an
    active cut) are buffered until the heal tick — delivered at
    ``max(arrival, heal)``, or never (INF) if the partition never
    heals. Identity when no partition is configured."""
    if not plan.has_partition:
        return arrival
    heal = jnp.int32(
        plan.partition_heal if plan.partition_heal >= 0 else INF
    )
    return jnp.where(cut, jnp.maximum(arrival, heal), arrival)


def defer_to_heal_offset(
    plan: FaultPlan, off: jnp.ndarray, cut, t
) -> jnp.ndarray:
    """:func:`defer_to_heal` for OFFSET clocks (tpu/common.py
    DTYPE_CLOCK): arrivals flagged ``cut`` are pushed to the heal tick
    expressed as an offset from ``t`` — ``max(off, heal - t)``, clamped
    into the int16 clock range, or the INF16 sentinel if the partition
    never heals. Identity when no partition is configured. All
    arithmetic is weakly typed so the widen_state() int32 reference
    path replays bit-identically."""
    from frankenpaxos_tpu.tpu.common import INF16

    if not plan.has_partition:
        return off
    if plan.partition_heal < 0:
        heal_off = INF16
    else:
        heal_off = jnp.minimum(
            jnp.int32(plan.partition_heal) - t, INF16
        ).astype(off.dtype)
    return jnp.where(cut, jnp.maximum(off, heal_off), off)


# ---------------------------------------------------------------------------
# Message planes
# ---------------------------------------------------------------------------


def message_faults(
    plan: FaultPlan,
    key: jnp.ndarray,
    shape: Tuple[int, ...],
    lat: jnp.ndarray,
    link_up=None,
    rates=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """UDP-plane fault transform for one batch of messages sent this
    tick with base latency ``lat``: returns ``(delivered, lat')``.

    ``delivered`` folds the extra Bernoulli drop, the eager-duplicate
    second chance (a message survives if EITHER copy does), and the
    partition cut (``link_up`` broadcast over ``shape``); ``lat'`` is
    the earliest surviving copy's latency (base + jitter, or base + 1 +
    jitter for a duplicate that outlived a dropped original). Callers
    AND ``delivered`` into their existing send masks and use ``lat'``
    in place of ``lat`` — the exact idiom the backends already use for
    their native ``drop_rate``.

    Inactive plan: ``(all-True, lat)`` with no PRNG draw (the
    structural no-op path). Traced plan: drop/dup read the traced
    ``rates`` vector instead of the static fields."""
    if not plan.messages_active:
        return jnp.ones(shape, bool), lat
    drop = _rate(plan, rates, R_DROP, plan.drop_rate)
    dup = _rate(plan, rates, R_DUP, plan.dup_rate)
    bits = jax.random.bits(key, shape)
    # [0:8) drop of the original, [8:16) duplicate decision,
    # [16:24) jitter of the original.
    delivered = bit_delivered(bits, 0, drop)
    lat_eff = (
        lat + bit_latency(bits, 16, 0, plan.jitter) if plan.jitter else lat
    )
    if plan.dup_active:
        bits2 = jax.random.bits(jax.random.fold_in(key, 1), shape)
        dup_sent = ~bit_delivered(bits, 8, dup)
        dup_delivered = dup_sent & bit_delivered(bits2, 0, drop)
        dup_lat = lat + 1 + (
            bit_latency(bits2, 8, 0, plan.jitter) if plan.jitter else 0
        )
        lat_eff = jnp.where(
            delivered & dup_delivered,
            jnp.minimum(lat_eff, dup_lat),
            jnp.where(delivered, lat_eff, dup_lat),
        )
        delivered = delivered | dup_delivered
    if link_up is not None and plan.has_partition:
        delivered = delivered & link_up
    return delivered, lat_eff


def tcp_latency(
    plan: FaultPlan, key: jnp.ndarray, shape: Tuple[int, ...], lat,
    rates=None,
) -> jnp.ndarray:
    """TCP-plane fault transform of a latency array: drops become
    retransmission penalties (``drop_penalty`` extra ticks — the link
    redelivers, it never loses), jitter adds its uniform delay, and
    duplicates are absorbed by the transport. Conservation invariants
    (chain pending-sets, cut pipelines) survive because every message
    still arrives exactly once. Identity when neither knob is set;
    traced plans read the traced drop rate from ``rates``."""
    if not plan.traced and plan.drop_rate <= 0.0 and plan.jitter <= 0:
        return lat
    bits = jax.random.bits(key, shape)
    out = lat
    if plan.jitter:
        out = out + bit_latency(bits, 8, 0, plan.jitter)
    if plan.traced or plan.drop_rate > 0.0:
        lost = ~bit_delivered(
            bits, 0, _rate(plan, rates, R_DROP, plan.drop_rate)
        )
        out = out + jnp.where(lost, jnp.int32(plan.drop_penalty), 0)
    return out


# ---------------------------------------------------------------------------
# Process crashes
# ---------------------------------------------------------------------------


def crash_step(
    plan: FaultPlan, key: jnp.ndarray, alive: jnp.ndarray, rates=None
):
    """One tick of the crash/revive process over a liveness mask (any
    shape): alive processes die with ``crash_rate``, dead ones revive
    with ``revive_rate``. Identity (no PRNG) when crash is off; traced
    plans read both rates from ``rates``."""
    if not plan.has_crash:
        return alive
    bits = jax.random.bits(key, alive.shape)
    dies = ~bit_delivered(
        bits, 0, _rate(plan, rates, R_CRASH, plan.crash_rate)
    )
    revives = ~bit_delivered(
        bits, 8, _rate(plan, rates, R_REVIVE, plan.revive_rate)
    )
    return jnp.where(alive, ~dies, revives)


def effective_process_rates(
    plan: FaultPlan, fail_rate: float, revive_rate: float, rates=None
):
    """Merge the plan's crash knobs into a backend's native
    fail/revive machinery: independent death sources compose as
    ``1 - (1-a)(1-b)``; the plan's revive rate (when set) overrides the
    native one. Returns the native rates unchanged when crash is off,
    so the merged machinery stays bit-identical under a none plan.

    Traced plans return TRACED scalars (the same composition over the
    state-side rates; the revive override becomes a traced select) —
    ``bit_delivered`` accepts either, but trace-time Python branches
    must gate on ``plan.has_crash`` / the native rate, never compare
    the returned values."""
    if not plan.has_crash:
        return fail_rate, revive_rate
    if plan.traced:
        crash = _rate(plan, rates, R_CRASH, plan.crash_rate)
        revive = _rate(plan, rates, R_REVIVE, plan.revive_rate)
        eff_fail = 1.0 - (1.0 - fail_rate) * (1.0 - crash)
        eff_revive = jnp.where(revive > 0.0, revive, revive_rate).astype(
            jnp.float32
        )
        return eff_fail, eff_revive
    eff_fail = 1.0 - (1.0 - fail_rate) * (1.0 - plan.crash_rate)
    eff_revive = plan.revive_rate if plan.revive_rate > 0.0 else revive_rate
    return eff_fail, eff_revive
